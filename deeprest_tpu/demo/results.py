"""Read-side access to the precomputed demo results artifact.

The counterpart of the reference demo's DataLoader (reference:
web-demo/dataloader.py:30-49,82-167), over the JSON artifact written by
precompute.py.  Re-anchoring and scale factors are already baked in at
precompute time, so reads are plain lookups; this class adds the option
wiring (which multipliers/compositions exist for a shape — reference:
dataloader.py:34-49) and panel assembly for the UI.
"""

from __future__ import annotations

import gzip
import json

from deeprest_tpu.demo.precompute import dataset_name


class ResultsStore:
    def __init__(self, results: dict):
        self.results = results
        self.meta = results["meta"]
        self.datasets = results["datasets"]

    @classmethod
    def load(cls, path: str) -> "ResultsStore":
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            return cls(json.loads(f.read().decode()))

    # -- option wiring (reference: dataloader.py:34-49) --------------------

    def options_shape(self) -> list[dict]:
        labels = {"waves": "Two peak hours per day", "flat": "Roughly stable"}
        return [{"label": labels.get(s, s), "value": s}
                for s in self.meta["shapes"]]

    def options_multiplier(self, shape: str) -> list[int]:
        if shape != "waves":
            return [1]
        return list(self.meta["multipliers"])

    def options_composition(self, shape: str) -> dict[str, list[list[float]]]:
        out = {"seen": self.meta["compositions"]["seen"]}
        if shape == "waves":
            out["unseen"] = self.meta["compositions"]["unseen"]
        return out

    # -- panel assembly ----------------------------------------------------

    def dataset(self, shape: str, multiplier: int, group: str,
                index: int) -> dict:
        key = dataset_name(shape, multiplier, group, index)
        if key not in self.datasets:
            raise KeyError(f"no dataset {key!r}; available: "
                           f"{sorted(self.datasets)[:5]}...")
        return self.datasets[key]

    def panel(self, shape: str, multiplier: int, group: str,
              index: int) -> dict:
        """Everything one UI render needs: traffic program, per-component
        scale factors (the bar charts) and utilization series (the line
        charts), in method order groundtruth/resrc/comp/ours."""
        ds = self.dataset(shape, multiplier, group, index)
        methods = self.meta["methods"]
        components = {}
        for comp, resources in ds["components"].items():
            rec = {}
            for resource, r in resources.items():
                rec[resource] = {
                    "scale": [r["scale"].get(m, 0.0) for m in methods],
                    "series": {m: r[m] for m in methods if m in r},
                    "band": {"lo": r["ours_lo"], "hi": r["ours_hi"]},
                    "observed": r["observed"],
                }
            components[comp] = rec
        return {
            "key": dataset_name(shape, multiplier, group, index),
            "composition": ds["composition"],
            "calls": ds["calls"],
            "methods": methods,
            "components": components,
        }
