"""Demo CLI.

    python -m deeprest_tpu.demo precompute --raw=corpus.jsonl \\
        --ckpt-dir=ckpt --out=results.json.gz [--ticks=120] [--quick]
    python -m deeprest_tpu.demo serve --results=results.json.gz --port=2021
"""

from __future__ import annotations

import argparse
import sys


def cmd_precompute(args) -> int:
    from deeprest_tpu.cli import _load_buckets
    from deeprest_tpu.data.featurize import featurize_buckets
    from deeprest_tpu.demo.precompute import (
        DemoConfig, precompute_results, save_results,
    )
    from deeprest_tpu.serve.predictor import Predictor

    predictor = Predictor.from_checkpoint(args.ckpt_dir)
    space = predictor.space()
    if space is None:
        sys.exit("error: checkpoint has no feature space; re-train first")
    buckets = _load_buckets(args.raw)
    observed = featurize_buckets(buckets, space=space)

    kwargs = {"ticks": args.ticks}
    if args.quick:   # small grid for smoke runs
        kwargs.update(shapes=("waves",), multipliers=(1, 3))
    cfg = DemoConfig(**kwargs)
    results = precompute_results(predictor, observed, buckets, cfg)
    path = save_results(results, args.out)
    print(f"wrote {len(results['datasets'])} datasets -> {path}")
    return 0


def cmd_serve(args) -> int:
    from deeprest_tpu.demo.results import ResultsStore
    from deeprest_tpu.demo.server import DemoServer

    store = ResultsStore.load(args.results)
    server = DemoServer(store, host=args.host, port=args.port)
    host, port = server.address
    print(f"demo at http://{host}:{port}/ "
          f"({len(store.datasets)} datasets)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="deeprest_tpu.demo")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("precompute", help="build the results artifact")
    p.add_argument("--raw", required=True, help="observed training corpus")
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--out", default="results.json.gz")
    p.add_argument("--ticks", type=int, default=120)
    p.add_argument("--quick", action="store_true")
    p.set_defaults(fn=cmd_precompute)

    p = sub.add_parser("serve", help="serve the demo UI")
    p.add_argument("--results", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2021)
    p.set_defaults(fn=cmd_serve)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
