"""The demo web server: stdlib http.server + a vanilla-JS/SVG page.

Serves the reference demo's four-panel capability (reference:
web-demo/app.py:51-122 — controls, traffic, scaling-factor bars,
utilization series) without the Dash/Plotly dependency stack: one static
HTML page (assets/index.html) calling two JSON endpoints:

    GET /api/meta                              → options for the controls
    GET /api/panel?shape=&multiplier=&group=&index=  → one render's data
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from deeprest_tpu.demo.results import ResultsStore

_ASSETS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "assets")


def make_handler(store: ResultsStore):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet by default
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, payload, code: int = 200) -> None:
            self._send(code, json.dumps(payload).encode(), "application/json")

        def do_GET(self):  # noqa: N802 (stdlib API)
            url = urlparse(self.path)
            try:
                if url.path in ("/", "/index.html"):
                    with open(os.path.join(_ASSETS, "index.html"), "rb") as f:
                        self._send(200, f.read(), "text/html; charset=utf-8")
                elif url.path == "/api/meta":
                    self._json({
                        "shapes": store.options_shape(),
                        "multipliers": {
                            s["value"]: store.options_multiplier(s["value"])
                            for s in store.options_shape()
                        },
                        "compositions": {
                            s["value"]: store.options_composition(s["value"])
                            for s in store.options_shape()
                        },
                        "apis": store.meta["apis"],
                        "components": store.meta["components"],
                        "resources": store.meta["resources"],
                        "methods": store.meta["methods"],
                    })
                elif url.path == "/api/panel":
                    q = parse_qs(url.query)
                    panel = store.panel(
                        q["shape"][0], int(q["multiplier"][0]),
                        q["group"][0], int(q["index"][0]),
                    )
                    self._json(panel)
                else:
                    self._json({"error": f"no route {url.path}"}, 404)
            except (KeyError, IndexError, ValueError) as exc:
                self._json({"error": str(exc)}, 400)

    return Handler


class DemoServer:
    """Threaded server wrapper usable both as a CLI and from tests."""

    def __init__(self, store: ResultsStore, host: str = "127.0.0.1",
                 port: int = 2021):
        self.httpd = ThreadingHTTPServer((host, port), make_handler(store))
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def start_background(self) -> "DemoServer":
        # graftlint: disable=TH001 -- lifecycle handle: start_background/stop run on the owning driver thread only, never in a request handler
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
