"""Build the demo's precomputed what-if results artifact.

The reference demo reads an opaque ``results.pkl`` whose generator is not
in the repo (reference: web-demo/dataloader.py:30-32, missing large blob);
this module is that missing piece, built on the framework's own stack: for
every (load shape × multiplier × API composition) dataset it

1. draws a hypothetical traffic program (users curve × composition),
2. generates the matching span-tree workload with the simulated app and
   runs the stateful resource model over it → **ground truth** (the
   reference needed a real cluster run per dataset),
3. estimates utilization from the synthesized traffic features with the
   trained quantile model → **ours**,
4. co-computes both reference baselines on the same program: history-
   replay (resource-aware) and invocation-count linear scaling
   (component-aware),
5. records peak scaling factors vs the observed baseline period, with the
   memory/usage re-anchoring rule (reference: web-demo/
   dataloader.py:143-156) applied at precompute time.

Output schema (JSON, gzip when the path ends in .gz):

    {"meta": {...}, "datasets": {key: {"calls": {api: [T]},
      "components": {comp: {resource: record}}}}}

record = {"groundtruth": [T], "ours": [T], "ours_lo": [T], "ours_hi": [T],
          "resrc": [T], "comp": [T], "observed": [T_obs],
          "scale": {method: float}}
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import zlib
from typing import Sequence

import numpy as np

from deeprest_tpu.data.featurize import FeaturizedData, count_invocations
from deeprest_tpu.data.synthesize import TraceSynthesizer
from deeprest_tpu.serve.predictor import Predictor
from deeprest_tpu.workload.scenarios import (
    SEEN_COMPOSITIONS, UNSEEN_COMPOSITIONS, LoadScenario,
)
from deeprest_tpu.workload.telemetry import ResourceModel, count_ops
from deeprest_tpu.workload.topology import API_ENDPOINTS, AppParams, SocialNetworkApp

# Resources whose absolute level depends on history the traffic cannot see
# (cumulative disk usage, resident memory): re-anchored before scaling.
REANCHOR_RESOURCES = ("memory", "usage")


@dataclasses.dataclass(frozen=True)
class DemoConfig:
    """Dataset grid; the default mirrors the reference demo's options
    (reference: web-demo/dataloader.py:6-28,34-49 — waves 1-3x over seen +
    unseen compositions, flat 1x over seen)."""

    shapes: tuple[str, ...] = ("waves", "flat")
    multipliers: tuple[int, ...] = (1, 2, 3)
    seen: tuple[tuple[float, float, float], ...] = SEEN_COMPOSITIONS[:9]
    unseen: tuple[tuple[float, float, float], ...] = UNSEEN_COMPOSITIONS
    ticks: int = 120
    components: tuple[str, ...] = ()   # () = every checkpointed component
    base_users: float = 100.0
    calls_per_user: float = 2.0
    seed: int = 7

    def dataset_keys(self) -> list[tuple[str, int, str, int]]:
        """(shape, multiplier, seen|unseen, index) — flat is 1x/seen-only,
        matching the reference's option wiring."""
        keys = []
        for shape in self.shapes:
            mults = self.multipliers if shape == "waves" else (1,)
            groups = ("seen", "unseen") if shape == "waves" else ("seen",)
            for mult in mults:
                for group in groups:
                    comps = self.seen if group == "seen" else self.unseen
                    keys.extend((shape, mult, group, i)
                                for i in range(len(comps)))
        return keys

    def composition(self, group: str, index: int) -> tuple[float, float, float]:
        return (self.seen if group == "seen" else self.unseen)[index]


def dataset_name(shape: str, mult: int, group: str, index: int) -> str:
    return f"{shape}-{mult}x-{group}-{index}"


def _api_root_labels(app: SocialNetworkApp) -> dict[str, str]:
    """Root span label of each API's primary trace (probabilistic side
    traces like the media upload surface as their own endpoints)."""
    rng = np.random.default_rng(0)
    return {api: app.generate(api, rng)[0].label for api in API_ENDPOINTS}


def _traffic_program(cfg: DemoConfig, shape: str, mult: int,
                     comp: tuple[float, float, float],
                     rng: np.random.Generator) -> np.ndarray:
    """[ticks, num_apis] integer calls: users curve × fixed composition."""
    scn = LoadScenario(
        name="demo", flat=shape != "waves",
        base_users=cfg.base_users * mult,
        peak_range=(1.4 * cfg.base_users * mult, 2.0 * cfg.base_users * mult),
        seed=cfg.seed,
    )
    users = scn.users_curve(cfg.ticks)
    compose, read_home, read_user = comp
    rest = max(0.0, 1.0 - compose - read_home - read_user)
    w = np.asarray([compose, read_home, read_user,
                    rest * 0.2, rest * 0.3, rest * 0.5])
    rates = users[:, None] * cfg.calls_per_user * (w / w.sum())
    return rng.poisson(rates).astype(np.int64)


def _reanchor(series: np.ndarray, anchor: float) -> np.ndarray:
    return series - series[0] + anchor


def precompute_results(
    predictor: Predictor,
    observed: FeaturizedData,
    observed_buckets: Sequence,
    config: DemoConfig | None = None,
    app_params: AppParams | None = None,
) -> dict:
    """The full results artifact.

    Args:
      predictor: restored from a checkpoint trained on ``observed``.
      observed: the featurized training corpus (baseline period).
      observed_buckets: its raw buckets (fits the trace synthesizer).
      config: dataset grid.
      app_params: branch probabilities for the ground-truth workload.
    """
    cfg = config or DemoConfig()
    app = SocialNetworkApp(app_params)
    roots = _api_root_labels(app)
    p_media = (app_params or AppParams()).p_media

    space = predictor.space()
    if space is None:
        raise ValueError("checkpoint predates sidecar feature spaces; "
                         "re-train to use the demo")
    synth = TraceSynthesizer(space).fit(list(observed_buckets))

    metric_names = predictor.metric_names
    if list(observed.metric_names) != list(metric_names):
        # anchors/baselines/scales index observed columns by checkpoint
        # metric order — a mismatched corpus would silently mix columns
        raise ValueError(
            "observed corpus metric set/order does not match the "
            "checkpoint's; pass the corpus the model was trained on"
        )
    components = sorted({m.rsplit("_", 1)[0] for m in metric_names})
    if cfg.components:
        components = [c for c in components if c in cfg.components]
    med = predictor.model.median_index()

    observed_targets = observed.targets()         # [T_obs, E] raw scale
    obs_peak = np.max(np.abs(observed_targets), axis=0)      # [E]
    obs_last = observed_targets[-1]                          # [E] anchors
    w = predictor.window_size

    datasets = {}
    for shape, mult, group, index in cfg.dataset_keys():
        comp3 = cfg.composition(group, index)
        key = dataset_name(shape, mult, group, index)
        # process-stable per-dataset stream (hash() is salted per process)
        rng = np.random.default_rng(cfg.seed + zlib.crc32(key.encode()))
        calls = _traffic_program(cfg, shape, mult, comp3, rng)

        # -- ground truth: simulated workload + resource model ------------
        per_tick_traces = []
        for t in range(cfg.ticks):
            traces = []
            for a, api in enumerate(API_ENDPOINTS):
                for _ in range(int(calls[t, a])):
                    traces.extend(app.generate(api, rng))
            per_tick_traces.append(traces)
        model = ResourceModel(seed=cfg.seed)
        comp_set = sorted({c for m in metric_names
                           for c in [m.rsplit("_", 1)[0]]})
        truth = {m: np.zeros(cfg.ticks, np.float32) for m in metric_names}
        for t, traces in enumerate(per_tick_traces):
            ops, writes = count_ops(traces)
            for sample in model.step_counts(ops, writes, components=comp_set):
                if sample.key in truth:
                    truth[sample.key][t] = sample.value

        # -- ours: synthesized features → quantile model ------------------
        mix_series = []
        for t in range(cfg.ticks):
            mix = {}
            for a, api in enumerate(API_ENDPOINTS):
                n = int(calls[t, a])
                if n and roots[api] in synth.endpoints:
                    mix[roots[api]] = mix.get(roots[api], 0) + n
            n_media = int(rng.binomial(int(calls[t, 0]), p_media))
            media_eps = [e for e in synth.endpoints if "media" in e]
            if n_media and media_eps:
                mix[media_eps[0]] = mix.get(media_eps[0], 0) + n_media
            mix_series.append(mix)
        x = synth.synthesize_series(mix_series, seed=cfg.seed + index)
        preds = predictor.predict_series(x)        # [ticks, E, Q]

        # -- baselines on the same program --------------------------------
        # history replay: the last observed window, tiled (reference:
        # baselines.py:69-77 "repeat one window for every test step")
        reps = int(np.ceil(cfg.ticks / w))
        resrc_all = np.tile(observed_targets[-w:], (reps, 1))[:cfg.ticks]
        # invocation-count linear scaling onto the observed metric range
        inv_hyp = np.zeros((cfg.ticks, len(components)), np.float64)
        comp_idx = {c: i for i, c in enumerate(components)}
        for t, traces in enumerate(per_tick_traces):
            for c, n in count_invocations(traces).items():
                if c in comp_idx:
                    inv_hyp[t, comp_idx[c]] = n

        comp_records = {}
        for c in components:
            res_records = {}
            for m_i, metric in enumerate(metric_names):
                m_comp, resource = metric.rsplit("_", 1)
                if m_comp != c:
                    continue
                obs_series = observed_targets[:, m_i]
                inv_obs = observed.invocations.get(
                    c, observed.invocations.get("general"))
                # reference scaling weights (baselines.py:88-107) on the
                # full observed (baseline) period
                w1, w3 = np.min(inv_obs), np.ptp(inv_obs)
                w2, w4 = np.ptp(obs_series), np.min(obs_series)
                inv_h = inv_hyp[:, comp_idx[c]]
                comp_pred = ((inv_h - w1) * w2 / max(w3, 1e-9) + w4
                             if w3 > 0 else np.full(cfg.ticks, w4))

                series = {
                    "groundtruth": truth[metric].astype(np.float64),
                    "ours": preds[:, m_i, med].astype(np.float64),
                    "ours_lo": preds[:, m_i, 0].astype(np.float64),
                    "ours_hi": preds[:, m_i, -1].astype(np.float64),
                    "resrc": resrc_all[:, m_i].astype(np.float64),
                    "comp": np.asarray(comp_pred, np.float64),
                }
                if resource in REANCHOR_RESOURCES:
                    anchor = float(obs_last[m_i])
                    series = {k: _reanchor(v, anchor)
                              for k, v in series.items()}
                peak_obs = max(float(obs_peak[m_i]), 1e-9)
                scale = {k: float(np.max(np.abs(v)) / peak_obs)
                         for k, v in series.items()
                         if k not in ("ours_lo", "ours_hi")}
                rec = {k: np.round(v, 5).tolist() for k, v in series.items()}
                rec["observed"] = np.round(
                    obs_series[-2 * w:], 5).tolist()
                rec["scale"] = scale
                res_records[resource] = rec
            if res_records:
                comp_records[c] = res_records

        datasets[key] = {
            "shape": shape, "multiplier": mult, "group": group,
            "index": index, "composition": list(comp3),
            "calls": {api: calls[:, a].tolist()
                      for a, api in enumerate(API_ENDPOINTS)},
            "components": comp_records,
        }

    return {
        "meta": {
            "apis": list(API_ENDPOINTS),
            "components": components,
            "resources": sorted({m.rsplit("_", 1)[1] for m in metric_names}),
            "shapes": list(cfg.shapes),
            "multipliers": list(cfg.multipliers),
            "compositions": {"seen": [list(c) for c in cfg.seen],
                             "unseen": [list(c) for c in cfg.unseen]},
            "ticks": cfg.ticks,
            "window_size": w,
            "methods": ["groundtruth", "resrc", "comp", "ours"],
        },
        "datasets": datasets,
    }


def save_results(results: dict, path: str) -> str:
    payload = json.dumps(results).encode()
    if path.endswith(".gz"):
        with gzip.open(path, "wb") as f:
            f.write(payload)
    else:
        with open(path, "wb") as f:
            f.write(payload)
    return path
