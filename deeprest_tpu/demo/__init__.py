"""Interactive what-if estimation demo (SURVEY.md §2.4).

Capability parity with the reference's Dash app (reference: web-demo/):
precomputed what-if estimation results over load shapes × multipliers ×
API compositions, browsed through a web UI with per-component scaling-
factor comparisons and utilization time series.  Re-designed: results are
a JSON artifact produced by `precompute` (the reference ships only an
opaque results.pkl, its generator missing), ground truth for hypothetical
mixes comes from the workload simulator's resource model (the reference
needed real cluster runs), and the server is stdlib http.server + vanilla
JS/SVG instead of a Dash/Plotly dependency.
"""

from deeprest_tpu.demo.precompute import DemoConfig, precompute_results
from deeprest_tpu.demo.results import ResultsStore

__all__ = ["DemoConfig", "precompute_results", "ResultsStore"]
