"""Typed configuration for the whole framework.

The reference scatters its knobs across module-level constant blocks
(reference: resource-estimation/estimate.py:13-18, featurize.py:6-7,
qrnn.py:7-8, locust/locustfile-*.py:14-23).  Here every knob is a field on a
frozen dataclass so configs are explicit, serializable, and hashable enough
to key jit caches.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Dimensions of the multi-task quantile GRU.

    Defaults mirror the reference model (reference:
    resource-estimation/qrnn.py:7-8 — hidden 128, 1 layer, bidirectional,
    quantiles (.05, .50, .95), dropout 0.5).
    """

    feature_dim: int = 8          # padded call-path feature capacity |M|
    num_metrics: int = 3          # number of component_resource targets (experts)
    hidden_size: int = 128
    num_layers: int = 1
    bidirectional: bool = True
    quantiles: tuple[float, ...] = (0.05, 0.50, 0.95)
    dropout_rate: float = 0.50
    # bfloat16 matmuls on the MXU; params and loss stay float32.
    compute_dtype: str = "float32"
    # GRU recurrence backend: 'auto' uses the fused pallas kernel on TPU
    # and `lax.scan` elsewhere (ops/gru.py, ops/pallas_gru.py).
    rnn_backend: str = "auto"

    @property
    def directions(self) -> int:
        return 2 if self.bidirectional else 1

    @property
    def rnn_out_dim(self) -> int:
        return self.hidden_size * self.directions


LEVEL_RESOURCES = ("usage",)
"""Resources modeled as per-bucket increments by default (the
``TrainConfig.delta_resources`` default).  Disk usage accumulates writes —
a level whose absolute value encodes history the traffic cannot see;
predicting its CHANGE and integrating from a window anchor is the modeling
counterpart of the re-anchoring the reference demo applies to exactly
these level-type series (reference: web-demo/dataloader.py:143-156)."""


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training-loop knobs (reference: resource-estimation/estimate.py:13-18)."""

    num_epochs: int = 50
    batch_size: int = 32
    learning_rate: float = 1e-3
    train_split: float = 0.40     # leading fraction of windows used for training
    window_size: int = 60         # sliding-window length (time steps)
    eval_stride: int = 60         # test windows sampled every `stride` steps
    eval_max_cycles: int = 9      # cap on evaluated test windows per epoch
    eval_batch_size: int = 64     # eval windows per device batch (pages the
                                  # eval like predict(); one giant batch
                                  # OOMs at wide F × many windows)
    seed: int = 0
    checkpoint_dir: str | None = None
    checkpoint_every_epochs: int = 10
    log_every_steps: int = 50
    # Batches kept in flight on-device ahead of the step consuming them:
    # device transfers are asynchronous, so depth>=1 overlaps the
    # host->device copy of batch t+1 with the compute of batch t (the
    # reference ships every batch synchronously, estimate.py:68-69).
    # 0 disables prefetch.
    prefetch_depth: int = 2
    # Resources trained as per-bucket INCREMENTS instead of absolute
    # levels.  Disk usage is an integrator — its absolute value encodes a
    # history API traffic cannot see, so a traffic→level regression
    # structurally trails a persistence baseline; its per-bucket CHANGE is
    # what traffic causes (the reference demo re-anchors exactly these
    # level-type series before comparing, web-demo/dataloader.py:143-156).
    # Predictions for these resources are integrated from the window
    # anchor at eval/serve time (train/data.py:integrate_level_columns).
    # Empty tuple disables the delta formulation entirely.
    delta_resources: tuple[str, ...] = LEVEL_RESOURCES
    # Device-resident input pipeline: "auto" stages the normalized BASE
    # series in HBM (bf16 for bf16 models) on ACCELERATOR backends when it
    # fits the byte budget, and each train step gathers its windows by
    # start index — per-step host→device traffic becomes [B] int32
    # instead of the [B,W,F] window tensor (windows overlap W−1 of W
    # rows; materialized shipping re-sends every row W times).  On the
    # CPU backend "auto" does NOT stage: the transfer it avoids is a
    # memcpy, and XLA's CPU gather lowers to scalar loops (~3× slower
    # than host streaming at month scale).  "always" forces staging
    # (tests, virtual meshes); "off" always streams from host.
    device_data: str = "auto"
    device_data_max_bytes: int = 4 << 30
    # Fused multi-step supersteps on the staged (device-resident) path:
    # the whole epoch's shuffled batch plan ([C, S, B] start indices +
    # weights, trailing chunk zero-weight padded) ships to device once per
    # epoch and ``jax.lax.scan`` runs S train steps inside ONE donated jit
    # dispatch — an epoch becomes ceil(K/S) dispatches instead of K, with
    # per-step losses accumulated on device and read back once per
    # superstep.  Bit-identical to the per-step loop (same fold_in(rng,
    # step) dropout, same step counter; padded steps pass the prior state
    # through a cond skip branch).  1 = per-step dispatch (the historical
    # loop); "epoch" = the
    # whole epoch in one dispatch; "auto" = min(epoch length,
    # log_every_steps or 32), capped so a plan chunk stays under ~1 MiB.
    # Ignored when the dataset is not staged (host-feed fallback keeps the
    # per-step loop).
    steps_per_superstep: int | str = "auto"
    # Window-coalesced gradient accumulation on the staged superstep path:
    # G consecutive plan steps (microbatches) fold into ONE fused
    # forward/backward whose recurrence sees G·B rows per matmul — G× the
    # MXU row occupancy of the latency-bound [32,128]×[128,384] per-step
    # dot (PERF.md round 11) — and the optimizer update applies once per G
    # with summed grads.  Groups share the weights, so the fold is
    # algebraically free (unlike the rejected expert fold).  1 = the
    # historical per-step update (default; the G>1 paths are new code,
    # never silently entered).  Requires the staged (device-resident)
    # feed; per-microbatch losses keep their meaning and the step counter
    # still counts real microbatches.
    grad_accum_windows: int = 1
    # How the G microbatches are fused (ignored at G=1):
    #   "exact" (default) — per-microbatch grads via jax.vmap with the
    #     mask fold staged through an explicit jax.vjp prologue, summed in
    #     microbatch order: bit-identical losses AND params to the
    #     unfused accumulation loop (pinned by tests/test_coalesce.py).
    #     XLA flattens the shared-weight dots to G·B rows.
    #   "flat" — the G batches reshape into one [G·B] row batch through
    #     the model's group axis: maximum kernel-level row occupancy (the
    #     pallas recurrence sees G·B rows directly), per-microbatch
    #     losses still bit-exact, but weight-grad contractions
    #     re-associate across groups (~1e-7 relative on f32 — measured,
    #     documented in PERF.md; same class as the fused-inference delta
    #     tolerance).
    #   "loop" — G sequential unfused passes, summed grads: the pinned
    #     reference the other two are measured against.
    grad_accum_mode: str = "exact"
    # Sparse-first traffic feed (the 10k-endpoint tier, ROADMAP item 4):
    # traffic rows travel host→device as padded-COO ``(cols[K], vals[K])``
    # pairs — >99% of a 10k-wide count vector is zeros — and densify to
    # the model's static [.., F] via ONE on-device scatter inside the
    # existing train/eval executables (ops/densify.py).  Staged feed
    # bytes drop ~F/(2K) (~80× at F=10240, K=64); losses stay
    # BIT-IDENTICAL to the dense reference (tests/test_sparse.py).  The
    # dense path remains the default and the parity spec.  Requires the
    # staged (device-resident) feed — incompatible with
    # device_data="off".
    sparse_feed: bool = False
    # Max nonzero traffic columns per bucket row under sparse_feed; a
    # fatter row RAISES (dropping call paths would corrupt the count
    # vector).  Also the padded-COO row width, so it sizes both ring
    # memory and feed bytes.
    sparse_nnz_cap: int = 64
    # Preemption-safe training (ROADMAP item 7, dynamic half): every this
    # many REAL train steps (superstep path: at the first chunk boundary
    # at or past the cadence) the trainer writes an atomic
    # deeprest-sharded-v1 checkpoint PLUS the epoch-plan cursor (epoch
    # index, steps done within the epoch, the shuffle rng's bit-generator
    # state at epoch start, global step) into the sidecar.  A killed run
    # restarts via ``Trainer.resume_training`` — onto whatever mesh
    # remains (cross-mesh restore) — replays the plan from the cursor,
    # and is bit-identical to the uninterrupted run at the same step
    # (tests/test_chaos.py).  0 = off (the historical behavior; epoch-
    # cadence checkpoints only).
    snapshot_every_steps: int = 0
    # Snapshot retention GC: keep only the newest this-many CURSOR
    # snapshots (the preemption-resume anchors) — snapshot_every_steps
    # used to accumulate checkpoints unboundedly.  Pruning happens only
    # AFTER a durable newer save and never touches the newest (restore-
    # target) snapshots or non-cursor checkpoints (epoch-cadence saves,
    # streaming refresh checkpoints — the stream's keep_checkpoints owns
    # those).  0 = unlimited (the historical behavior).
    snapshot_keep: int = 3
    # Elastic remeshing (ROADMAP item 7's last training gap): survive
    # device loss IN-PROCESS.  The fault barrier around the step/
    # superstep dispatch catches the device-loss family (real
    # XlaRuntimeError device errors on hardware; the deterministic
    # FaultInjector's DeviceLossError on CPU), re-enumerates healthy
    # devices, rebuilds the mesh (data axis shrinks by divisors,
    # expert/model preserved — parallel/mesh.shrink_mesh_config),
    # re-derives every sharding from the one rule table, restores the
    # newest fsync'd cursor snapshot through the cross-mesh assembly,
    # re-stages the epoch plan onto the new mesh, and continues — the
    # post-remesh trajectory is BIT-IDENTICAL to killing the process and
    # running resume_training on the survivor mesh (tests/test_chaos.py).
    # Requires cursor snapshots (snapshot_every_steps >= 1 and a
    # checkpoint_dir at fit time).
    elastic: bool = False
    # Bounded recovery: total remeshes one fit() may perform before the
    # barrier surfaces RemeshExhaustedError instead of respinning (the
    # RS004 discipline on the training plane), and the backoff slept
    # before each rebuild (scaled by the attempt number).
    remesh_max_attempts: int = 3
    remesh_backoff_ms: float = 100.0

    def __post_init__(self):
        v = self.steps_per_superstep
        ok = v in ("auto", "epoch") or (
            isinstance(v, int) and not isinstance(v, bool) and v >= 1)
        if not ok:
            raise ValueError(
                f"TrainConfig.steps_per_superstep={v!r}: must be 'auto', "
                f"'epoch', or an int >= 1")
        g = self.grad_accum_windows
        if not isinstance(g, int) or isinstance(g, bool) or g < 1:
            raise ValueError(
                f"TrainConfig.grad_accum_windows={g!r}: must be an int >= 1")
        if self.grad_accum_mode not in ("exact", "flat", "loop"):
            raise ValueError(
                f"TrainConfig.grad_accum_mode={self.grad_accum_mode!r}: "
                f"must be 'exact', 'flat', or 'loop'")
        if not isinstance(self.sparse_nnz_cap, int) \
                or isinstance(self.sparse_nnz_cap, bool) \
                or self.sparse_nnz_cap < 1:
            raise ValueError(
                f"TrainConfig.sparse_nnz_cap={self.sparse_nnz_cap!r}: "
                f"must be an int >= 1")
        s = self.snapshot_every_steps
        if not isinstance(s, int) or isinstance(s, bool) or s < 0:
            raise ValueError(
                f"TrainConfig.snapshot_every_steps={s!r}: must be an "
                f"int >= 0 (0 = snapshots off)")
        k = self.snapshot_keep
        if not isinstance(k, int) or isinstance(k, bool) or k < 0:
            raise ValueError(
                f"TrainConfig.snapshot_keep={k!r}: must be an int >= 0 "
                "(0 = unlimited retention)")
        a = self.remesh_max_attempts
        if not isinstance(a, int) or isinstance(a, bool) or a < 1:
            raise ValueError(
                f"TrainConfig.remesh_max_attempts={a!r}: must be an "
                "int >= 1 (the barrier must stay bounded)")
        if not isinstance(self.remesh_backoff_ms, (int, float)) \
                or isinstance(self.remesh_backoff_ms, bool) \
                or self.remesh_backoff_ms < 0:
            raise ValueError(
                f"TrainConfig.remesh_backoff_ms="
                f"{self.remesh_backoff_ms!r}: must be a number >= 0")
        if self.elastic and self.snapshot_every_steps < 1:
            raise ValueError(
                "TrainConfig.elastic=True requires snapshot_every_steps "
                ">= 1: the remesh barrier restores from cursor "
                "snapshots; without them a device loss would restart "
                "training from scratch silently")
        if self.sparse_feed and self.device_data == "off":
            raise ValueError(
                "TrainConfig.sparse_feed=True requires the staged "
                "(device-resident) feed — the on-device densify lives "
                "inside the staged executables; set device_data to "
                "'auto' or 'always'")


@dataclasses.dataclass(frozen=True)
class FeaturizeConfig:
    """Call-path feature-space construction.

    The raw feature space is unbounded (one dimension per observed
    root-to-node call path; reference: resource-estimation/featurize.py:11-24).
    XLA wants static shapes, so the vector is materialized at a fixed
    ``capacity``; ``hash_features=True`` switches from a growable dictionary
    to stable hash-bucketing so streaming corpora never force a recompile.
    """

    capacity: int = 0             # 0 = size to the observed space, rounded up
    round_to: int = 128           # pad capacity to a multiple (MXU lane width)
    hash_features: bool = False
    hash_seed: int = 0x5EED

    def __post_init__(self):
        if self.hash_features and self.capacity <= 0:
            raise ValueError(
                "hash_features=True requires an explicit capacity > 0 "
                "(there is no observed vocabulary to size the space from)"
            )


@dataclasses.dataclass(frozen=True)
class EtlConfig:
    """Host-ETL pipeline knobs (featurization + streaming ingest).

    The featurization firehose is host-side work (trace walking, hashing,
    counting) that must keep up with the device (PERF.md "Host ETL"):
    ``workers`` shards offline corpus featurization across a forked
    process pool, and ``overlap`` moves the streaming trainer's
    tail→parse→featurize onto a background thread double-buffered against
    device fine-tuning, with ``queue_depth`` bounding the featurized-but-
    not-yet-ingested backlog (backpressure blocks the ETL thread, which
    in turn stops draining the tailer).
    """

    workers: int = 1              # offline featurize pool: 1 = serial, 0 = per-CPU
    queue_depth: int = 512        # buckets buffered between ETL and train threads
    overlap: bool = True          # background ETL thread in StreamingTrainer.run

    def __post_init__(self):
        if self.workers < 0:
            raise ValueError(f"EtlConfig.workers={self.workers}: must be >= 0")
        if self.queue_depth < 1:
            raise ValueError(
                f"EtlConfig.queue_depth={self.queue_depth}: must be >= 1")


@dataclasses.dataclass(frozen=True)
class InferConfig:
    """Serving-side rolled-inference knobs (serve/fused.py).

    ``fused=True`` routes ``predict_series`` / ``predict_series_many``
    through the device-resident one-dispatch-per-page pipeline (on-device
    normalize → model → clamp, prefix-sum delta integration, carry
    threaded between pages on device); ``False`` pins the host-loop
    reference path.  ``page_windows`` sets the fused page size explicitly
    (an off-ladder value adds one per-rung executable).  ``None`` picks a
    backend-tuned default: small cache-resident pages on the CPU backend
    (measured ~2x per-window over rung-32/64 batches — PERF.md "rolled
    inference"), the ladder's top rung on accelerators (MXU occupancy).
    """

    fused: bool = True
    page_windows: int | None = None
    # Multi-series/multi-scenario page coalescing (serve/fused.py): fold up
    # to this many consecutive pages of the window plan into ONE dispatch,
    # so a rung-64 page becomes a 64·G-row batch that actually fills MXU
    # rows instead of paging thin.  The carry/segment machinery already
    # expresses any fold in one batch, so this only widens dispatches (new
    # super-rungs page·{2..G} join the jit ladder).  None = backend auto:
    # 1 on the CPU backend (the per-window cost there is cache-bound and
    # MINIMIZED at small pages — PERF.md "rolled inference"), 4 on
    # accelerators (256 recurrence rows at the default ladder).
    coalesce_pages: int | None = None
    # Sparse-first serving feed (the serve-side twin of
    # TrainConfig.sparse_feed): traffic series ship host→device as
    # padded-COO ``(cols[K], vals[K])`` window pages and densify inside
    # the fused executable (ops/densify.py) — ~F/(2K) fewer feed bytes
    # at 10k-endpoint width, bit-identical non-delta outputs, and the
    # executable count stays flat (one sparse program per rung).  Dense
    # entry paths remain the default and the parity spec.
    sparse_feed: bool = False
    sparse_nnz_cap: int = 64
    # Quantized serving (ops/quantize.py, round 22): "int8" stores every
    # GRU/dense weight matrix per-output-channel symmetric int8 and
    # dequantizes at use inside the fused executables (~3.9x fewer weight
    # bytes); "bf16" halves them.  Output drift vs the f32 reference is
    # measured at quantize time and pinned as a parity envelope next to
    # the checkpoint — a violating reload raises (QuantParityError).
    quant: str = "off"

    def __post_init__(self):
        if self.quant not in ("off", "int8", "bf16"):
            raise ValueError(
                f"InferConfig.quant={self.quant!r}: must be one of "
                "'off', 'int8', 'bf16'")
        if not isinstance(self.sparse_nnz_cap, int) \
                or isinstance(self.sparse_nnz_cap, bool) \
                or self.sparse_nnz_cap < 1:
            raise ValueError(
                f"InferConfig.sparse_nnz_cap={self.sparse_nnz_cap!r}: "
                f"must be an int >= 1")
        if self.page_windows is not None and self.page_windows < 1:
            raise ValueError(
                f"InferConfig.page_windows={self.page_windows}: must be "
                ">= 1 (or None for the ladder's top rung)")
        if self.coalesce_pages is not None and self.coalesce_pages < 1:
            raise ValueError(
                f"InferConfig.coalesce_pages={self.coalesce_pages}: must "
                "be >= 1 (or None for the backend default)")


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (deeprest_tpu/obs).

    ``enabled`` gates the SPAN recorder only — metrics counters are
    always live (they are the cheap half, and /metrics must answer even
    on a spans-off plane).  ``span_capacity`` bounds the in-process span
    ring (newest win; a long-lived server must never grow unbounded).
    """

    enabled: bool = False
    span_capacity: int = 4096

    def __post_init__(self):
        if self.span_capacity < 1:
            raise ValueError(
                f"ObsConfig.span_capacity={self.span_capacity}: must be "
                ">= 1")


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Model-quality monitors + the drift→retrain→reload loop
    (deeprest_tpu/obs/quality.py, train/stream.DriftController —
    ROADMAP item 6).

    The monitors watch the live bucket stream: feature-distribution
    drift (streaming per-call-path PSI/KS vs the training reference),
    rolling q-band coverage + pinball calibration, and the continuous
    not-justified-by-traffic anomaly check.  Every verdict stream runs
    through a hysteresis machine — separate enter/exit thresholds plus
    sustained-sweep counts — so one noisy window never flaps the
    surface.  ``auto_retrain`` is the act half: sustained drift triggers
    an out-of-cadence retrain on the retained rings, then a rolling
    reload into the serving plane (``retrain_cooldown_buckets`` bounds
    the loop's own thrash; ``auto_retrain=False`` is the manual
    override — verdicts only, a human pulls the trigger).
    """

    enabled: bool = False
    # sweep cadence (buckets between monitor passes) and the trailing
    # live window the drift score compares against the reference
    sweep_every_buckets: int = 30
    live_window: int = 120
    min_sweep_buckets: int = 8
    # Drift-reference anchor: the trailing this-many retained buckets at
    # (re)train time.  The verdict's question is "has the distribution
    # moved since the model last trained" — anchoring on the ring TAIL
    # (not the whole history) lets the verdict EXIT once a retrain has
    # adapted to the new regime, instead of forever comparing the live
    # stream against a pre/post mixture.
    reference_window: int = 240
    # hysteresis: enter/exit thresholds per stream + sustained counts
    drift_enter: float = 0.25          # traffic-mass-weighted PSI
    drift_exit: float = 0.10
    calibration_enter: float = 0.30    # undercoverage (nominal - observed)
    calibration_exit: float = 0.15
    anomaly_enter: float = 1.00        # mean normalized excess (≥ one
    anomaly_exit: float = 0.25         # full scale unit above the band)
    sustain_enter: int = 2
    sustain_exit: int = 2
    # calibration rolling window, in sweeps
    calibration_sweeps: int = 8
    # the continuous not-justified-by-traffic check's knobs (the same
    # meaning as the batch /v1/anomaly route's)
    anomaly_tolerance: float = 0.10
    anomaly_min_run: int = 5
    # Cold-start honesty: a stream's model in its first refreshes is
    # undertrained, and a bad band produces one-sided excess that is
    # indistinguishable from a real traffic-decoupled consumer (measured
    # — PERF.md round 18).  The model-CONDITIONED verdict streams
    # (calibration, anomaly) therefore arm only after this many
    # refreshes on the train plane; the serving plane arms immediately
    # (its checkpoint is trusted by definition of serving it).
    model_warmup_refreshes: int = 3
    # the act half (DriftController)
    auto_retrain: bool = True
    retrain_cooldown_buckets: int = 240
    # retraining ON anomalous data would teach the model the very
    # consumption the paper's sanity check exists to flag; default off
    retrain_during_anomaly: bool = False

    def __post_init__(self):
        for name in ("sweep_every_buckets", "live_window",
                     "min_sweep_buckets", "reference_window",
                     "sustain_enter", "sustain_exit",
                     "calibration_sweeps", "anomaly_min_run"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"QualityConfig.{name}={v!r}: must be an int >= 1")
        if self.retrain_cooldown_buckets < 0:
            raise ValueError(
                f"QualityConfig.retrain_cooldown_buckets="
                f"{self.retrain_cooldown_buckets}: must be >= 0")
        if not isinstance(self.model_warmup_refreshes, int) \
                or isinstance(self.model_warmup_refreshes, bool) \
                or self.model_warmup_refreshes < 0:
            raise ValueError(
                f"QualityConfig.model_warmup_refreshes="
                f"{self.model_warmup_refreshes!r}: must be an int >= 0")
        for enter, exit_ in (("drift_enter", "drift_exit"),
                             ("calibration_enter", "calibration_exit"),
                             ("anomaly_enter", "anomaly_exit")):
            if getattr(self, exit_) > getattr(self, enter):
                raise ValueError(
                    f"QualityConfig.{exit_} must be <= {enter} "
                    "(hysteresis needs exit at or below enter)")


@dataclasses.dataclass(frozen=True)
class SurfaceConfig:
    """Capacity-surface plane (deeprest_tpu/serve/surface.py — ROADMAP
    item 5): precomputed what-if surfaces answering ``/v1/whatif`` and
    ``/v1/whatif/surface`` by multilinear interpolation, invalidated on
    every backend reload.

    ``grid`` is the per-axis scale ladder a surface sweeps around its
    base program; ``max_axes`` caps the grid dimensionality (more active
    endpoints than this collapse to one shared scale axis — vertex count
    is ``len(grid) ** axes``); ``jitter`` is the Monte-Carlo probe count
    behind the measured parity envelope.  ``max_surfaces``/``max_bytes``
    bound the host-resident LRU; ``warm_async`` builds cache-miss
    surfaces on a background thread (the miss answers from the frontier
    meanwhile) instead of inline.
    """

    enabled: bool = False
    grid: tuple[float, ...] = (0.5, 1.0, 2.0, 4.0)
    max_axes: int = 3
    jitter: int = 8
    max_surfaces: int = 8
    max_bytes: int = 64 * 1024 * 1024
    warm_async: bool = True

    def __post_init__(self):
        for name in ("max_axes", "max_surfaces", "max_bytes"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"SurfaceConfig.{name}={v!r}: must be an int >= 1")
        if not isinstance(self.jitter, int) or isinstance(self.jitter, bool) \
                or self.jitter < 0:
            raise ValueError(
                f"SurfaceConfig.jitter={self.jitter!r}: must be an int >= 0")
        grid = tuple(float(g) for g in self.grid)
        if len(grid) < 2 or list(grid) != sorted(set(grid)) or grid[0] <= 0:
            raise ValueError(
                f"SurfaceConfig.grid={self.grid!r}: must be >= 2 strictly-"
                "increasing positive scales")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet tier (deeprest_tpu/serve/fleet.py — ROADMAP item 3): M
    tenant applications on one serving plane through a checkpoint-keyed
    predictor pool.

    ``hbm_budget`` bounds how many tenants' params stay device-resident
    (the LRU working set — evicted tenants spill to host memory and
    restore with one ``device_put``); ``aot`` loads serialized
    executables at admission (serve/aot.py) so a tenant's cold start is
    a deserialize, not a compile; ``top_k_tenants`` bounds per-tenant
    observability cardinality (/metrics labels, /healthz maps — the
    rest rolls up under ``__other__``); ``quality`` attaches one
    QualityMonitor per pool entry (per-tenant /v1/verdict).
    """

    enabled: bool = False
    hbm_budget: int = 4
    aot: bool = True
    top_k_tenants: int = 8
    quality: bool = True

    def __post_init__(self):
        for name in ("hbm_budget", "top_k_tenants"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"FleetConfig.{name}={v!r}: must be an int >= 1")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device-mesh shape for pjit/GSPMD execution.

    Axes: ``data`` shards the batch (DP over ICI), ``expert`` shards the
    stacked per-metric experts (EP), ``model`` shards the feature/hidden
    dimensions of the mask and GRU projections (TP) for huge call-path
    spaces.  Pipeline/sequence parallelism are deliberately N/A for this
    model family (window length 60, recurrent core; SURVEY.md §2.5/§5.7).
    """

    data: int = 1
    expert: int = 1
    model: int = 1

    @property
    def size(self) -> int:
        return self.data * self.expert * self.model

    @classmethod
    def parse(cls, spec: str) -> "MeshConfig":
        """``"D,E,M"`` → MeshConfig (the shared ``--mesh`` CLI contract
        for train, serve, predict, and bench)."""
        try:
            d, e, m = (int(x) for x in spec.split(","))
        except ValueError:
            raise ValueError(
                f"mesh spec {spec!r} is not data,expert,model") from None
        if min(d, e, m) < 1:
            raise ValueError(f"mesh spec {spec!r}: axis sizes must be >= 1")
        return cls(data=d, expert=e, model=m)


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    featurize: FeaturizeConfig = dataclasses.field(default_factory=FeaturizeConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    etl: EtlConfig = dataclasses.field(default_factory=EtlConfig)
    infer: InferConfig = dataclasses.field(default_factory=InferConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    quality: QualityConfig = dataclasses.field(default_factory=QualityConfig)
    surface: SurfaceConfig = dataclasses.field(default_factory=SurfaceConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)

    def replace(self, **sections: Any) -> "Config":
        return dataclasses.replace(self, **sections)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Config":
        def build(tp, section):
            known = {f.name for f in dataclasses.fields(tp)}
            kwargs = dict(section)
            unknown = set(kwargs) - known
            if unknown:
                raise ValueError(
                    f"unknown {tp.__name__} keys: {sorted(unknown)} "
                    f"(known: {sorted(known)})"
                )
            for k, v in kwargs.items():
                if isinstance(v, list):
                    kwargs[k] = tuple(v)
            return tp(**kwargs)

        return cls(
            model=build(ModelConfig, d.get("model", {})),
            train=build(TrainConfig, d.get("train", {})),
            featurize=build(FeaturizeConfig, d.get("featurize", {})),
            mesh=build(MeshConfig, d.get("mesh", {})),
            etl=build(EtlConfig, d.get("etl", {})),
            infer=build(InferConfig, d.get("infer", {})),
            obs=build(ObsConfig, d.get("obs", {})),
            quality=build(QualityConfig, d.get("quality", {})),
            surface=build(SurfaceConfig, d.get("surface", {})),
            fleet=build(FleetConfig, d.get("fleet", {})),
        )

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls.from_dict(json.loads(s))
