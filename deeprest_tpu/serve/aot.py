"""AOT executable serialization: tenant cold-start is a deserialize.

The fleet tier (serve/fleet.py) shares ONE fused-engine executable set
across every tenant, so the plane compiles each (program, rung) pair at
most once — but that once is still an XLA compile on the serving path,
and a cold plane admitting its first tenant pays the whole ladder.  This
module moves the compile to EXPORT time: ``export_aot`` lowers the fused
serving programs at every rung (``jax.jit(...).lower().compile()``, the
AOT lineage), serializes each compiled executable
(``jax.experimental.serialize_executable``), and writes the artifacts
next to the checkpoint (``<ckpt>/aot/``).  ``load_aot`` — called at pool
admission — deserializes every artifact whose manifest fingerprint
matches the live engine and installs it into the engine's AOT dispatch
table, so the first request compiles nothing; rungs with no loadable
artifact fall back to the normal lazy jit compile and are counted
loudly (the pool's compile-fallback counter).

Three contracts keep this honest:

- **Params-agnostic artifacts.**  The fused program threads params and
  normalization stats as runtime ARGUMENTS (serve/fused.py bit-parity
  contract), so one artifact set serves every tenant of the same
  architecture + quant mode; only avals (shapes/dtypes/tree structure)
  are baked, and the manifest fingerprints exactly those.
- **Identical lowering.**  The serialized executable is compiled from
  the SAME traced program the lazy jit path would compile, with default
  options on the same backend — outputs are bit-identical either way
  (asserted by benchmarks/fleet_bench.py's parity arm).
- **Loud staleness.**  A manifest whose fingerprint (jax version, XLA
  platform, geometry, params tree signature) mismatches the live engine
  is never partially loaded: the whole load falls back to compile, with
  the mismatch named in the result — a stale artifact must cost a
  compile, never a wrong answer.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

AOT_SUBDIR = "aot"
MANIFEST_NAME = "manifest.json"


def aot_dir(checkpoint_dir: str) -> str:
    """Where a checkpoint's AOT artifacts live (next to the checkpoint —
    the artifacts are as checkpoint-adjacent as the quant parity
    envelope, and ride the same directory copy)."""
    return os.path.join(checkpoint_dir, AOT_SUBDIR)


def _tree_signature(params) -> str:
    """Stable hash of the params AVAL pytree — structure plus per-leaf
    shape/dtype, never values: the executable is params-agnostic but
    aval-exact, so this is the exact compatibility surface."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    h = hashlib.sha1(str(treedef).encode())
    for leaf in leaves:
        # np.result_type reads dtype METADATA (no array materialization,
        # no device->host copy for jax leaves)
        dtype = getattr(leaf, "dtype", None) or np.result_type(leaf)
        h.update(str((tuple(np.shape(leaf)), str(dtype))).encode())
    return h.hexdigest()[:16]


def engine_fingerprint(predictor) -> dict:
    """Everything that must match between the exporting and the loading
    engine for a serialized executable to be callable and correct."""
    import jax

    eng = predictor.fused
    if eng is None:
        raise ValueError("AOT artifacts cover the fused serving engine; "
                         "construct the predictor with fused=True")
    return {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "window_size": int(predictor.window_size),
        "feature_dim": int(predictor.feature_dim),
        "num_metrics": len(predictor.metric_names),
        "num_quantiles": len(predictor.quantiles),
        "quant": predictor.quant,
        "rungs": list(int(r) for r in eng.rungs),
        "delta": bool(eng._has_delta),
        "sparse_nnz_cap": eng._sparse_nnz_cap,
        "params_tree": _tree_signature(eng._params),
    }


def _example_args(predictor, rung: int, sparse: bool):
    """The exact argument tuple the fused dispatch site passes at this
    rung — same shapes, dtypes, and tree structure (serve/fused.py
    ``_predict_many_inner``); zeros everywhere because only avals
    matter for lowering and tree reconstruction."""
    import jax.numpy as jnp

    eng = predictor.fused
    w = eng.window_size
    g = jnp.asarray(np.full((rung,), w - 1, np.int32))
    seg = jnp.asarray(np.zeros((rung,), np.bool_))
    tail = (eng._x_mn, eng._x_rg, eng._y_mn, eng._y_rg, eng._carry0,
            g, seg, np.int32(rung), np.bool_(True))
    if sparse:
        k = eng._sparse_nnz_cap
        xc = jnp.asarray(np.zeros((rung, w, k), np.int32))
        xv = jnp.asarray(np.zeros((rung, w, k), np.float32))
        return (eng._params, xc, xv) + tail
    feat = int(predictor.feature_dim)
    x = jnp.asarray(np.zeros((rung, w, feat), np.float32))
    return (eng._params,) + (x,) + tail


def _programs(eng):
    out = [("dense", eng._jit)]
    if eng._jit_sparse is not None:
        out.append(("sparse", eng._jit_sparse))
    return out


def export_aot(predictor, checkpoint_dir: str,
               rungs=None) -> dict:
    """Compile and serialize the fused serving executables next to the
    checkpoint.  Returns the manifest (also written to
    ``<ckpt>/aot/manifest.json``).

    Lowering + AOT compile does NOT enter the jit call cache (verified
    by tests/test_fleet.py), so exporting from a live predictor never
    perturbs the zero-post-warmup-compiles ledger.
    """
    import jax
    from jax.experimental.serialize_executable import serialize

    eng = predictor.fused
    fp = engine_fingerprint(predictor)
    out_dir = aot_dir(checkpoint_dir)
    os.makedirs(out_dir, exist_ok=True)
    entries = []
    # Compile OUTSIDE the persistent compilation cache: a cache-hit
    # executable serializes as a thin reference to jit-compiled symbols
    # ("Symbols not found" at deserialize time) instead of embedding its
    # object code, and the artifact must be self-contained on any host.
    # Disabling the flag is NOT enough: the cache keeps an in-memory
    # layer, and a prior compile of the same program (the predictor's
    # own warmup, with the cache live) leaves a cache-backed executable
    # there that .compile() returns even with the flag off — reset it
    # so the export compile is genuinely fresh.
    cache_was = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):
        # private API moved: exports still compile fresh whenever no
        # prior cache-backed executable exists; load_aot's fallback
        # path names any artifact that fails to deserialize
        pass
    try:
        for rung in (tuple(rungs) if rungs is not None else eng.rungs):
            for kind, jitted in _programs(eng):
                args = _example_args(predictor, int(rung), kind == "sparse")
                compiled = jitted.lower(*args).compile()
                payload, _, _ = serialize(compiled)
                fname = f"{kind}_r{int(rung)}.bin"
                with open(os.path.join(out_dir, fname), "wb") as f:
                    f.write(payload)
                entries.append({"kind": kind, "rung": int(rung),
                                "file": fname, "bytes": len(payload)})
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_was)
    manifest = {"fingerprint": fp, "entries": entries}
    with open(os.path.join(out_dir, MANIFEST_NAME), "w",
              encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return manifest


def load_aot(predictor, checkpoint_dir: str) -> dict:
    """Load-or-compile at pool admission: deserialize every artifact
    whose fingerprint matches the live engine into the engine's AOT
    dispatch table.  Never raises on artifact problems — a missing/
    stale/corrupt artifact means that rung compiles lazily through the
    normal jit path, and the result names every such fallback:

    ``{"loaded": n, "fallback_rungs": [(kind, rung), ...],
       "reason": None | str, "bytes": total_payload_bytes}``
    """
    from jax.experimental.serialize_executable import deserialize_and_load
    import jax.tree_util as jtu

    eng = predictor.fused
    result = {"loaded": 0, "fallback_rungs": [], "reason": None, "bytes": 0}
    if eng is None:
        result["reason"] = "fused engine disabled"
        return result
    want = [(kind, int(r)) for r in eng.rungs for kind, _ in _programs(eng)]
    man_path = os.path.join(aot_dir(checkpoint_dir), MANIFEST_NAME)
    if not os.path.exists(man_path):
        result["reason"] = "no artifacts"
        result["fallback_rungs"] = want
        return result
    try:
        with open(man_path, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        result["reason"] = f"unreadable manifest: {e}"
        result["fallback_rungs"] = want
        return result
    fp = engine_fingerprint(predictor)
    stored = manifest.get("fingerprint", {})
    if stored != fp:
        diff = sorted(k for k in set(fp) | set(stored)
                      if fp.get(k) != stored.get(k))
        result["reason"] = f"fingerprint mismatch: {diff}"
        result["fallback_rungs"] = want
        return result
    by_key = {(e["kind"], int(e["rung"])): e
              for e in manifest.get("entries", ())}
    errors = []
    for kind, rung in want:
        entry = by_key.get((kind, rung))
        if entry is None:
            result["fallback_rungs"].append((kind, rung))
            continue
        try:
            with open(os.path.join(aot_dir(checkpoint_dir),
                                   entry["file"]), "rb") as f:
                payload = f.read()
            args = _example_args(predictor, rung, kind == "sparse")
            _, in_tree = jtu.tree_flatten((args, {}))
            # the program returns (out, carry): a 2-tuple of arrays
            _, out_tree = jtu.tree_flatten((0.0, 0.0))
            loaded = deserialize_and_load(payload, in_tree, out_tree)
            eng._aot[(kind, rung)] = loaded
            result["loaded"] += 1
            result["bytes"] += len(payload)
        except Exception as e:   # noqa: BLE001 — any artifact failure
            # must degrade to a compile, never kill an admission
            result["fallback_rungs"].append((kind, rung))
            errors.append(f"{kind}_r{rung}: {type(e).__name__}: {e}")
    if errors:
        result["reason"] = "; ".join(errors[:4])
    return result
