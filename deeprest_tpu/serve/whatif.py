"""What-if capacity estimation: hypothetical API traffic → utilization.

The headline DeepRest use case (reference: README.md:5, web-demo/): "how
much resource would each component need if traffic looked like X?" for X
with shapes/scales/compositions never observed.  Pipeline: per-endpoint
trace synthesis (data/synthesize.py) → feature series → quantile
predictions per component×resource.
"""

from __future__ import annotations

import numpy as np

from deeprest_tpu.data.synthesize import TraceSynthesizer
from deeprest_tpu.serve.predictor import Predictor


class WhatIfEstimator:
    """Synthesizer + predictor, composed."""

    def __init__(self, predictor: Predictor, synthesizer: TraceSynthesizer):
        if synthesizer.space.capacity != predictor.feature_dim:
            raise ValueError(
                f"synthesizer capacity {synthesizer.space.capacity} != model "
                f"feature_dim {predictor.feature_dim}"
            )
        self.predictor = predictor
        self.synthesizer = synthesizer

    @property
    def endpoints(self) -> list[str]:
        return self.synthesizer.endpoints

    def _is_relative(self, e: int) -> bool:
        dm = self.predictor.delta_mask
        return dm is not None and bool(dm[e])

    def estimate(
        self,
        expected_traffic: list[dict[str, int]],
        seed: int = 0,
    ) -> dict[str, dict[str, np.ndarray]]:
        """``expected_traffic[t] = {endpoint: count}`` → per-metric series.

        Returns ``{metric: {"q05"|"q50"|"q95": [T] utilization}}`` (keys
        follow the configured quantiles).  Delta-trained metrics
        (``predictor.delta_mask``, e.g. disk usage) come back as RELATIVE
        growth from the start of the hypothetical program — there is no
        observed level to anchor a what-if to; the reference demo
        re-anchors exactly these series before display
        (web-demo/dataloader.py:143-156).
        """
        x = self.synthesizer.synthesize_series(expected_traffic, seed=seed)
        preds = self.predictor.predict_series(x)          # [T, E, Q]
        quantiles = self.predictor.quantiles
        out: dict[str, dict[str, np.ndarray]] = {}
        for e, metric in enumerate(self.predictor.metric_names):
            out[metric] = {
                f"q{int(q * 100):02d}": preds[:, e, qi]
                for qi, q in enumerate(quantiles)
            }
        return out

    def scaling_factor(
        self,
        baseline_traffic: list[dict[str, int]],
        hypothetical_traffic: list[dict[str, int]],
        seed: int = 0,
    ) -> dict[str, float]:
        """Per-metric peak scaling factor between two traffic programs
        (the number the reference demo renders as bar charts,
        web-demo/dataloader.py:143-156).  For delta-trained level metrics
        the factor compares GROWTH over the program (peak minus start) —
        the reference demo's own post-re-anchor semantics; a peak ratio on
        a relative-from-zero rollout would be meaningless.

        With a MicroBatcher attached to the predictor the two programs
        are estimated CONCURRENTLY, so their windows coalesce into shared
        device batches instead of two sequential dispatch trains."""
        if getattr(self.predictor, "batcher", None) is not None:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=2) as pool:
                fb = pool.submit(self.estimate, baseline_traffic, seed)
                fh = pool.submit(self.estimate, hypothetical_traffic,
                                 seed + 1)
                base, hypo = fb.result(), fh.result()
        else:
            base = self.estimate(baseline_traffic, seed=seed)
            hypo = self.estimate(hypothetical_traffic, seed=seed + 1)
        factors = {}
        for e, metric in enumerate(self.predictor.metric_names):
            bs, hs = base[metric]["q50"], hypo[metric]["q50"]
            if self._is_relative(e):
                # Growth can legitimately be ~0 (a program driving no
                # writes): clamp at 0 and define 0-growth/0-growth as 1.0
                # (no change) instead of letting inf leak into bar charts.
                b = max(float(np.max(bs) - bs[0]), 0.0)
                h = max(float(np.max(hs) - hs[0]), 0.0)
                factors[metric] = (h / b if b > 0
                                   else (1.0 if h == 0 else float("inf")))
            else:
                b = float(np.max(bs))
                h = float(np.max(hs))
                factors[metric] = h / b if b > 0 else float("inf")
        return factors
