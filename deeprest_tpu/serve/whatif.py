"""What-if capacity estimation: hypothetical API traffic → utilization.

The headline DeepRest use case (reference: README.md:5, web-demo/): "how
much resource would each component need if traffic looked like X?" for X
with shapes/scales/compositions never observed.  Pipeline: per-endpoint
trace synthesis (data/synthesize.py) → feature series → quantile
predictions per component×resource.

Multi-scenario estimation (:meth:`WhatIfEstimator.estimate_many`, the
capacity sweep :meth:`WhatIfEstimator.sweep`, and
:meth:`WhatIfEstimator.scaling_factor`) batches S hypothetical traffic
programs through the predictor's fused device pipeline
(``predict_series_many``, serve/fused.py): all scenarios fold into the
scenario×window batch axis and page through the same per-rung fused
executables — S scenarios cost ~⌈ΣS windows / page⌉ device dispatches
instead of S sequential host-loop prediction trains, and compile nothing
new.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

import numpy as np

from deeprest_tpu.data.synthesize import TraceSynthesizer
from deeprest_tpu.serve.predictor import Predictor

# Per-estimator raw-prediction memo size.  Sized for the repeat pattern
# that actually occurs (the BASELINE program re-estimated by every
# scaling_factor/sweep call against the same snapshot), not as a general
# result cache — that is serve/surface.py's job.
_RAW_CACHE_MAX = 32


def _program_key(program: list[dict], seed: int) -> str:
    """Canonical memo key for one (traffic program, synthesis seed)."""
    return json.dumps(program, sort_keys=True,
                      separators=(",", ":")) + f"|{seed}"


class WhatIfEstimator:
    """Synthesizer + predictor, composed.

    Estimation is memoized per (traffic program, seed) in a small LRU:
    ``scaling_factor`` and ``sweep`` re-estimate the same BASELINE
    program on every call, and the what-if surface plane
    (serve/surface.py) probes overlapping mixes.  The memo lives on the
    estimator instance, and every reload path builds a FRESH estimator
    over the fresh backend (server.maybe_reload/reload_from), so a memo
    entry can never outlive the params snapshot that produced it.
    """

    def __init__(self, predictor: Predictor, synthesizer: TraceSynthesizer):
        if synthesizer.space.capacity != predictor.feature_dim:
            raise ValueError(
                f"synthesizer capacity {synthesizer.space.capacity} != model "
                f"feature_dim {predictor.feature_dim}"
            )
        self.predictor = predictor
        self.synthesizer = synthesizer
        # raw [T, E, Q] results keyed by _program_key; entries are
        # write-locked numpy arrays shared across callers.  The lock
        # guards the OrderedDict + hit/miss counters only — synthesis and
        # prediction always run OUTSIDE it.
        self._raw_cache: OrderedDict[str, np.ndarray] = OrderedDict()
        self._raw_lock = threading.Lock()
        self.raw_cache_hits = 0
        self.raw_cache_misses = 0

    @property
    def endpoints(self) -> list[str]:
        return self.synthesizer.endpoints

    def _is_relative(self, e: int) -> bool:
        dm = self.predictor.delta_mask
        return dm is not None and bool(dm[e])

    def _bands(self, preds: np.ndarray) -> dict[str, dict[str, np.ndarray]]:
        """[T, E, Q] predictions → {metric: {"qNN": [T] series}}."""
        quantiles = self.predictor.quantiles
        return {
            metric: {
                f"q{int(q * 100):02d}": preds[:, e, qi]
                for qi, q in enumerate(quantiles)
            }
            for e, metric in enumerate(self.predictor.metric_names)
        }

    def estimate(
        self,
        expected_traffic: list[dict[str, int]],
        seed: int = 0,
    ) -> dict[str, dict[str, np.ndarray]]:
        """``expected_traffic[t] = {endpoint: count}`` → per-metric series.

        Returns ``{metric: {"q05"|"q50"|"q95": [T] utilization}}`` (keys
        follow the configured quantiles).  Delta-trained metrics
        (``predictor.delta_mask``, e.g. disk usage) come back as RELATIVE
        growth from the start of the hypothetical program — there is no
        observed level to anchor a what-if to; the reference demo
        re-anchors exactly these series before display
        (web-demo/dataloader.py:143-156).
        """
        return self.estimate_many([expected_traffic], seeds=[seed])[0]

    def estimate_many(
        self,
        traffic_programs: list[list[dict[str, int]]],
        seed: int = 0,
        seeds: list[int] | None = None,
    ) -> list[dict[str, dict[str, np.ndarray]]]:
        """Batched multi-scenario estimation: S traffic programs (of
        possibly different lengths) → S per-metric band dicts, one
        prediction train.

        All scenarios synthesize on host, then fold into the predictor's
        fused scenario×window batch axis (``predict_series_many``): the
        delta-integration carry resets per scenario, pages are shared
        across scenarios, and no new executables compile for any S.
        ``seeds`` pins each scenario's synthesis seed (defaults to
        ``seed + i`` — scenario i of a sweep is reproducible regardless
        of batch composition).
        """
        raws = self.estimate_many_raw(traffic_programs, seed=seed,
                                      seeds=seeds)
        return [self._bands(p) for p in raws]

    def estimate_many_raw(
        self,
        traffic_programs: list[list[dict[str, int]]],
        seed: int = 0,
        seeds: list[int] | None = None,
        cache: bool = True,
    ) -> list[np.ndarray]:
        """Like :meth:`estimate_many` but returns the raw ``[T, E, Q]``
        prediction arrays (read-only) instead of band dicts — the shape
        the capacity-surface plane stacks into interpolation grids.

        With ``cache=True`` (default), each (program, seed) result is
        memoized in a per-estimator LRU: repeated baselines across
        ``scaling_factor``/``sweep`` calls cost one prediction train
        total.  Only the MISSES synthesize and fold into the device
        batch; a fully-cached call does no dispatch at all.  Surface
        builds pass ``cache=False`` — their thousands of vertices are
        stored once in the surface itself and would only churn this LRU.
        """
        if seeds is None:
            seeds = [seed + i for i in range(len(traffic_programs))]
        if len(seeds) != len(traffic_programs):
            raise ValueError(
                f"{len(seeds)} seeds for {len(traffic_programs)} programs")
        n = len(traffic_programs)
        out: list[np.ndarray | None] = [None] * n
        miss_idx = list(range(n))
        keys: list[str] | None = None
        if cache:
            keys = [_program_key(p, s)
                    for p, s in zip(traffic_programs, seeds)]
            miss_idx = []
            with self._raw_lock:
                for i, k in enumerate(keys):
                    hit = self._raw_cache.get(k)
                    if hit is not None:
                        self._raw_cache.move_to_end(k)
                        self.raw_cache_hits += 1
                        out[i] = hit
                    else:
                        self.raw_cache_misses += 1
                        miss_idx.append(i)
        if miss_idx:
            series = [
                self.synthesizer.synthesize_series(
                    traffic_programs[i], seed=seeds[i])
                for i in miss_idx
            ]
            many = getattr(self.predictor, "predict_series_many", None)
            if many is not None:
                preds = many(series)
            else:
                preds = [self.predictor.predict_series(x) for x in series]
            for i, p in zip(miss_idx, preds):
                # graftlint: disable=JX003 -- designed sink: the memo stores host numpy; this is the one materialization point
                arr = np.asarray(p, dtype=np.float32)
                # shared across future cache hits: freeze so no caller
                # can corrupt another's result
                arr.setflags(write=False)
                out[i] = arr
            if cache:
                with self._raw_lock:
                    for i in miss_idx:
                        # concurrent misses of the same key both insert;
                        # values are deterministic, so last-wins is fine
                        self._raw_cache[keys[i]] = out[i]
                        self._raw_cache.move_to_end(keys[i])
                    while len(self._raw_cache) > _RAW_CACHE_MAX:
                        self._raw_cache.popitem(last=False)
        return out

    def sweep(
        self,
        base_traffic: list[dict[str, int]],
        factors: list[float],
        seed: int = 0,
    ) -> list[dict]:
        """Capacity-sweep grid: scale ``base_traffic`` by each factor and
        estimate all scaled programs in ONE batched prediction train.

        Returns one record per factor:
        ``{"factor": f, "peaks": {metric: {"qNN": peak}}}``
        where delta-trained (relative) metrics report peak GROWTH over the
        program (peak minus start — the demo's post-re-anchor semantics)
        and absolute metrics report the plain peak.
        """
        if not factors:
            raise ValueError("sweep requires at least one factor")
        programs = [
            [{ep: int(round(n * f)) for ep, n in step.items()}
             for step in base_traffic]
            for f in factors
        ]
        results = self.estimate_many(programs, seed=seed)
        out = []
        for f, bands in zip(factors, results):
            peaks: dict[str, dict[str, float]] = {}
            for e, metric in enumerate(self.predictor.metric_names):
                per_q = {}
                for q, series in bands[metric].items():
                    if self._is_relative(e):
                        # graftlint: disable=JX003 -- host data: estimate_many already materialized the bands to numpy
                        per_q[q] = max(float(np.max(series) - series[0]), 0.0)
                    else:
                        # graftlint: disable=JX003 -- host data: same materialized numpy bands
                        per_q[q] = float(np.max(series))
                peaks[metric] = per_q
            # graftlint: disable=JX003 -- host data: f is a python float from the factors argument
            out.append({"factor": float(f), "peaks": peaks})
        return out

    def scaling_factor(
        self,
        baseline_traffic: list[dict[str, int]],
        hypothetical_traffic: list[dict[str, int]],
        seed: int = 0,
    ) -> dict[str, float]:
        """Per-metric peak scaling factor between two traffic programs
        (the number the reference demo renders as bar charts,
        web-demo/dataloader.py:143-156).  For delta-trained level metrics
        the factor compares GROWTH over the program (peak minus start) —
        the reference demo's own post-re-anchor semantics; a peak ratio on
        a relative-from-zero rollout would be meaningless.

        Both programs fold into one batched prediction train through
        ``estimate_many`` (shared fused pages — this replaced the earlier
        two-thread MicroBatcher workaround), and the per-estimator memo
        means a repeated baseline (every demo interaction re-compares
        against "today's traffic") is estimated once per snapshot, not
        once per call.  Degenerate peaks follow one
        convention for BOTH metric kinds: zero baseline and zero
        hypothetical means "no change" (1.0); zero baseline with real
        hypothetical load is unbounded (inf) — previously absolute metrics
        leaked inf into bar charts even when both peaks were zero.
        """
        base, hypo = self.estimate_many(
            [baseline_traffic, hypothetical_traffic],
            seeds=[seed, seed + 1])
        factors = {}
        for e, metric in enumerate(self.predictor.metric_names):
            bs, hs = base[metric]["q50"], hypo[metric]["q50"]
            if self._is_relative(e):
                # Growth can legitimately be ~0 (a program driving no
                # writes): clamp at 0 and define 0-growth/0-growth as 1.0
                # (no change) instead of letting inf leak into bar charts.
                # graftlint: disable=JX003 -- host data: estimate_many already materialized q50 to numpy
                b = max(float(np.max(bs) - bs[0]), 0.0)
                # graftlint: disable=JX003 -- host data: same materialized numpy series
                h = max(float(np.max(hs) - hs[0]), 0.0)
                factors[metric] = (h / b if b > 0
                                   else (1.0 if h == 0 else float("inf")))
            else:
                # graftlint: disable=JX003 -- host data: estimate_many already materialized q50 to numpy
                b = float(np.max(bs))
                # graftlint: disable=JX003 -- host data: same materialized numpy series
                h = float(np.max(hs))
                factors[metric] = (h / b if b > 0
                                   else (1.0 if h <= 0 else float("inf")))
        return factors
