"""Fleet tier: M tenant applications on one serving plane.

The paper's deployment story is many applications, each with its own
trace corpus and trained model; production means hundreds of tenants
behind one mesh (Clipper multiplexes models behind one interface —
PAPERS.md [2] — ours multiplexes *applications*).  The scaling hazard is
never the weights — a tenant's params tree is a few MB — it is the
EXECUTABLES: a naive plane jit-compiles a fresh shape ladder per tenant,
so HBM and compile time grow linearly in M.  This module pins both flat:

:class:`PredictorPool`
    Tenant → predictor entries keyed by ``(checkpoint_path,
    params_digest, quant)``, with three storage tiers:

    - **device-resident** — up to ``hbm_budget`` tenants' params live in
      HBM, managed as an LRU on the request path (``resolve``);
    - **host spill** — evicted tenants' weights are copied to host
      memory (pinned staging buffers on a TPU runtime; plain host numpy
      on CPU) and restored by ``jax.device_put`` on next touch — never a
      disk read and never a compile (executables key by shape, not by
      params);
    - **disk** — the checkpoint itself, the third tier, touched only at
      admission.

    Every admitted predictor adopts the pool template's compiled
    executables (``Predictor.share_executables_from``): params and
    normalization stats are runtime arguments throughout, so ONE fused
    ladder serves every tenant and ``jit_cache_size`` stays flat in M.
    Admission is a *deserialize* when AOT artifacts ride next to the
    checkpoint (serve/aot.py), with a loud compile-fallback counter when
    they don't.

Eviction never breaks an in-flight request: a spill REPLACES the
predictor's device params with the host copy (same bytes), so a request
that resolved the entry before the eviction keeps computing bit-exact
results — the device buffers free when the last in-flight reference
drops, and the next ``resolve`` re-stages the host copy with one
``device_put``.

Pool-entry accessor discipline (graftlint TN001): every per-tenant
mutable object — device params, host spill, the per-tenant
QualityMonitor, the reason-labeled invalidation counters — lives on
:class:`PoolEntry` attributes named ``_tenant_*`` and is reached ONLY
through the entry's accessor methods.  Outside ``serve/fleet.py``, any
``._tenant_*`` attribute access in ``serve/`` fires TN001 at the access
site: per-tenant state touched off the accessor path is how one
tenant's reload bleeds into another's responses.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

from deeprest_tpu.obs import spans as obs_spans


class UnknownTenantError(KeyError):
    """Raised by ``resolve``/``peek`` for a tenant never admitted to the
    pool — the HTTP layer maps this to a 404, never to a silent
    fall-through onto another tenant's model."""


class PoolEntry:
    """One tenant's serving state.  Mutable per-tenant objects live on
    ``_tenant_*`` attributes (TN001 discipline, module docstring) and
    are reached through the accessors below."""

    def __init__(self, tenant: str, key: tuple, predictor, quality=None):
        self.tenant = tenant
        self.key = key                       # (ckpt_path, digest, quant)
        self.resident = True
        self.spills = 0
        self.restores = 0
        self.served = 0
        self._tenant_predictor = predictor
        self._tenant_spill = None            # host params tree when spilled
        self._tenant_quality = quality
        self._tenant_invalidations: dict[str, int] = {}

    # -- accessors (the only sanctioned read path — TN001) ---------------

    def predictor(self):
        """The tenant's serving backend (device-resident params when the
        entry is resident; host-staged but still correct mid-eviction)."""
        return self._tenant_predictor

    def quality(self):
        """The tenant's QualityMonitor, or None when the pool was built
        without per-tenant quality."""
        return self._tenant_quality

    def invalidations(self) -> dict[str, int]:
        """Reason → count of this tenant's weight-swap invalidations."""
        return dict(self._tenant_invalidations)

    def note_invalidation(self, reason: str) -> None:
        self._tenant_invalidations[reason] = (
            self._tenant_invalidations.get(reason, 0) + 1)


class PredictorPool:
    """Checkpoint-keyed predictor pool with an HBM-resident LRU, host
    spill, one shared executable set, and AOT load-or-compile admission
    (module docstring).

    ``quality_config`` (a QualityConfig with ``enabled=True``) attaches
    one QualityMonitor per pool entry, each with a PRIVATE metrics
    registry — the process registry keeps exactly one binding per gauge
    name, so per-tenant gauges render through the serving collector
    (server.py) with a ``tenant`` label and top-K + ``__other__``
    cardinality bounding instead.
    """

    def __init__(self, hbm_budget: int = 4, aot: bool = True,
                 quality_config=None, top_k_tenants: int = 8,
                 default_tenant: str = "default"):
        if hbm_budget < 1:
            raise ValueError(f"hbm_budget {hbm_budget} must be >= 1")
        if top_k_tenants < 1:
            raise ValueError(f"top_k_tenants {top_k_tenants} must be >= 1")
        self.hbm_budget = int(hbm_budget)
        self.aot = bool(aot)
        self.top_k_tenants = int(top_k_tenants)
        self.default_tenant = str(default_tenant)
        self._quality_config = (quality_config
                                if quality_config is not None
                                and getattr(quality_config, "enabled", False)
                                else None)
        # Guards the LRU order, entry residency, and the ledger below.
        # Restores (device_put) run under the lock — rare by design (the
        # budget exists so the working set stays resident) and bounded by
        # one host→device weight transfer; device DISPATCH never runs
        # under it (callers get the entry and predict outside).
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, PoolEntry]" = \
            collections.OrderedDict()
        # The executable holder: the first admitted predictor.  Later
        # admissions adopt its compiled programs; it stays referenced
        # even if its tenant is evicted or reloaded away, because the
        # jitted callables (and their executable caches) live on it.
        self._template = None
        self._frozen_cache: int | None = None
        self.admissions = 0
        self.hits = 0
        self.unknown_tenants = 0
        self.spill_count = 0
        self.restore_count = 0
        self.evictions = 0
        self.aot_loaded = 0
        self.aot_bytes = 0
        self.compile_fallbacks = 0
        self.aot_last_reason = None

    # -- admission --------------------------------------------------------

    def admit(self, tenant: str, predictor, checkpoint_path: str = "") \
            -> PoolEntry:
        """Admit a tenant's predictor.  First admission makes it the
        plane's executable template and runs AOT load-or-compile from
        ``checkpoint_path`` (serve/aot.py); later admissions adopt the
        template's executables and load nothing — the artifacts were
        already installed into the SHARED AOT dispatch table."""
        key = (str(checkpoint_path), predictor.params_digest(),
               getattr(predictor, "quant", "off"))
        with self._lock:
            if tenant in self._entries:
                raise ValueError(
                    f"tenant {tenant!r} already admitted; use reload() "
                    "for a weight hot-swap")
            with obs_spans.RECORDER.span("fleet.admit",
                                         component="deeprest-fleet") as sp:
                sp.tag(tenant=tenant, quant=key[2])
                if self._template is None:
                    self._template = predictor
                    if self.aot and checkpoint_path:
                        from deeprest_tpu.serve.aot import load_aot

                        res = load_aot(predictor, checkpoint_path)
                        self.aot_loaded += res["loaded"]
                        self.aot_bytes += res["bytes"]
                        self.compile_fallbacks += len(res["fallback_rungs"])
                        self.aot_last_reason = res["reason"]
                        sp.tag(aot_loaded=res["loaded"],
                               aot_fallbacks=len(res["fallback_rungs"]))
                    elif self.aot:
                        self.aot_last_reason = "no checkpoint_path"
                else:
                    predictor.share_executables_from(self._template)
                quality = None
                if self._quality_config is not None:
                    from deeprest_tpu.obs import metrics as obs_metrics
                    from deeprest_tpu.obs.quality import QualityMonitor

                    quality = QualityMonitor(
                        predictor.metric_names,
                        config=self._quality_config,
                        registry=obs_metrics.MetricsRegistry())
                entry = PoolEntry(tenant, key, predictor, quality)
                self._entries[tenant] = entry
                self.admissions += 1
                self._evict_over_budget_locked(keep=entry)
        return entry

    # -- the request path -------------------------------------------------

    def resolve(self, tenant: str | None) -> PoolEntry:
        """Tenant → pool entry, on the dispatch path: LRU touch, restore
        from host spill if evicted (one ``device_put`` per leaf — no
        disk, no compile), and the serve counter.  ``None`` resolves to
        the pool's default tenant."""
        t = tenant if tenant is not None else self.default_tenant
        with self._lock:
            entry = self._entries.get(t)
            if entry is None:
                self.unknown_tenants += 1
                raise UnknownTenantError(t)
            self._entries.move_to_end(t)
            entry.served += 1
            self.hits += 1
            if not entry.resident:
                self._restore_locked(entry)
                self._evict_over_budget_locked(keep=entry)
        return entry

    def peek(self, tenant: str | None) -> PoolEntry:
        """Read-only entry lookup: no LRU touch, no restore, no counters
        — for metadata paths (verdicts, response metric names) that must
        not perturb the eviction order the dispatch path maintains."""
        t = tenant if tenant is not None else self.default_tenant
        with self._lock:
            entry = self._entries.get(t)
        if entry is None:
            raise UnknownTenantError(t)
        return entry

    # -- weight hot-swap --------------------------------------------------

    def reload(self, tenant: str, fresh, reason: str = "manual") \
            -> PoolEntry:
        """Per-tenant weight hot-swap.  The swap is one reference
        assignment under the pool lock: requests in flight finish on the
        predictor they resolved (old params stay alive on their stack —
        the same no-mixed-params guarantee the router's
        ``rolling_reload_from`` gives the shared backend), and every
        later ``resolve`` serves the fresh weights.  ``reason`` labels
        the tenant's invalidation counter end to end — the per-tenant
        twin of the surface store's reason-labeled invalidation (the
        ``(params_hash, mix-space-hash)`` surface key already isolates
        tenants, so one tenant's reload never blinds another's
        surfaces)."""
        with self._lock:
            entry = self._entries.get(tenant)
            if entry is None:
                raise UnknownTenantError(tenant)
            with obs_spans.RECORDER.span("fleet.reload",
                                         component="deeprest-fleet") as sp:
                sp.tag(tenant=tenant, reason=reason)
                if self._template is not None and fresh is not self._template:
                    fresh.share_executables_from(self._template)
                entry.key = (entry.key[0], fresh.params_digest(),
                             getattr(fresh, "quant", "off"))
                entry._tenant_predictor = fresh
                entry._tenant_spill = None
                entry.resident = True
                entry.note_invalidation(reason)
                quality = entry._tenant_quality
                self._evict_over_budget_locked(keep=entry)
        if quality is not None:
            quality.on_model_refresh()
        return entry

    # -- LRU / spill / restore (callers hold self._lock) ------------------

    def _resident_locked(self):
        return [e for e in self._entries.values() if e.resident]

    def _evict_over_budget_locked(self, keep: PoolEntry | None = None):
        resident = self._resident_locked()
        while len(resident) > self.hbm_budget:
            victim = next((e for e in resident if e is not keep), None)
            if victim is None:       # budget 0-vs-keep degenerate: keep wins
                break
            self._spill_locked(victim)
            self.evictions += 1
            resident = self._resident_locked()

    def _spill_locked(self, entry: PoolEntry) -> None:
        """Device → host: copy every params leaf to a host-owned buffer
        and point the predictor at the host tree.  Same bytes, so any
        in-flight request stays bit-exact (jax re-stages host args per
        dispatch); the device buffers free when the last in-flight
        reference drops."""
        import jax

        pred = entry._tenant_predictor
        with obs_spans.RECORDER.span("fleet.spill",
                                     component="deeprest-fleet") as sp:
            sp.tag(tenant=entry.tenant)
            # graftlint: disable=JX003 -- designed sink: spilling IS the device->host copy
            host = jax.tree_util.tree_map(
                lambda leaf: np.array(np.asarray(leaf), copy=True),
                pred.params)
        entry._tenant_spill = host
        pred.params = host
        if pred.fused is not None:
            pred.fused._params = host
        entry.resident = False
        entry.spills += 1
        self.spill_count += 1

    def _restore_locked(self, entry: PoolEntry) -> None:
        """Host → device: one ``device_put`` per leaf from the spill
        copy.  Never a disk read, never a compile — the executables key
        by shape/mode, and the restored tree has the exact avals the
        ladder was compiled for."""
        import jax

        pred = entry._tenant_predictor
        with obs_spans.RECORDER.span("fleet.restore",
                                     component="deeprest-fleet") as sp:
            sp.tag(tenant=entry.tenant)
            dev = jax.tree_util.tree_map(jax.device_put,
                                         entry._tenant_spill)
        pred.params = dev
        if pred.fused is not None:
            pred.fused._params = dev
        entry._tenant_spill = None
        entry.resident = True
        entry.restores += 1
        self.restore_count += 1

    # -- executable ledger ------------------------------------------------

    def _jit_cache_size_locked(self) -> int | None:
        tmpl = self._template
        return tmpl.jit_cache_size() if tmpl is not None else None

    def jit_cache_size(self) -> int | None:
        """The plane-wide compiled-executable count — every tenant shares
        the template's programs, so any entry reports the same number;
        this reads the template's."""
        with self._lock:
            return self._jit_cache_size_locked()

    def freeze(self) -> int | None:
        """Pin the current executable count as the post-warmup ceiling.
        After this, ``assert_frozen`` (and the fleet bench's ledger
        gate) treats ANY growth as a per-tenant compile leak."""
        with self._lock:
            self._frozen_cache = self._jit_cache_size_locked()
            return self._frozen_cache

    def assert_frozen(self) -> int | None:
        with self._lock:
            now = self._jit_cache_size_locked()
            frozen = self._frozen_cache
        if frozen is not None and now is not None and now > frozen:
            raise RuntimeError(
                f"jit cache grew post-freeze: {frozen} -> {now} — a "
                "tenant dispatch compiled a new executable (per-tenant "
                "executables are exactly what the fleet tier exists to "
                "prevent)")
        return now

    # -- observability ----------------------------------------------------

    def tenant_meta(self, limit: int | None = None) -> dict:
        """Per-tenant ``{quant, params_digest, resident}`` map (the
        /healthz ``fleet.tenants`` view; satellite: the boot handshake's
        single global quant/params_digest grown to a per-tenant map).
        ``limit`` bounds the map to the top-N by serve count with the
        remainder rolled into ``__other__`` counts."""
        with self._lock:
            entries = list(self._entries.values())
        entries.sort(key=lambda e: e.served, reverse=True)
        cut = entries if limit is None else entries[:limit]
        out = {e.tenant: {"quant": e.key[2], "params_digest": e.key[1],
                          "resident": e.resident} for e in cut}
        rest = entries[len(cut):]
        if rest:
            out["__other__"] = {
                "tenants": len(rest),
                "resident": sum(e.resident for e in rest),
            }
        return out

    def stats(self) -> dict:
        with self._lock:
            entries = list(self._entries.values())
            resident = sum(e.resident for e in entries)
            per_tenant = {
                e.tenant: {
                    "resident": e.resident,
                    "served": e.served,
                    "spills": e.spills,
                    "restores": e.restores,
                    "invalidations": e.invalidations(),
                }
                for e in sorted(entries, key=lambda e: e.served,
                                reverse=True)[:self.top_k_tenants]
            }
            return {
                "hbm_budget": self.hbm_budget,
                "tenants": len(entries),
                "resident": resident,
                "spilled": len(entries) - resident,
                "admissions": self.admissions,
                "hits": self.hits,
                "unknown_tenants": self.unknown_tenants,
                "spills": self.spill_count,
                "restores": self.restore_count,
                "evictions": self.evictions,
                "aot": {
                    "enabled": self.aot,
                    "loaded": self.aot_loaded,
                    "bytes": self.aot_bytes,
                    "compile_fallbacks": self.compile_fallbacks,
                    "last_reason": self.aot_last_reason,
                },
                "jit_cache_size": self._jit_cache_size_locked(),
                "frozen": self._frozen_cache is not None,
                "frozen_cache_size": self._frozen_cache,
                "per_tenant": per_tenant,
            }

    def quality_rollup(self) -> list[tuple[str, dict]]:
        """``(tenant_label, verdict_summary)`` rows for the /metrics
        collector: the top-K tenants by serve count get their own
        ``tenant`` label; everyone else aggregates under ``__other__``
        (worst state, max scores, summed sweeps) — per-tenant gauges
        with BOUNDED cardinality no matter how many apps share the
        plane."""
        with self._lock:
            entries = [e for e in self._entries.values()
                       if e.quality() is not None]
        entries.sort(key=lambda e: e.served, reverse=True)
        state_rank = {"ok": 0, "drift": 1, "anomaly": 2}

        def summarize(entry):
            v = entry.quality().verdicts()
            metrics = v.get("metrics", {})
            worst = max((state_rank.get(m.get("state"), 0)
                         for m in metrics.values()), default=0)
            scores = [m.get("anomaly_score") or 0.0
                      for m in metrics.values()]
            coverages = [m["coverage"] for m in metrics.values()
                         if isinstance(m, dict)
                         and m.get("coverage") is not None]
            pinballs = [m["pinball"] for m in metrics.values()
                        if isinstance(m, dict)
                        and m.get("pinball") is not None]
            return {
                "sweeps": v.get("sweeps", 0),
                "verdict": worst,
                "anomaly_score": max(scores, default=0.0),
                "coverage": (float(np.mean(coverages))
                             if coverages else None),
                "pinball": float(np.mean(pinballs)) if pinballs else None,
            }

        rows = [(e.tenant, summarize(e))
                for e in entries[:self.top_k_tenants]]
        rest = entries[self.top_k_tenants:]
        if rest:
            summaries = [summarize(e) for e in rest]
            rows.append(("__other__", {
                "sweeps": sum(s["sweeps"] for s in summaries),
                "verdict": max(s["verdict"] for s in summaries),
                "anomaly_score": max(s["anomaly_score"]
                                     for s in summaries),
                "coverage": None,
                "pinball": None,
            }))
        return rows

    def tenants(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
