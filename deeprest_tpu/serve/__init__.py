"""Serving: trained-model prediction, what-if estimation, anomaly detection."""

from deeprest_tpu.serve.predictor import Predictor
from deeprest_tpu.serve.whatif import WhatIfEstimator
from deeprest_tpu.serve.anomaly import AnomalyDetector, AnomalyReport

__all__ = ["Predictor", "WhatIfEstimator", "AnomalyDetector", "AnomalyReport"]
