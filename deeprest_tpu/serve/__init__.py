"""Serving: trained-model prediction, what-if estimation, anomaly detection,
the portable export artifact, the cross-request micro-batching engine,
precomputed capacity surfaces, and the HTTP prediction service."""

from deeprest_tpu.serve.batcher import (
    BatcherConfig, MicroBatcher, ShapeLadder,
)
from deeprest_tpu.serve.fused import FusedRolledEngine
from deeprest_tpu.serve.predictor import (
    Predictor, rolled_prediction, rolled_prediction_reference,
)
from deeprest_tpu.serve.surface import (
    CapacitySurface, CapacitySurfaceManager, MixSpace,
)
from deeprest_tpu.serve.whatif import WhatIfEstimator
from deeprest_tpu.serve.anomaly import AnomalyDetector, AnomalyReport
from deeprest_tpu.serve.export import ExportedPredictor, export_predictor
from deeprest_tpu.serve.server import (
    CheckpointReloader, PredictionServer, PredictionService, ServingError,
)
from deeprest_tpu.serve.replica import (
    EngineReplica, ProcessReplica, ReplicaDeadError, clone_backend,
)
from deeprest_tpu.serve.router import (
    AdmissionError, ReplicaRouter, RouterConfig,
)

__all__ = [
    "BatcherConfig",
    "MicroBatcher",
    "ShapeLadder",
    "FusedRolledEngine",
    "Predictor",
    "rolled_prediction",
    "rolled_prediction_reference",
    "CapacitySurface",
    "CapacitySurfaceManager",
    "MixSpace",
    "WhatIfEstimator",
    "AnomalyDetector",
    "AnomalyReport",
    "ExportedPredictor",
    "export_predictor",
    "CheckpointReloader",
    "PredictionServer",
    "PredictionService",
    "ServingError",
    "EngineReplica",
    "ProcessReplica",
    "ReplicaDeadError",
    "clone_backend",
    "AdmissionError",
    "ReplicaRouter",
    "RouterConfig",
]
