"""Engine replicas: N independent serving stacks behind one routing front.

One fused engine saturates around a single device's dispatch pipeline —
PERF.md's serving table stops at concurrency 16 on one
Predictor/MicroBatcher/FusedRolledEngine stack inside one process.  The
Clipper shape (PAPERS.md [2]) scales past that by replicating the model
container and putting batching/admission in a routing layer.  This module
is the replica half of that split; serve/router.py is the front.

Two replica kinds behind ONE interface (``predict_series``,
``predict_series_many``, ``outstanding``, ``drain``/``resume``/
``wait_idle``, ``reload_backend``, ``close``):

``EngineReplica``
    In-process: a full serving stack (Predictor or ExportedPredictor +
    shape ladder + fused rolled engine + optional per-stack MicroBatcher)
    pinned to one device via ``jax.default_device``.  Replicas that
    resolve to the SAME device (the virtual-CPU dev box, or more replicas
    than chips) SHARE one stack: executables are per-device, so a second
    replica on a device compiles nothing new — the scheduling state
    (outstanding-work counter, drain flag) stays per replica.

``ProcessReplica``
    A worker subprocess (``multiprocessing`` spawn context — fork after
    JAX initialization is unsafe) building its own stack from a spec
    (checkpoint dir, artifact dir, or a ``module:function`` factory) and
    serving requests over a duplex pipe.  The parent side multiplexes
    concurrent requests by id (send lock + one reader thread resolving
    futures); the child handles them on a small thread pool so its
    MicroBatcher still coalesces.  Process replicas sidestep the GIL and
    give each engine its own runtime — the deployment shape for one
    replica per host/chip.

The router never sees the difference: both kinds expose the same
outstanding-work signal its least-outstanding-work dispatch reads.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans


class ReplicaDeadError(RuntimeError):
    """A replica cannot answer this request: its worker died, its pipe
    broke, or it blew through the per-request deadline.

    ``retriable`` encodes the router's no-double-execution safety rule
    (serve/router.py): True only when the failure PROVES no response was
    or ever will be produced — the send never reached the worker, or the
    worker process is dead (its device state died with it, so the work
    cannot complete elsewhere-visibly).  A deadline expiry on a LIVE
    worker is retriable=False: the request may still be executing on the
    device, and re-dispatching it would double-execute — the router
    ejects the replica and answers a fast 503 instead.
    """

    def __init__(self, message: str, replica: str = "",
                 retriable: bool = False):
        super().__init__(message)
        self.replica = replica
        self.retriable = retriable


def _release_proc(proc) -> None:
    """Free a reaped worker's parent-side resources NOW (the Popen
    sentinel pipe fd otherwise lives until garbage collection — the
    chaos harness's post-storm fd census counts exactly such strays).
    No-op while the process is still running."""
    if proc is None or proc.is_alive():
        return
    try:
        proc.close()
    except ValueError:
        pass        # already closed / never started


def _num_windows(t: int, w: int) -> int:
    """Window count of a [T, F] series under the serving tiling (regular
    stride-W tiling + right-aligned ragged tail) — the router's
    outstanding-work unit."""
    if t < w:
        return 1
    n = (t - w) // w + 1
    return n + (1 if (t - w) % w != 0 else 0)


def clone_backend(backend, device=None, **overrides):
    """A fresh serving stack sharing ``backend``'s restored state.

    Params/stats/metadata are shared (device_put onto ``device`` when one
    is given); ladders, fused engines, and jit wrappers are NEW — each
    clone compiles for (and dispatches on) its own device.  Works for
    both in-process backends: Predictor (has ``params``) and
    ExportedPredictor (has the serialized module).
    """
    import jax

    if hasattr(backend, "params"):           # in-process Predictor
        from deeprest_tpu.serve.predictor import Predictor

        params = backend.params
        if device is not None:
            params = jax.device_put(params, device)
        kwargs = dict(
            ladder=backend.ladder.base_ladder,
            coalesce_groups=backend.ladder.coalesce_groups,
            fused=backend.fused is not None,
            page_windows=(backend.fused.page
                          if backend.fused is not None else None),
            coalesce_pages=(backend.fused.coalesce_pages
                            if backend.fused is not None else None),
        )
        kwargs.update(overrides)
        return Predictor(
            params=params,
            model_config=backend.model_config,
            x_stats=backend.x_stats,
            y_stats=backend.y_stats,
            metric_names=backend.metric_names,
            window_size=backend.window_size,
            space_dict=backend.space_dict,
            delta_mask=backend.delta_mask,
            **kwargs,
        )
    if hasattr(backend, "_exported"):        # exported artifact
        from deeprest_tpu.serve.export import ExportedPredictor

        kwargs = dict(
            ladder=backend.ladder.base_ladder,
            coalesce_groups=backend.ladder.coalesce_groups,
            fused=backend.fused is not None,
            page_windows=(backend.fused.page
                          if backend.fused is not None else None),
            coalesce_pages=(backend.fused.coalesce_pages
                            if backend.fused is not None else None),
        )
        kwargs.update(overrides)
        return ExportedPredictor(backend._exported, backend.manifest,
                                 **kwargs)
    raise TypeError(f"cannot clone serving backend {type(backend).__name__}")


class EngineReplica:
    """One in-process serving stack + the per-replica scheduling state the
    router reads (outstanding windows, drain flag).

    ``backend`` may be SHARED with other replicas pinned to the same
    device (executables are per-device; see module docstring) — the
    router's rolling reload groups such replicas and swaps their shared
    stack once, after draining all of them.
    """

    kind = "thread"

    def __init__(self, backend, name: str = "r0", device=None,
                 batching=None):
        from deeprest_tpu.serve.batcher import MicroBatcher

        self.name = name
        self.device = device
        # Guards every mutable field below: the ThreadingHTTPServer front
        # calls replicas from concurrent handler threads while the router
        # reads outstanding counters and the reload path flips the drain
        # flag (graftlint TH001 discipline).
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._backend = backend
        self._outstanding = 0          # windows currently dispatched here
        # Served totals are obs Counters (per-instance objects): the
        # stats() JSON, the router's /metrics collector, and the
        # autoscaler's demand read all consume the SAME objects.
        self._m_served_requests = obs_metrics.Counter(
            "deeprest_replica_served_requests_total",
            labelnames=("replica",))
        self._m_served_windows = obs_metrics.Counter(
            "deeprest_replica_served_windows_total",
            labelnames=("replica",))
        self._draining = False
        self._closed = False
        self._batching = batching
        if batching is not None and backend.batcher is None:
            backend.attach_batcher(MicroBatcher(backend.ladder, batching))

    # -- scheduling signal (read by the router's dispatch loop) ----------

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def available(self) -> bool:
        with self._lock:
            return not (self._draining or self._closed)

    @property
    def window_size(self) -> int:
        with self._lock:
            return self._backend.window_size

    def backend(self):
        with self._lock:
            return self._backend

    # -- serving ---------------------------------------------------------

    def _begin(self, windows: int):
        with self._lock:
            if self._closed:
                raise RuntimeError(f"replica {self.name} is closed")
            self._outstanding += windows
        return windows

    def _end(self, windows: int, requests: int = 1) -> None:
        with self._cv:
            self._outstanding -= windows
            self._cv.notify_all()      # wake wait_idle() drains
        self._m_served_requests.inc(requests, replica=self.name)
        self._m_served_windows.inc(windows, replica=self.name)

    def served_requests(self) -> int:
        return int(self._m_served_requests.value(replica=self.name))

    def served_windows(self) -> int:
        return int(self._m_served_windows.value(replica=self.name))

    def predict_series(self, traffic: np.ndarray,
                       integrate: bool = True, backend=None) -> np.ndarray:
        # ``backend`` override: the fleet tier (serve/fleet.py) resolves
        # tenant → pool-entry predictor BEFORE dispatch and serves this
        # one request through it — the replica still owns the scheduling
        # state (outstanding windows, drain flag), the pool owns the
        # per-tenant weights.  None keeps the replica's own stack.
        if backend is None:
            with self._lock:
                backend = self._backend
        n = self._begin(_num_windows(len(traffic), backend.window_size))
        try:
            with _device_ctx(self.device), \
                    obs_spans.RECORDER.span(
                        "replica.predict",
                        component="deeprest-replica") as sp:
                sp.tag(replica=self.name, windows=n)
                return backend.predict_series(traffic, integrate=integrate)
        finally:
            self._end(n)

    def predict_series_many(self, series_list, integrate: bool = True,
                            backend=None):
        if backend is None:
            with self._lock:
                backend = self._backend
        series_list = list(series_list)
        n = self._begin(sum(_num_windows(len(s), backend.window_size)
                            for s in series_list))
        try:
            with _device_ctx(self.device), \
                    obs_spans.RECORDER.span(
                        "replica.predict",
                        component="deeprest-replica") as sp:
                sp.tag(replica=self.name, windows=n,
                       series=len(series_list))
                return backend.predict_series_many(series_list,
                                                   integrate=integrate)
        finally:
            self._end(n, requests=len(series_list))

    # -- lifecycle (the router's rolling-reload path) --------------------

    def drain(self) -> None:
        """Stop receiving router dispatches (in-flight work finishes)."""
        with self._lock:
            self._draining = True

    def resume(self) -> None:
        with self._lock:
            self._draining = False

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until every dispatched window has completed."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
            return True

    def reload_backend(self, fresh) -> None:
        """Swap in a drained-and-rebuilt stack.  The caller (router) has
        already drained this replica, so no request straddles the swap —
        the no-mixed-params guarantee is structural."""
        from deeprest_tpu.serve.batcher import MicroBatcher

        with self._lock:
            # ONE critical section from the batching/backend read to the
            # publish (graftrace RC003): two concurrent reloads — or a
            # reload racing set_batching — would otherwise both read the
            # same `old`, and the loser's published stack (batcher and
            # all) retires silently, never detached or closed.  The
            # MicroBatcher built here touches only the unpublished
            # `fresh`, so holding the lock across it cannot invert
            # lock order.
            batching = self._batching
            old = self._backend
            if batching is not None and fresh.batcher is None:
                fresh.attach_batcher(MicroBatcher(fresh.ladder, batching))
            self._backend = fresh
        old_b = old.batcher
        if old_b is not None and old_b is not fresh.batcher:
            old.attach_batcher(None)
            old_b.close()

    def set_batching(self, config) -> None:
        """(Re)attach a per-stack MicroBatcher (None detaches)."""
        from deeprest_tpu.serve.batcher import MicroBatcher

        with self._lock:
            backend = self._backend
            self._batching = config
        old = backend.batcher
        fresh = (MicroBatcher(backend.ladder, config)
                 if config is not None else None)
        backend.attach_batcher(fresh)
        if old is not None:
            old.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._draining = True
            backend = self._backend
        b = backend.batcher
        if b is not None:
            backend.attach_batcher(None)
            b.close()

    def stats(self) -> dict:
        with self._lock:
            out = {
                "name": self.name,
                "kind": self.kind,
                "device": str(self.device) if self.device is not None else None,
                "outstanding_windows": self._outstanding,
                "served_requests": self.served_requests(),
                "served_windows": self.served_windows(),
                "state": ("closed" if self._closed
                          else "draining" if self._draining else "live"),
            }
            backend = self._backend
        b = backend.batcher
        if b is not None:
            out["batcher"] = b.stats()
        cache = getattr(backend, "jit_cache_size", None)
        if callable(cache):
            out["jit_cache_size"] = cache()
        return out


def _device_ctx(device):
    """``jax.default_device`` ONLY when the replica's device differs from
    the process default: the default-device setting is part of the jit
    cache key, so entering the context for the device that is already the
    default would mint a second, bit-identical executable per program —
    exactly the waste the shared-stack plane avoids.  Committed params
    (clone_backend's device_put) pin Predictor dispatches regardless; the
    context covers uncommitted-input backends (exported artifacts)."""
    import jax

    if device is None:
        return contextlib.nullcontext()
    default = getattr(jax.config, "jax_default_device", None)
    if default is None:
        default = jax.devices()[0]
    if device == default:
        return contextlib.nullcontext()
    return jax.default_device(device)


# ---------------------------------------------------------------------------
# Worker-subprocess replicas


def _resolve_factory(path: str):
    import importlib

    mod, _, fn = path.partition(":")
    if not fn:
        raise ValueError(f"bad factory spec {path!r} (want 'module:function')")
    return getattr(importlib.import_module(mod), fn)


def build_backend_from_spec(spec: dict):
    """Child-side stack construction: checkpoint dir, artifact dir, or a
    ``module:function`` factory, with optional serving kwargs."""
    import sys

    for p in spec.get("sys_path", ()):     # test factories live off-package
        if p not in sys.path:
            sys.path.insert(0, p)
    kwargs = dict(spec.get("kwargs") or {})
    if spec.get("ckpt_dir"):
        from deeprest_tpu.serve.predictor import Predictor

        return Predictor.from_checkpoint(spec["ckpt_dir"], **kwargs)
    if spec.get("artifact"):
        from deeprest_tpu.serve.export import ExportedPredictor

        return ExportedPredictor.load(spec["artifact"], **kwargs)
    if spec.get("factory"):
        return _resolve_factory(spec["factory"])(**kwargs)
    raise ValueError(f"replica spec needs ckpt_dir, artifact, or factory: "
                     f"{sorted(spec)}")


def _worker_main(spec: dict, conn) -> None:
    """Subprocess entry: build the stack, then serve pipe requests on a
    small thread pool (so the in-child MicroBatcher still coalesces).

    Observability: with ``spec["obs"]`` the child enables its own span
    recorder, adopts the parent's propagated ``(trace_id, span_id)``
    context per request, and forwards its committed spans back over the
    SAME duplex pipe as ``"__spans__"``-tagged messages — the parent's
    reader ingests them into the process-default recorder, so a request's
    trace crosses the process boundary intact.
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    os.environ.setdefault("JAX_PLATFORMS", spec.get("jax_platform", "cpu"))
    obs_on = bool(spec.get("obs"))
    if obs_on:
        from deeprest_tpu import obs

        obs.configure(enabled=True)
    try:
        backend = build_backend_from_spec(spec)
        if spec.get("batching"):
            from deeprest_tpu.serve.batcher import BatcherConfig, MicroBatcher

            cfg = BatcherConfig(**spec["batching"])
            backend.attach_batcher(MicroBatcher(backend.ladder, cfg))
    except Exception as exc:   # surface the constructor error to the parent
        conn.send(("__boot__", False, f"{type(exc).__name__}: {exc}"))
        conn.close()
        return
    conn.send(("__boot__", True, {
        "window_size": backend.window_size,
        "metric_names": list(backend.metric_names),
        "feature_dim": backend.feature_dim,
        "quantiles": list(backend.quantiles),
        "median_index": backend.median_index(),
        "delta_mask": (np.asarray(backend.delta_mask, bool).tolist()
                       if backend.delta_mask is not None else None),
        # y_stats ride the handshake so the router can serve the full
        # AnomalyDetector protocol (scale floors for re-anchored/delta
        # metrics) — the streaming verdict surface sweeps THROUGH the
        # router, same as /v1/anomaly.
        "y_stats": (backend.y_stats.to_dict()
                    if getattr(backend, "y_stats", None) is not None
                    else None),
        # Per-tenant serving identity under a ``fleet`` key (ADDITIVE —
        # every existing handshake field keeps its shape).  A worker
        # subprocess serves exactly one stack, so its map has one entry,
        # but the SHAPE matches the pool's /healthz view: consumers read
        # fleet.tenants[t].{quant, params_digest} whether the plane is
        # one process worker or a hundred-tenant pool.
        "fleet": {"tenants": {"default": {
            "quant": getattr(backend, "quant", "off"),
            "params_digest": (backend.params_digest()
                              if callable(getattr(backend, "params_digest",
                                                  None)) else None),
        }}},
    }))
    send_lock = threading.Lock()

    def handle(req_id, method, args, ctx=None):
        token = obs_spans.set_context(ctx) if ctx is not None else None
        try:
            with obs_spans.RECORDER.span("replica.worker",
                                         component="deeprest-replica") as sp:
                if method == "predict_series":
                    traffic, integrate = args
                    sp.tag(method=method, windows=_num_windows(
                        len(traffic), backend.window_size))
                    out = backend.predict_series(traffic,
                                                 integrate=integrate)
                elif method == "predict_series_many":
                    series_list, integrate = args
                    sp.tag(method=method, series=len(series_list))
                    out = backend.predict_series_many(series_list,
                                                      integrate=integrate)
                else:
                    raise ValueError(f"unknown method {method!r}")
            with send_lock:
                conn.send((req_id, True, out))
        except Exception as exc:
            with send_lock:
                conn.send((req_id, False, f"{type(exc).__name__}: {exc}"))
        finally:
            if token is not None:
                obs_spans.set_context(None)
            if obs_on:
                batch = [r.to_dict() for r in obs_spans.RECORDER.drain()]
                if batch:
                    with send_lock:
                        conn.send(("__spans__", True, batch))

    try:
        with ThreadPoolExecutor(
                max_workers=int(spec.get("worker_threads", 4))) as pool:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    break              # parent went away: drain and exit
                if msg is None:        # shutdown sentinel
                    break
                pool.submit(handle, *msg)
    finally:
        # close the child's pipe end on EVERY exit path (a handler bug
        # escaping the pool must not strand the parent's reader thread
        # on a half-open pipe)
        conn.close()


class ProcessReplica:
    """Worker-subprocess replica behind the EngineReplica interface."""

    kind = "process"

    def __init__(self, spec: dict, name: str = "p0",
                 boot_timeout_s: float = 120.0,
                 request_timeout_s: float | None = None):
        from concurrent.futures import Future

        self.name = name
        self.device = None             # the child owns its device binding
        self.spec = dict(spec)
        # Per-request deadline (None = the historical indefinite wait).
        # Without it a worker that dies mid-request BETWEEN heartbeats
        # wedges its caller forever on the response future — the bug the
        # router's ejection path consumes as a typed ReplicaDeadError.
        self.request_timeout_s = request_timeout_s
        # The child mirrors the parent's span-recording state at boot
        # (an explicit spec["obs"] wins — tests pin both modes).
        self.spec.setdefault("obs", obs_spans.RECORDER.enabled)
        self.boot_timeout_s = boot_timeout_s
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._outstanding = 0
        self._m_served_requests = obs_metrics.Counter(
            "deeprest_replica_served_requests_total",
            labelnames=("replica",))
        self._m_served_windows = obs_metrics.Counter(
            "deeprest_replica_served_windows_total",
            labelnames=("replica",))
        self._draining = False
        self._closed = False
        self._next_id = 0
        self._futures: dict[int, Future] = {}
        # Dedicated send lock: a pipe send can block when the OS buffer
        # fills, and blocking while holding the bookkeeping lock would
        # stall the reader thread (which needs it per response) — the
        # classic duplex-pipe deadlock.
        self._send_lock = threading.Lock()
        self._conn = None
        self._proc = None
        self._meta = None
        self._boot()

    def _boot(self) -> None:
        """Spawn a worker and wait for its stack to come up.  Called from
        __init__ and from reload (restart-with-newest-checkpoint); the
        caller guarantees no requests are in flight."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")  # fork after jax init is unsafe
        conn, child = ctx.Pipe(duplex=True)
        proc = None
        try:
            proc = ctx.Process(target=_worker_main,
                               args=(self.spec, child), daemon=True)
            proc.start()
            child.close()
            if not conn.poll(self.boot_timeout_s):
                raise RuntimeError(
                    f"replica {self.name}: worker boot timed out")
            # recv itself can raise (EOFError when the worker dies after
            # start but before the handshake lands) — the except below
            # owns cleanup for EVERY failed-boot path, so no path leaks
            # a pipe end or a live subprocess (graftlint RS001)
            tag, ok, meta = conn.recv()
            if tag != "__boot__" or not ok:
                raise RuntimeError(f"replica {self.name}: worker failed "
                                   f"to boot: {meta}")
        except Exception:
            conn.close()
            child.close()
            if proc is not None and proc.pid is not None:
                if proc.is_alive():
                    proc.terminate()
                proc.join(timeout=5)
                _release_proc(proc)
            raise
        with self._lock:
            self._conn = conn
            self._proc = proc
            self._meta = meta
            self._next_id = 0
        reader = threading.Thread(target=self._read_loop, args=(conn,),
                                  daemon=True,
                                  name=f"replica-{self.name}-reader")
        reader.start()

    # -- parent-side metadata -------------------------------------------

    @property
    def window_size(self) -> int:
        with self._lock:       # a reload swaps self._meta
            return self._meta["window_size"]

    def fleet_meta(self) -> dict | None:
        """The worker's per-tenant serving identity from the boot
        handshake (``{"tenants": {name: {quant, params_digest}}}``) —
        the process-replica half of the /healthz ``fleet`` view."""
        with self._lock:
            meta = self._meta
        return meta.get("fleet") if meta is not None else None

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def available(self) -> bool:
        with self._lock:
            return not (self._draining or self._closed)

    def alive(self) -> bool:
        """Is the worker subprocess running?  The router's health layer
        reads this to pick between retry (dead ⇒ the request provably
        has no surviving execution) and eject-without-retry (alive but
        wedged ⇒ possible double-execution)."""
        with self._lock:
            proc = self._proc
        if proc is None:
            return False
        try:
            return proc.is_alive()
        except ValueError:
            return False       # reaped and released (close()/restart())

    # -- request multiplexing -------------------------------------------

    def _read_loop(self, conn) -> None:
        """Resolve response futures from ONE pipe generation; a reload
        swaps the pipe, and this loop exits on its EOF.  ``"__spans__"``
        messages are the worker's forwarded span batches — ingested into
        the parent's recorder, never a request response."""
        while True:
            try:
                req_id, ok, payload = conn.recv()
            except (EOFError, OSError):
                with self._lock:
                    stale = self._conn is not conn
                    pending = ([] if stale
                               else list(self._futures.values()))
                    if not stale:
                        self._futures.clear()
                for f in pending:
                    # Worker death proves no response will ever come and
                    # its device state died with it — retriable: the
                    # router may re-dispatch these to a survivor.
                    f.set_exception(ReplicaDeadError(
                        f"replica {self.name}: worker exited "
                        "mid-request", replica=self.name, retriable=True))
                return
            if req_id == "__spans__":
                if ok:
                    obs_spans.RECORDER.ingest(payload)
                continue
            with self._lock:
                fut = self._futures.pop(req_id, None)
            if fut is None:
                continue
            if ok:
                fut.set_result(payload)
            else:
                fut.set_exception(RuntimeError(payload))

    def _call(self, method: str, args, windows: int, requests: int = 1):
        from concurrent.futures import Future
        from concurrent.futures import TimeoutError as FutureTimeout

        fut = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError(f"replica {self.name} is closed")
            req_id = self._next_id
            self._next_id += 1
            self._futures[req_id] = fut
            self._outstanding += windows
            conn = self._conn
        try:
            # the propagated trace context rides in the request tuple, so
            # the child's spans join this request's trace
            ctx = obs_spans.current_context()
            try:
                with self._send_lock:
                    conn.send((req_id, method, args, ctx))
            except (OSError, BrokenPipeError, ValueError) as exc:
                # the request never reached the worker: provably safe to
                # re-dispatch on a survivor
                with self._lock:
                    self._futures.pop(req_id, None)
                raise ReplicaDeadError(
                    f"replica {self.name}: request send failed ({exc})",
                    replica=self.name, retriable=True) from exc
            try:
                out = fut.result(timeout=self.request_timeout_s)
            except FutureTimeout:
                # Deadline blown.  Withdraw the future so a late answer
                # is dropped (the reader treats unknown ids as stale).
                # Retriability hinges on worker liveness: a DEAD worker
                # cannot be mid-execution — safe to retry; a live one may
                # still be running the request on its device, so a retry
                # would double-execute (the router ejects + 503s).
                with self._lock:
                    self._futures.pop(req_id, None)
                dead = not self.alive()
                why = ("worker dead" if dead else
                       "worker alive — not retried, the request may "
                       "still be executing")
                raise ReplicaDeadError(
                    f"replica {self.name}: no response within "
                    f"{self.request_timeout_s:.3f}s ({why})",
                    replica=self.name, retriable=dead) from None
        finally:
            with self._cv:
                self._outstanding -= windows
                self._cv.notify_all()
            self._m_served_requests.inc(requests, replica=self.name)
            self._m_served_windows.inc(windows, replica=self.name)
        return out

    def served_requests(self) -> int:
        return int(self._m_served_requests.value(replica=self.name))

    def served_windows(self) -> int:
        return int(self._m_served_windows.value(replica=self.name))

    def predict_series(self, traffic: np.ndarray,
                       integrate: bool = True, backend=None) -> np.ndarray:
        if backend is not None:
            # The override would need the tenant's params INSIDE the
            # worker subprocess; shipping a params tree per request over
            # the pipe is exactly the weight traffic the pool's
            # device-resident LRU exists to avoid.
            raise ValueError(
                "fleet backend override is not supported on process "
                "replicas — serve the fleet tier over in-process "
                "(thread) replicas")
        traffic = np.ascontiguousarray(traffic, np.float32)
        n = _num_windows(len(traffic), self.window_size)
        return self._call("predict_series", (traffic, integrate), n)

    def predict_series_many(self, series_list, integrate: bool = True,
                            backend=None):
        if backend is not None:
            raise ValueError(
                "fleet backend override is not supported on process "
                "replicas — serve the fleet tier over in-process "
                "(thread) replicas")
        series_list = [np.ascontiguousarray(s, np.float32)
                       for s in series_list]
        n = sum(_num_windows(len(s), self.window_size)
                for s in series_list)
        return self._call("predict_series_many", (series_list, integrate), n,
                          requests=len(series_list))

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        with self._lock:
            self._draining = True

    def resume(self) -> None:
        with self._lock:
            self._draining = False

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
            return True

    def reload_backend(self, fresh) -> None:
        """Process replicas reload by restart: the worker rebuilds its
        stack from the spec — ``fresh`` is only the reload trigger, since
        the child loads the newest checkpoint step itself.  The caller
        (router) has drained this replica, so no request is in flight."""
        self.restart()

    def restart(self) -> None:
        """Reboot the worker: new process/pipe/reader generation from the
        same spec.  Works on a HEALTHY drained worker (rolling reload)
        and on a dead or wedged one (the router's probe-and-rejoin path
        after an ejection — a SIGKILLed worker reboots here).  Any
        requests still pending against the old generation fail with a
        retriable ReplicaDeadError first, so no caller is left holding a
        future the new worker will never answer."""
        with self._lock:
            if self._closed:
                raise RuntimeError(f"replica {self.name} is closed")
            old_conn, old_proc = self._conn, self._proc
            orphans = list(self._futures.values())
            self._futures.clear()
        for f in orphans:
            f.set_exception(ReplicaDeadError(
                f"replica {self.name}: worker restarted mid-request",
                replica=self.name, retriable=True))
        try:
            self._boot()               # new pipe/process/reader generation
        finally:
            # reap the old generation even when the fresh boot fails (the
            # router's probe will retry the restart; the dead worker and
            # its pipe end must not outlive this attempt)
            if old_conn is not None:
                try:
                    old_conn.send(None)
                except (OSError, BrokenPipeError):
                    pass
                old_conn.close()       # old reader exits on EOF
            if old_proc is not None:
                old_proc.join(timeout=10)
                if old_proc.is_alive():
                    old_proc.terminate()
                    old_proc.join(timeout=5)
                _release_proc(old_proc)

    def set_batching(self, config) -> None:
        """Batching lives inside the worker's own stack: record the knob
        in the spec — it applies at the next boot (reload), where
        ``_worker_main`` attaches the MicroBatcher."""
        with self._lock:
            if config is None:
                self.spec.pop("batching", None)
            else:
                self.spec["batching"] = {
                    "max_batch": config.max_batch,
                    "max_linger_s": config.max_linger_s,
                    "max_queue": config.max_queue,
                }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._draining = True
            conn, proc = getattr(self, "_conn", None), getattr(
                self, "_proc", None)
        if conn is not None:
            try:
                conn.send(None)
            except (OSError, BrokenPipeError):
                pass
            conn.close()
        if proc is not None:
            proc.join(timeout=10)
            if proc.is_alive():
                # a handler may still be mid-predict (the shutdown
                # sentinel only stops the recv loop); reap the SIGTERM
                # so close() returns with the worker actually gone
                proc.terminate()
                proc.join(timeout=5)
            _release_proc(proc)

    def stats(self) -> dict:
        with self._lock:
            try:
                pid = self._proc.pid if self._proc is not None else None
            except ValueError:
                pid = None     # reaped and released (close())
            return {
                "name": self.name,
                "kind": self.kind,
                "pid": pid,
                "outstanding_windows": self._outstanding,
                "served_requests": self.served_requests(),
                "served_windows": self.served_windows(),
                "state": ("closed" if self._closed
                          else "draining" if self._draining else "live"),
            }
