"""The inference engine: checkpointed model → utilization predictions.

Bundles everything a consumer needs — params, model config, normalization
statistics, metric names — restored from one checkpoint directory, so
serving cannot drift from training state (the reference never serializes
its model at all; SURVEY.md §5.4).  Prediction over arbitrary-length
traffic series runs the window as a rolling jit-compiled batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeprest_tpu.config import Config, ModelConfig
from deeprest_tpu.data.windows import MinMaxStats
from deeprest_tpu.models.qrnn import QuantileGRU, resolve_params
from deeprest_tpu.ops import quantize as quant_ops
from deeprest_tpu.serve.batcher import BatchedBackendMixin
from deeprest_tpu.serve.fused import FusedInferenceMixin


def rolled_prediction_reference(
        apply_fn, x_stats: MinMaxStats, y_stats: MinMaxStats,
        window_size: int, traffic: np.ndarray,
        max_batch: int = 64,
        delta_mask: np.ndarray | None = None,
        median_index: int | None = None) -> np.ndarray:
    """[T, F] raw traffic → de-normalized [T, E, Q] predictions.

    The HOST-LOOP reference implementation: windows stacked and
    normalized in numpy, every batch read back, de-normalized on host,
    delta columns integrated with a sequential per-window carry.  The
    production path is the fused device program (serve/fused.py) — this
    loop is kept as the pinned numerical specification
    (tests/test_fused_infer.py: the fused path must match it bit-exactly
    on CPU for non-delta metrics, <= 1e-5 relative for the prefix-sum
    delta carry).

    The series is tiled into non-overlapping windows (last window
    right-aligned so every step is covered exactly once; the recurrent
    core supports any duration — reference claim at
    resource-estimation/README.md:83).  Windows go through ``apply_fn``
    in batches of at most ``max_batch``, so memory stays bounded for
    arbitrarily long series (a month of minutes is ~720 windows; only one
    batch of them is ever resident on device).  Shared by the in-process
    Predictor and the exported-artifact loader so both serve identical
    semantics by construction.

    A ragged last batch (series length not a multiple of
    ``max_batch * window_size``) is NOT a new device shape: both serving
    backends hand in a shape-laddered ``apply_fn``
    (serve/batcher.ShapeLadder) that pads every batch up a fixed rung
    ladder and strips the padding rows, so the jit cache holds one
    executable per rung instead of one per ragged shape.

    ``delta_mask`` marks metrics the model predicts as per-bucket
    increments (train/data.py delta formulation): those columns are
    integrated back to a LEVEL series — each window's cumulative sum,
    chained across windows on the median quantile so the rollout is
    continuous.  The absolute offset is a pure-prediction rollout from 0
    (no observations exist here); consumers with observations re-anchor
    (AnomalyDetector, the demo's results layer).  Quantile columns are
    offset from the shared median base, so the band reflects within-
    window uncertainty rather than compounding across the whole series.
    """
    w = window_size
    t = len(traffic)
    if t < w:
        raise ValueError(f"series length {t} < window_size {w}")
    if delta_mask is not None and delta_mask.any() and median_index is None:
        raise ValueError("delta_mask requires median_index for the "
                         "cross-window carry")
    starts = list(range(0, t - w + 1, w))
    if starts[-1] != t - w:
        starts.append(t - w)

    out = None
    for lo in range(0, len(starts), max_batch):
        chunk = starts[lo:lo + max_batch]
        x = np.stack([traffic[s:s + w] for s in chunk]).astype(np.float32)
        x = x_stats.apply(x).astype(np.float32)
        # graftlint: disable=JX003 -- designed sink: the pinned HOST-LOOP reference reads every batch back by definition; the production path is the fused engine
        preds = np.asarray(apply_fn(x))                   # [n, W, E, Q]
        preds = y_stats.invert(
            np.maximum(preds, 1e-6).transpose(0, 1, 3, 2)
        ).transpose(0, 1, 3, 2)
        if out is None:
            out = np.empty((t, preds.shape[2], preds.shape[3]), np.float32)
        for s, window in zip(chunk, preds):
            if delta_mask is not None and delta_mask.any():
                # graftlint: disable=JX003 -- host data: `window` is a numpy slice of the already-read-back batch
                window = np.array(window, copy=True)
                c = np.cumsum(window[:, delta_mask, :], axis=0)
                # carry: the already-written median level one step before
                # this window (0 for the very first step of the series)
                base = (out[s - 1, delta_mask, median_index][None, :, None]
                        if s > 0 else 0.0)
                window[:, delta_mask, :] = base + c
            out[s:s + w] = window      # later (right-aligned) window wins
    return out


# Historical name, kept for consumers pinned to the host loop.
rolled_prediction = rolled_prediction_reference


class Predictor(BatchedBackendMixin, FusedInferenceMixin):
    """Quantile predictions for traffic feature series."""

    def __init__(self, params, model_config: ModelConfig,
                 x_stats: MinMaxStats, y_stats: MinMaxStats,
                 metric_names: list[str], window_size: int,
                 space_dict: dict | None = None,
                 delta_mask: np.ndarray | None = None,
                 ladder: tuple[int, ...] | None = None,
                 fused: bool = True,
                 page_windows: int | None = None,
                 coalesce_pages: int | None = None,
                 coalesce_groups: int = 1,
                 sparse_feed: bool = False,
                 sparse_nnz_cap: int = 64,
                 quant: str = "off",
                 quant_budget: dict | None = None):
        # Quantized serving (round 22, ops/quantize.py): weight leaves
        # stored int8 (+f32 scales) or bf16, dequantized at use INSIDE
        # the jitted wrappers below via models.qrnn.resolve_params — the
        # one sanctioned site, on device, fused into the executables.
        if quant not in quant_ops.QUANT_MODES:
            raise ValueError(
                f"quant mode {quant!r} not in {quant_ops.QUANT_MODES}")
        self.quant = quant
        ref_params = params
        if quant != "off":
            params = quant_ops.quantize_params(params, quant)
        self.params = params
        self.model = QuantileGRU(config=model_config)
        self.x_stats = x_stats
        self.y_stats = y_stats
        self.metric_names = list(metric_names)
        self.window_size = window_size
        # serialized CallPathSpace of the training corpus (if checkpointed):
        # lets consumers featurize raw traces column-exactly — see space()
        self.space_dict = space_dict
        # [E] bool: metrics the model predicts as per-bucket increments
        # (train/data.py delta formulation); predict_series integrates
        # them back to levels.  None (pre-delta checkpoints): no-op.
        self.delta_mask = (np.asarray(delta_mask, bool)
                           if delta_mask is not None else None)
        # resolve_params is the weights-adapter: identity trace for f32
        # trees, on-device dequant for quantized ones — ONE apply path,
        # so the executable count stays flat across quant modes.
        self._apply = jax.jit(
            lambda p, x: self.model.apply({"params": resolve_params(p)},
                                          x, deterministic=True)
        )
        # Sparse-first serving feed (InferConfig.sparse_feed): a second
        # jitted apply taking RAW padded-COO windows plus the staged
        # stats — densify (one scatter-add) + normalize + model, all on
        # device (ops/densify.py for the bit-parity contract; stats are
        # runtime ARGUMENTS, like the fused engine's, so XLA cannot
        # strength-reduce the divide).  Dense entries stay the default.
        self.sparse_feed = bool(sparse_feed)
        self.sparse_nnz_cap = int(sparse_nnz_cap)
        apply_sparse = None
        if self.sparse_feed:
            from deeprest_tpu.ops.densify import (
                densify_coo, normalize_minmax,
            )

            feat = model_config.feature_dim
            x_mn = jnp.asarray(
                np.asarray(x_stats.min, np.float32).reshape(-1))
            x_rg = jnp.asarray(
                np.asarray(x_stats.range, np.float32).reshape(-1))
            self._apply_sparse = jax.jit(
                lambda p, c, v, mn, rg: self.model.apply(
                    {"params": resolve_params(p)},
                    normalize_minmax(densify_coo(c, v, feat), mn, rg),
                    deterministic=True))
            apply_sparse = lambda c, v: self._apply_sparse(
                self.params, jnp.asarray(c), jnp.asarray(v), x_mn, x_rg)
        else:
            self._apply_sparse = None
        # All serving batches go through the shape ladder (and, when one
        # is attached, the cross-request MicroBatcher): the jit cache
        # holds one executable per rung, never one per ragged shape.
        self._init_batching(
            lambda x: self._apply(self.params, jnp.asarray(x)),
            ladder=ladder, coalesce_groups=coalesce_groups,
            apply_sparse_fn=apply_sparse)
        # The fused device-resident rolled-inference engine (serve/fused.py)
        # shares the ladder's rung set, so mixed series lengths compile at
        # most one fused executable per rung.  Params thread through the
        # fused jit as arguments (bit parity — see FusedRolledEngine).
        self._init_fused(
            lambda p, x: self._apply(p, x), params=self.params,
            enabled=fused, page_windows=page_windows,
            coalesce_pages=coalesce_pages,
            sparse_nnz_cap=(self.sparse_nnz_cap if self.sparse_feed
                            else None))
        # Parity is a product contract: measure the per-(metric,
        # quantile) envelope vs the f32 reference at quantize time, and
        # fail LOUDLY if a stored budget (the checkpoint's pinned
        # envelope) is exceeded — a quantized predictor never serves
        # outside the parity its checkpoint recorded.
        self.parity_envelope = None
        if quant != "off":
            self.parity_envelope = self._measure_parity(
                ref_params, quant_budget)

    def _measure_parity(self, ref_params, budget: dict | None) -> dict:
        """Quantize-time parity measurement on the deterministic probe
        batch (ops/quantize.probe_batch): quantized apply vs the f32
        reference, reduced to the per-(metric, quantile) envelope.

        Runs through a throwaway jitted apply, NOT ``self._apply``, so
        the probe never perturbs the serving executable count the
        zero-post-warmup-compiles probes pin.  With a ``budget`` (the
        envelope stored next to the checkpoint) any violated cell
        raises — the loud gate."""
        probe = quant_ops.probe_batch(self.window_size,
                                      self.model.config.feature_dim)
        x = jnp.asarray(probe)
        apply_once = jax.jit(
            lambda p, xx: self.model.apply(
                {"params": resolve_params(p)}, xx, deterministic=True))
        measured = quant_ops.parity_envelope(
            apply_once(ref_params, x), apply_once(self.params, x),
            self.metric_names, self.model.config.quantiles)
        envelope = {
            "mode": self.quant,
            "measured": measured,
            "budget": (dict(budget["budget"]) if budget is not None
                       else quant_ops.budget_from_measured(measured)),
        }
        if budget is not None:
            violations = quant_ops.check_envelope(measured,
                                                  envelope["budget"])
            if violations:
                raise quant_ops.QuantParityError(
                    f"quantized ({self.quant}) predictions exceed the "
                    "stored parity envelope: "
                    + "; ".join(violations[:8])
                    + (f" (+{len(violations) - 8} more)"
                       if len(violations) > 8 else ""))
        return envelope

    def share_executables_from(self, donor: "Predictor") -> None:
        """Adopt the donor's jitted serving programs (fleet tier,
        serve/fleet.py): params and normalization stats are runtime
        ARGUMENTS throughout — ``_apply`` threads the params tree,
        the fused engine threads params AND stats (serve/fused.py bit-
        parity contract) — so predictors of the same architecture and
        quant mode serve different tenants' weights through the SAME
        compiled executables, and ``jit_cache_size`` stays flat in the
        number of tenants.

        The architecture/quant/geometry compatibility this requires is
        checked loudly here and in ``FusedRolledEngine.
        adopt_executables``; a mismatch would silently re-trace a new
        executable per tenant, which is exactly the regression the fleet
        bench's frozen-ledger gate exists to catch."""
        if not isinstance(donor, Predictor):
            raise TypeError(
                f"can only share executables between Predictors, got "
                f"{type(donor).__name__}")
        if donor is self:
            return
        if self.model_config != donor.model_config:
            raise ValueError(
                "cannot share executables across architectures: "
                f"{self.model_config} != {donor.model_config}")
        if self.quant != donor.quant:
            raise ValueError(
                f"cannot share executables across quant modes "
                f"({self.quant!r} vs {donor.quant!r}): the params tree "
                "leaf dtypes differ, which re-traces per mode")
        if self.window_size != donor.window_size:
            raise ValueError(
                f"cannot share executables across window sizes "
                f"({self.window_size} vs {donor.window_size})")
        if self.ladder.ladder != donor.ladder.ladder:
            raise ValueError(
                f"cannot share executables across shape ladders "
                f"({self.ladder.ladder} vs {donor.ladder.ladder})")
        if (self.sparse_feed, self.sparse_nnz_cap) != (
                donor.sparse_feed, donor.sparse_nnz_cap):
            raise ValueError(
                "cannot share executables across sparse-feed settings")
        self._apply = donor._apply
        if self._apply_sparse is not None:
            # the per-tenant entry wrapper closes over THIS predictor's
            # stats/params and late-binds self._apply_sparse, so only
            # the jitted function (and its cache) is shared
            self._apply_sparse = donor._apply_sparse
        if self._fused is not None and donor._fused is not None:
            self._fused.adopt_executables(donor._fused)

    def params_digest(self) -> str:
        """Stable fingerprint of the served params — the ``params_hash``
        half of the capacity-surface cache key (serve/surface.py).
        Computed ONCE per predictor (each reload builds a new instance)
        and cached: the tree walk reads every leaf back to host exactly
        one time, never on a request path."""
        digest = getattr(self, "_params_digest", None)
        if digest is None:
            import hashlib

            h = hashlib.sha1()
            # Quant mode enters the digest: a surface built at int8 must
            # never be served by (or to) an f32 predictor — the quant
            # mode is part of the cache-key identity, explicitly, not
            # just via the (already different) quantized leaf bytes.
            if self.quant != "off":
                h.update(self.quant.encode())
            for leaf in jax.tree_util.tree_leaves(self.params):
                # graftlint: disable=JX003 -- host data: one-time per-checkpoint fingerprint, cached on the instance
                h.update(np.asarray(leaf).tobytes())
            digest = self._params_digest = h.hexdigest()[:16]
        return digest

    def jit_cache_size(self) -> int | None:
        """Total compiled-executable count across BOTH serving programs —
        the per-rung batched apply and the fused rolled-inference pipeline
        (None when the running jax version has no cache probe) — the test
        hook behind the 'mixed series lengths trigger zero new compiles'
        guarantee.  ``jit_cache_stats`` has the per-program breakdown."""
        sizes = []
        for fn in (self._apply, self._apply_sparse):
            probe = getattr(fn, "_cache_size", None) if fn is not None \
                else None
            if callable(probe):
                sizes.append(int(probe()))
        if self._fused is not None:
            fused = self._fused.cache_size()
            if fused is not None:
                sizes.append(fused)
        return sum(sizes) if sizes else None

    def jit_cache_stats(self) -> dict:
        """Per-program executable counts plus the rung sets bounding them."""
        probe = getattr(self._apply, "_cache_size", None)
        sprobe = getattr(self._apply_sparse, "_cache_size", None) \
            if self._apply_sparse is not None else None
        return {
            "apply": int(probe()) if callable(probe) else None,
            "apply_sparse": int(sprobe()) if callable(sprobe) else None,
            "fused": (self._fused.cache_size()
                      if self._fused is not None else None),
            "ladder_rungs": len(self.ladder.ladder),
            "fused_rungs": (len(self._fused.rungs)
                            if self._fused is not None else 0),
            # the quant mode these executables were built at — the
            # flat-executable probes compare counts ACROSS modes, so the
            # breakdown must name which mode it counted
            "quant": self.quant,
        }

    @property
    def model_config(self) -> ModelConfig:
        """The restored architecture, as public API (equivalent to
        ``self.model.config``, which is an implementation detail)."""
        return self.model.config

    # The serving protocol shared with serve.export.ExportedPredictor —
    # consumers (AnomalyDetector, WhatIfEstimator, the HTTP server) use
    # only these, so either backend can sit behind them.

    @property
    def quantiles(self) -> tuple[float, ...]:
        return self.model.config.quantiles

    @property
    def feature_dim(self) -> int:
        return self.model.config.feature_dim

    def median_index(self) -> int:
        return self.model.median_index()

    # ------------------------------------------------------------------

    @classmethod
    def from_checkpoint(cls, directory: str, config: Config | None = None,
                        step: int | None = None,
                        ladder: tuple[int, ...] | None = None,
                        fused: bool = True,
                        page_windows: int | None = None,
                        coalesce_pages: int | None = None,
                        coalesce_groups: int = 1,
                        sparse_feed: bool = False,
                        sparse_nnz_cap: int = 64,
                        mesh_config=None,
                        quant: str = "off") -> "Predictor":
        """Restore params + host stats written by Trainer.save().

        ``quant`` ({'off','int8','bf16'}, ops/quantize.py): quantize the
        restored weights for serving.  The per-(metric, quantile) parity
        envelope vs the f32 reference is measured at quantize time and
        stored NEXT TO the checkpoint (``quant_parity_<mode>.json``); on
        every later load at the same mode the re-measured parity is
        checked against that stored budget and a violation raises — the
        envelope is a product contract, not a hope.

        With ``config=None`` the architecture comes wholesale from the
        checkpoint sidecar (all checkpoints written by Trainer.save carry
        it), so the restored predictor cannot drift from training.  An
        explicitly passed config is trusted as-is — the caller owns both
        architecture and serving knobs (compute_dtype, rnn_backend).

        ``mesh_config`` (a MeshConfig or None) lays a serving device mesh
        under the restored params: shardings resolve from the SAME
        partition-rule table the trainer pins with
        (parallel/sharding.PARTITION_RULES), so e.g. ``model=N`` gives the
        serving ladder and fused engine feature-axis TP over the F that
        grows with the endpoint vocabulary — there is no serving-side
        spec list to drift from training's.  The checkpoint may have been
        saved under any mesh shape (restore assembles by global index).
        """
        from deeprest_tpu.obs import spans as obs_spans
        from deeprest_tpu.parallel.mesh import make_mesh
        from deeprest_tpu.train.checkpoint import (
            latest_step, load_sidecar, restore_checkpoint,
        )
        from deeprest_tpu.train.trainer import Trainer

        with obs_spans.RECORDER.span("predictor.load",
                                     component="deeprest-predictor") as sp:
            sp.tag(directory=directory, step=step, quant=quant)
            return cls._from_checkpoint_inner(
                directory, config, step, ladder, fused, page_windows,
                coalesce_pages, coalesce_groups, sparse_feed,
                sparse_nnz_cap, mesh_config,
                make_mesh, latest_step, load_sidecar, restore_checkpoint,
                Trainer, quant)

    @staticmethod
    def _quant_envelope_path(directory: str, quant: str) -> str:
        import os

        return os.path.join(directory, f"quant_parity_{quant}.json")

    @classmethod
    def _from_checkpoint_inner(cls, directory, config, step, ladder, fused,
                               page_windows, coalesce_pages,
                               coalesce_groups, sparse_feed,
                               sparse_nnz_cap, mesh_config, make_mesh,
                               latest_step, load_sidecar,
                               restore_checkpoint, Trainer,
                               quant: str = "off") -> "Predictor":
        if step is None:
            step = latest_step(directory)
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {directory!r}")
        extra = load_sidecar(directory, step)

        if config is None:
            if "model_config" not in extra:
                raise ValueError(
                    f"checkpoint {directory!r} predates sidecar model configs; "
                    "pass the architecture explicitly via `config`"
                )
            mc = dict(extra["model_config"])
            mc["quantiles"] = tuple(mc.get("quantiles", ()))
            config = Config(model=ModelConfig(**mc))

        metric_names = extra["metric_names"]
        mesh = make_mesh(mesh_config) if mesh_config is not None else None
        trainer = Trainer(config, extra["feature_dim"], metric_names,
                          mesh=mesh)
        target = trainer.init_state(
            np.zeros((1, extra["window_size"], extra["feature_dim"]), np.float32)
        )
        state, _ = restore_checkpoint(directory, target, step=step)
        # The stored parity envelope rides next to the checkpoint: first
        # quantized load at a mode measures and pins it; every later
        # load re-measures and the budget gate raises on violation
        # (Predictor._measure_parity).
        quant_budget = None
        if quant != "off":
            import json
            import os

            env_path = cls._quant_envelope_path(directory, quant)
            if os.path.exists(env_path):
                with open(env_path, encoding="utf-8") as fh:
                    quant_budget = json.load(fh)
        predictor = cls(
            params=state.params,
            model_config=trainer.model_config,
            x_stats=MinMaxStats.from_dict(extra["x_stats"]),
            y_stats=MinMaxStats.from_dict(extra["y_stats"]),
            metric_names=metric_names,
            window_size=extra["window_size"],
            space_dict=extra.get("space"),
            delta_mask=extra.get("delta_mask"),
            ladder=ladder,
            fused=fused,
            page_windows=page_windows,
            coalesce_pages=coalesce_pages,
            coalesce_groups=coalesce_groups,
            sparse_feed=sparse_feed,
            sparse_nnz_cap=sparse_nnz_cap,
            quant=quant,
            quant_budget=quant_budget,
        )
        if quant != "off" and quant_budget is None:
            import json

            env_path = cls._quant_envelope_path(directory, quant)
            with open(env_path, "w", encoding="utf-8") as fh:
                json.dump({"step": step, **predictor.parity_envelope},
                          fh, indent=2, sort_keys=True)
        return predictor

    def space(self):
        """The training corpus's CallPathSpace (column-exact featurization
        for raw serve-time traces); None for pre-sidecar checkpoints."""
        if self.space_dict is None:
            return None
        from deeprest_tpu.data.featurize import CallPathSpace

        return CallPathSpace.from_dict(self.space_dict)

    # ------------------------------------------------------------------
    # predict_series / predict_series_many come from FusedInferenceMixin:
    # the fused one-dispatch-per-page device pipeline by default, falling
    # back to rolled_prediction_reference through apply_windows (the
    # shape-laddered, MicroBatcher-coalesced host path) — see
    # serve/fused.py for the routing rule and numerics contract.
