"""The routing front: least-outstanding-work dispatch, admission control,
per-tenant fairness, and zero-downtime rolling reload over engine replicas.

Clipper's split (PAPERS.md [2]): model containers stay dumb and
replicated; the routing layer owns batching policy, admission, and the
latency SLO.  Here the containers are :mod:`serve/replica.py` stacks and
this router IS the serving backend the HTTP service sees — it exposes the
same protocol as a single Predictor (``predict_series``,
``predict_series_many``, metadata, ``space``), so PredictionService and
every consumer (WhatIfEstimator, AnomalyDetector) run unchanged on one
engine or on forty.

Policies:

- **Dispatch** — least outstanding work: each request goes to the live
  replica with the fewest windows currently in flight (ties resolve
  round-robin).  Window counts, not request counts: one what-if sweep can
  carry 100× the windows of a single-window predict.
- **Admission** — a bounded global in-flight depth.  Beyond it, requests
  FAIL FAST with 429 + ``Retry-After`` instead of queueing into collapse
  (the closed-loop serve_bench at concurrency 1024 pins p99 staying
  bounded).  A small bounded wait absorbs micro-bursts; the queue itself
  is also bounded.
- **Fairness** — smooth weighted round-robin over the ``X-Tenant`` key.
  When slots free up, waiting tenants are granted in WRR order, so a
  tenant flooding the plane cannot starve the others beyond its weight
  share; unknown tenants get weight 1.
- **Rolling reload** — drain one replica at a time, swap its stack, and
  re-admit it before touching the next.  A request is served end-to-end
  by the single backend its replica held at dispatch, so no response ever
  mixes old and new params (pinned by tests/test_router.py under live
  load).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

import numpy as np

from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans
from deeprest_tpu.serve.replica import (
    EngineReplica, ReplicaDeadError, clone_backend,
)
from deeprest_tpu.serve.server import ServingError


class AdmissionError(ServingError):
    """The plane is saturated: fast 429 with a Retry-After hint."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message, status=429,
                         headers={"Retry-After": f"{retry_after_s:.3f}"})
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Admission/fairness/health knobs for :class:`ReplicaRouter`.

    ``admission_depth`` bounds concurrently ADMITTED requests across the
    whole plane; ``max_waiting`` bounds the short fairness queue behind it
    (everything beyond fails fast).  ``max_wait_s`` is how long a request
    may sit in that queue before it too turns into a 429 — the knob that
    keeps p99 bounded instead of collapsing under overload.

    The health knobs are the dynamic half of ROADMAP item 7:
    ``replica_timeout_s`` is the per-request deadline handed to process
    replicas (a worker dead between heartbeats turns into a typed
    ``ReplicaDeadError`` instead of an indefinite ``recv``);
    ``eject_after_failures`` consecutive dead-replica failures eject the
    replica from dispatch; ``retry_budget`` bounds how many times one
    request may be re-dispatched onto survivors (and ONLY for failures
    that prove the request never produced — and can never produce — a
    response: worker dead or send failed.  A deadline expiry on a live
    worker is never retried: the work may still be executing, and
    re-running it would double-execute); ``probe_interval_s`` paces the
    background probe that reboots ejected process replicas (reload-by-
    restart) and rejoins them.
    """

    admission_depth: int = 64
    max_waiting: int | None = None        # default: == admission_depth
    max_wait_s: float = 0.25
    retry_after_s: float = 0.05
    tenant_weights: dict[str, float] | None = None
    default_tenant: str = "default"
    replica_timeout_s: float | None = 30.0
    eject_after_failures: int = 3
    retry_budget: int = 1
    probe_interval_s: float = 0.5

    def __post_init__(self):
        if self.admission_depth < 1:
            raise ValueError(
                f"admission_depth {self.admission_depth} must be >= 1")
        if self.max_waiting is not None and self.max_waiting < 0:
            raise ValueError(f"max_waiting {self.max_waiting} must be >= 0")
        if self.max_wait_s < 0 or self.retry_after_s < 0:
            raise ValueError("max_wait_s/retry_after_s must be >= 0")
        for t, w in (self.tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight {w} must be > 0")
        if self.replica_timeout_s is not None and self.replica_timeout_s <= 0:
            raise ValueError(
                f"replica_timeout_s {self.replica_timeout_s} must be > 0 "
                "(None = no deadline)")
        if self.eject_after_failures < 1:
            raise ValueError(f"eject_after_failures "
                             f"{self.eject_after_failures} must be >= 1")
        if self.retry_budget < 0:
            raise ValueError(f"retry_budget {self.retry_budget} "
                             "must be >= 0")
        if self.probe_interval_s <= 0:
            raise ValueError(f"probe_interval_s {self.probe_interval_s} "
                             "must be > 0")

    @property
    def waiting_bound(self) -> int:
        return (self.admission_depth if self.max_waiting is None
                else self.max_waiting)


@dataclasses.dataclass
class _ReplicaHealth:
    """Per-replica health the router tracks across dispatches (all
    mutations under the router lock)."""

    consecutive_failures: int = 0
    ejected: bool = False
    ejections: int = 0
    rejoins: int = 0
    last_error: str | None = None


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False


class WeightedAdmission:
    """Bounded in-flight slots granted in smooth-WRR order per tenant.

    Smooth weighted round-robin (the nginx algorithm): each grant adds
    every waiting tenant's weight to its credit, picks the max-credit
    tenant, and charges it the total active weight — over time grants
    converge to the weight ratio, without bursts.
    """

    def __init__(self, config: RouterConfig):
        self.config = config
        self._lock = threading.Lock()
        self._inflight = 0
        self._waiting: dict[str, collections.deque[_Waiter]] = {}
        self._credit: dict[str, float] = {}
        # Admission counters ARE obs metrics now (one source of truth):
        # stats() / the autoscaler's demand read / the /metrics
        # exposition all read these same objects.  Per-instance — a
        # rebuilt plane re-exposes its fresh counters (obs registry
        # replace-by-name) while tests with several routers keep correct
        # per-instance values.  "queued" is monotone (requests that ever
        # waited), same meaning as the historical dict field.
        self._m_admission = obs_metrics.Counter(
            "deeprest_admission_requests_total",
            "admission outcomes across the serving plane",
            labelnames=("outcome",))
        self._m_tenants = obs_metrics.Counter(
            "deeprest_admission_tenant_requests_total",
            "per-tenant admission outcomes (X-Tenant WRR key)",
            labelnames=("tenant", "outcome"))
        self._m_in_plane = obs_metrics.Histogram(
            "deeprest_in_plane_latency_seconds",
            "admission grant -> response written (the latency window "
            "the admission bound controls)")
        for m in (self._m_admission, self._m_tenants, self._m_in_plane):
            obs_metrics.REGISTRY.expose(m)
        # IN-PLANE latency window (admission grant → response written):
        # the portion of request latency the admission bound actually
        # controls — client-observed latency additionally carries the
        # HTTP layer's thread scheduling, which no admission policy can
        # cap on a saturated host.  The deque keeps the exact-percentile
        # JSON view; the histogram above is the scrapeable twin.
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=8192)

    def _weight(self, tenant: str) -> float:
        return (self.config.tenant_weights or {}).get(tenant, 1.0)

    def _note(self, tenant: str, outcome: str) -> None:
        """One admission outcome into the obs counters (the single
        bookkeeping the JSON stats, /metrics, and the autoscaler share)."""
        self._m_admission.inc(outcome=outcome)
        self._m_tenants.inc(tenant=tenant, outcome=outcome)

    def try_acquire(self, tenant: str | None) -> "_AdmissionTicket":
        cfg = self.config
        tenant = tenant or cfg.default_tenant
        waiter = None
        with self._lock:
            if self._inflight < cfg.admission_depth and not any(
                    self._waiting.values()):
                self._inflight += 1
                self._note(tenant, "admitted")
                return _AdmissionTicket(self, tenant)
            total_waiting = sum(len(q) for q in self._waiting.values())
            if cfg.max_wait_s <= 0 or total_waiting >= cfg.waiting_bound:
                self._note(tenant, "rejected")
                raise AdmissionError(
                    f"serving plane saturated ({self._inflight} in flight, "
                    f"{total_waiting} waiting); retry after "
                    f"{cfg.retry_after_s:.3f}s", cfg.retry_after_s)
            waiter = _Waiter()
            self._waiting.setdefault(tenant, collections.deque()).append(
                waiter)
            self._note(tenant, "queued")
        waiter.event.wait(cfg.max_wait_s)
        with self._lock:
            if waiter.granted:
                self._note(tenant, "admitted")
                return _AdmissionTicket(self, tenant)
            # timed out: withdraw from the queue (the grant path may race
            # us — granted wins, checked again under the lock above)
            q = self._waiting.get(tenant)
            if q is not None and waiter in q:
                q.remove(waiter)
                if not q:
                    del self._waiting[tenant]
            if waiter.granted:          # grant landed between wait and lock
                self._note(tenant, "admitted")
                return _AdmissionTicket(self, tenant)
            self._note(tenant, "rejected")
        raise AdmissionError(
            f"serving plane saturated (waited {cfg.max_wait_s:.3f}s); "
            f"retry after {cfg.retry_after_s:.3f}s", cfg.retry_after_s)

    def release(self, in_plane_s: float | None = None) -> None:
        with self._lock:
            self._inflight -= 1
            if in_plane_s is not None:
                self._latencies.append(in_plane_s)
                self._m_in_plane.observe(in_plane_s)
            self._grant_next_locked()

    def reset_window(self) -> None:
        """Start a fresh in-plane latency window (bench cell boundary)."""
        with self._lock:
            self._latencies.clear()

    def _grant_next_locked(self) -> None:
        cfg = self.config
        while (self._inflight < cfg.admission_depth
               and any(self._waiting.values())):
            active = [t for t, q in self._waiting.items() if q]
            total = sum(self._weight(t) for t in active)
            best = None
            for t in active:
                self._credit[t] = self._credit.get(t, 0.0) + self._weight(t)
                if best is None or self._credit[t] > self._credit[best]:
                    best = t
            self._credit[best] -= total
            waiter = self._waiting[best].popleft()
            if not self._waiting[best]:
                del self._waiting[best]
            waiter.granted = True
            self._inflight += 1
            waiter.event.set()

    def counts(self) -> dict[str, int]:
        """Monotone admission outcome totals straight off the obs
        counters (what the autoscaler's demand read consumes)."""
        series = self._m_admission.series()
        return {k: int(series.get((k,), 0.0))
                for k in ("admitted", "rejected", "queued")}

    def stats(self) -> dict:
        tenants: dict[str, dict[str, int]] = {}
        for (tenant, outcome), v in self._m_tenants.series().items():
            if outcome in ("admitted", "rejected"):
                tenants.setdefault(
                    tenant, {"admitted": 0, "rejected": 0})[outcome] = int(v)
        with self._lock:
            lats = sorted(self._latencies)
            out = {
                "depth": self.config.admission_depth,
                "inflight": self._inflight,
                "waiting": sum(len(q) for q in self._waiting.values()),
                **self.counts(),
                "tenants": {t: tenants[t] for t in sorted(tenants)},
            }

        def pct(p):
            if not lats:
                return None
            k = min(len(lats) - 1, int(round(p / 100 * (len(lats) - 1))))
            return round(1e3 * lats[k], 3)

        out["in_plane_p50_ms"] = pct(50)
        out["in_plane_p99_ms"] = pct(99)
        return out


class _AdmissionTicket:
    """Context manager covering one admitted request end-to-end; its
    lifetime is the request's IN-PLANE latency sample (measured through
    the obs Stopwatch — the sanctioned clock OB001 points hot modules
    at — and observed into the admission latency histogram on release)."""

    __slots__ = ("_admission", "tenant", "_sw")

    def __init__(self, admission: WeightedAdmission, tenant: str):
        self._admission = admission
        self.tenant = tenant
        self._sw = obs_metrics.Stopwatch()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._admission.release(in_plane_s=self._sw.elapsed())
        return False


class ReplicaRouter:
    """N replicas behind the single-predictor serving protocol."""

    def __init__(self, replicas: list, config: RouterConfig | None = None,
                 batching=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.config = config or RouterConfig()
        self.admission = WeightedAdmission(self.config)
        # Guards the replica registry (autoscaler grows/shrinks it and the
        # rolling reload flips drain states while handler threads pick
        # replicas) and the counters below.
        self._lock = threading.Lock()
        self._replicas = list(replicas)
        self._rr = 0                   # round-robin tiebreak cursor
        self._reloads = 0
        self._last_reload_reason: str | None = None
        self._dispatched = 0
        self._batching = batching
        self._autoscaler_decision: dict | None = None
        # Per-replica health (keyed by object identity — names recycle
        # across scale_to generations) + the probe-and-rejoin thread.
        # The probe starts lazily at the first ejection and parks itself
        # once every replica is live again.
        self._health: dict[int, _ReplicaHealth] = {}
        self._probe_thread: threading.Thread | None = None
        self._probe_stop = threading.Event()
        self._closed = False
        self._m_ejections = obs_metrics.Counter(
            "deeprest_router_ejections_total",
            "replicas ejected from dispatch by the health layer",
            labelnames=("replica",))
        self._m_retries = obs_metrics.Counter(
            "deeprest_router_retries_total",
            "requests re-dispatched onto a survivor after a dead replica",
            labelnames=("replica",))
        self._m_rejoins = obs_metrics.Counter(
            "deeprest_router_rejoins_total",
            "ejected replicas probed healthy and re-admitted to dispatch",
            labelnames=("replica",))
        self._m_reloads_by_reason = obs_metrics.Counter(
            "deeprest_router_reloads_by_reason_total",
            "rolling reloads by trigger (watch/drift/manual)",
            labelnames=("reason",))
        for m in (self._m_ejections, self._m_retries, self._m_rejoins,
                  self._m_reloads_by_reason):
            obs_metrics.REGISTRY.expose(m)
        self._meta = self._probe_meta(replicas[0])
        # Fleet tier (serve/fleet.py): attach_fleet installs a
        # PredictorPool; tenant-aware dispatches then resolve tenant →
        # pool entry FIRST and serve through the entry's predictor via
        # the replica's backend override.
        self._fleet = None
        # Render-time /metrics view over the replica plane: everything it
        # publishes is already counted by the replicas' and admission's
        # own obs counters — the collector adds zero steady-state cost.
        # Replace-by-name: the newest router owns the exposition.
        obs_metrics.REGISTRY.register_collector("router",
                                                self._collect_metrics)

    @staticmethod
    def _probe_meta(replica) -> dict:
        backend = getattr(replica, "backend", None)
        if callable(backend):
            b = backend()
            return {
                "metric_names": list(b.metric_names),
                "window_size": b.window_size,
                "feature_dim": b.feature_dim,
                "quantiles": tuple(b.quantiles),
                "median_index": b.median_index(),
                "delta_mask": (np.asarray(b.delta_mask, bool)
                               if b.delta_mask is not None else None),
                "space_dict": getattr(b, "space_dict", None),
                "y_stats": getattr(b, "y_stats", None),
            }
        meta = replica._meta            # ProcessReplica boot handshake
        y_stats = None
        if meta.get("y_stats") is not None:
            from deeprest_tpu.data.windows import MinMaxStats

            y_stats = MinMaxStats.from_dict(meta["y_stats"])
        return {
            "metric_names": list(meta["metric_names"]),
            "window_size": int(meta["window_size"]),
            "feature_dim": int(meta["feature_dim"]),
            "quantiles": tuple(meta["quantiles"]),
            "median_index": int(meta["median_index"]),
            "delta_mask": (np.asarray(meta["delta_mask"], bool)
                           if meta.get("delta_mask") is not None else None),
            "space_dict": meta.get("space_dict"),
            "y_stats": y_stats,
        }

    # -- construction ----------------------------------------------------

    @classmethod
    def build(cls, backend, n: int, config: RouterConfig | None = None,
              batching=None, devices=None) -> "ReplicaRouter":
        """N in-process replicas over ``backend``, round-robin across
        ``devices`` (default: every attached device).  Replicas landing on
        the SAME device share one stack — executables are per-device, so
        replica count beyond the device count adds scheduling slots, not
        compiles (pinned by tests/test_router.py)."""
        import jax

        if n < 1:
            raise ValueError(f"replica count {n} must be >= 1")
        if devices is None:
            devices = list(jax.devices())
        from deeprest_tpu.serve.batcher import MicroBatcher

        by_device: dict[int, object] = {}
        replicas = []
        for i in range(n):
            dev = devices[i % len(devices)]
            key = id(dev)
            stack = by_device.get(key)
            if stack is None:
                stack = (backend if not by_device
                         else clone_backend(backend, device=dev))
                if batching is not None and stack.batcher is None:
                    stack.attach_batcher(MicroBatcher(stack.ladder,
                                                      batching))
                by_device[key] = stack
            replicas.append(EngineReplica(stack, name=f"r{i}", device=dev,
                                          batching=batching))
        return cls(replicas, config=config, batching=batching)

    @classmethod
    def build_process(cls, spec: dict, n: int,
                      config: RouterConfig | None = None,
                      batching=None) -> "ReplicaRouter":
        """N worker-subprocess replicas from one spec (each child builds
        and owns its full stack; see serve/replica.ProcessReplica)."""
        from deeprest_tpu.serve.replica import ProcessReplica

        if n < 1:
            raise ValueError(f"replica count {n} must be >= 1")
        config = config or RouterConfig()
        if batching is not None:
            spec = dict(spec)
            spec["batching"] = {"max_batch": batching.max_batch,
                               "max_linger_s": batching.max_linger_s,
                               "max_queue": batching.max_queue}
        replicas = []
        try:
            for i in range(n):
                replicas.append(ProcessReplica(
                    spec, name=f"p{i}",
                    request_timeout_s=config.replica_timeout_s))
        except Exception:
            # a failing Nth boot must not leak the N-1 live workers
            for r in replicas:
                r.close()
            raise
        return cls(replicas, config=config, batching=batching)

    # -- serving protocol (what PredictionService consumes) --------------

    def _meta_get(self, key: str):
        with self._lock:       # a rolling reload re-probes self._meta
            return self._meta[key]

    @property
    def metric_names(self) -> list[str]:
        return self._meta_get("metric_names")

    @property
    def window_size(self) -> int:
        return self._meta_get("window_size")

    @property
    def feature_dim(self) -> int:
        return self._meta_get("feature_dim")

    @property
    def quantiles(self) -> tuple[float, ...]:
        return self._meta_get("quantiles")

    @property
    def delta_mask(self):
        return self._meta_get("delta_mask")

    @property
    def space_dict(self):
        return self._meta_get("space_dict")

    @property
    def y_stats(self):
        """Target normalization stats (the AnomalyDetector's scale-floor
        source) — probed from the lead replica like the rest of the
        metadata, so the detector and the streaming verdict surface run
        over the router exactly as over one Predictor."""
        return self._meta_get("y_stats")

    def median_index(self) -> int:
        return self._meta_get("median_index")

    def space(self):
        space_dict = self._meta_get("space_dict")
        if space_dict is None:
            return None
        from deeprest_tpu.data.featurize import CallPathSpace

        return CallPathSpace.from_dict(space_dict)

    def admit(self, tenant: str | None):
        """The PredictionService admission hook (fast 429 on overload)."""
        return self.admission.try_acquire(tenant)

    # -- fleet tier (tenant → pool entry before dispatch) -----------------

    def attach_fleet(self, pool) -> None:
        """Install a :class:`~deeprest_tpu.serve.fleet.PredictorPool`:
        every tenant-aware dispatch resolves through it and rides the
        replicas' backend override.  The existing ``X-Tenant`` WRR front
        keeps metering fairness — same header, two layers: admission
        meters it, the pool resolves it."""
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            if not callable(getattr(r, "backend", None)):
                raise ValueError(
                    f"replica {r.name} ({r.kind}) cannot serve a fleet "
                    "pool: the backend override needs in-process (thread) "
                    "replicas — process workers would re-ship tenant "
                    "params per request")
        with self._lock:
            self._fleet = pool

    def fleet(self):
        with self._lock:
            return self._fleet

    def _fleet_entry(self, tenant: str | None):
        """Resolve tenant → pool entry for ONE request (LRU touch +
        restore-if-spilled happen here, exactly once — retries reuse the
        entry).  None when no pool is attached."""
        with self._lock:
            pool = self._fleet
        if pool is None:
            return None
        from deeprest_tpu.serve.fleet import UnknownTenantError

        try:
            return pool.resolve(tenant)
        except UnknownTenantError as exc:
            raise ServingError(
                f"unknown tenant {exc.args[0]!r}: not admitted to the "
                "fleet pool", status=404) from None

    def _health_locked(self, replica) -> _ReplicaHealth:
        """The replica's health record (caller holds ``self._lock``)."""
        h = self._health.get(id(replica))
        if h is None:
            h = self._health[id(replica)] = _ReplicaHealth()
        return h

    def _pick(self, excluded: frozenset = frozenset()):
        """Least-outstanding-work LIVE replica (ties: round-robin),
        skipping ejected replicas and this request's ``excluded`` set
        (replicas that already failed it).  Waits briefly only through a
        rolling reload's drain gap — a plane whose every candidate is
        ejected or excluded sheds FAST with a 503 instead of hanging
        (ejections heal through the probe, seconds away; making the
        request wait for that is exactly the unbounded-latency failure
        the chaos gate forbids)."""
        deadline = time.monotonic() + 5.0
        while True:
            with self._lock:
                candidates = [r for r in self._replicas
                              if id(r) not in excluded]
                live = [r for r in candidates
                        if r.available()
                        and not self._health_locked(r).ejected]
                if live:
                    self._rr += 1
                    best = min(
                        range(len(live)),
                        key=lambda i: (live[i].outstanding(),
                                       (i - self._rr) % len(live)))
                    self._dispatched += 1
                    return live[best]
                # a DRAINING (non-ejected) candidate is a reload gap —
                # sub-second by design, worth a bounded wait; but never
                # wait when this request already burned a replica
                recoverable = not excluded and any(
                    not self._health_locked(r).ejected
                    for r in candidates)
            if not recoverable or time.monotonic() > deadline:
                raise ServingError(
                    "no live replica (plane reloading, replicas ejected, "
                    "or shut down)", status=503)
            time.sleep(0.005)

    def _dispatch(self, call, tags: dict):
        """One request through the health layer: dispatch, and on a typed
        ReplicaDeadError note the failure (possibly ejecting the replica)
        and — ONLY when the error proves the request never produced and
        can never produce a response (worker dead / send failed, never a
        deadline expiry on a live worker: that work may still be
        executing and a re-run would double-execute) — re-dispatch onto
        a survivor, at most ``retry_budget`` times.  Every other
        exception is a request-level error and propagates untouched."""
        cfg = self.config
        excluded: set[int] = set()
        retries = 0
        while True:
            replica = self._pick(frozenset(excluded))
            try:
                with obs_spans.RECORDER.span(
                        "router.dispatch",
                        component="deeprest-router") as sp:
                    sp.tag(replica=replica.name, **tags)
                    if retries:
                        sp.tag(retry=retries)
                    out = call(replica)
            except ReplicaDeadError as exc:
                self._note_replica_failure(replica, exc)
                excluded.add(id(replica))
                if not exc.retriable:
                    raise ServingError(
                        f"replica {replica.name} failed mid-request and "
                        f"the request may still be executing ({exc}); "
                        "not retried — no double-execution", status=503,
                    ) from exc
                if retries >= cfg.retry_budget:
                    raise ServingError(
                        f"request failed on {retries + 1} replica(s), "
                        f"retry budget {cfg.retry_budget} exhausted "
                        f"({exc})", status=503) from exc
                retries += 1
                self._m_retries.inc(replica=replica.name)
                with obs_spans.RECORDER.span(
                        "router.retry",
                        component="deeprest-router") as sp:
                    sp.tag(replica=replica.name, attempt=retries)
                continue
            self._note_replica_ok(replica)
            return out

    def predict_series(self, traffic: np.ndarray,
                       integrate: bool = True,
                       tenant: str | None = None) -> np.ndarray:
        entry = self._fleet_entry(tenant)
        if entry is not None:
            backend = entry.predictor()
            return self._dispatch(
                lambda r: r.predict_series(traffic, integrate=integrate,
                                           backend=backend),
                {"series": 1, "tenant": entry.tenant})
        return self._dispatch(
            lambda r: r.predict_series(traffic, integrate=integrate),
            {"series": 1})

    def predict_series_many(self, series_list, integrate: bool = True,
                            tenant: str | None = None):
        series_list = list(series_list)
        entry = self._fleet_entry(tenant)
        if entry is not None:
            backend = entry.predictor()
            return self._dispatch(
                lambda r: r.predict_series_many(series_list,
                                                integrate=integrate,
                                                backend=backend),
                {"series": len(series_list), "tenant": entry.tenant})
        return self._dispatch(
            lambda r: r.predict_series_many(series_list,
                                            integrate=integrate),
            {"series": len(series_list)})

    # -- replica health: ejection, retry, probe-and-rejoin ---------------

    def _note_replica_ok(self, replica) -> None:
        with self._lock:
            h = self._health.get(id(replica))
            if h is not None and h.consecutive_failures:
                h.consecutive_failures = 0

    def _replica_alive(self, replica) -> bool:
        alive = getattr(replica, "alive", None)
        return alive() if callable(alive) else True

    def _note_replica_failure(self, replica, exc) -> None:
        dead = not self._replica_alive(replica)
        with self._lock:
            h = self._health_locked(replica)
            h.consecutive_failures += 1
            h.last_error = str(exc)
            fails = h.consecutive_failures
            eject = (not h.ejected
                     and (dead or fails >= self.config.eject_after_failures))
            if eject:
                h.ejected = True
                h.ejections += 1
        if eject:
            self._m_ejections.inc(replica=replica.name)
            with obs_spans.RECORDER.span("router.eject",
                                         component="deeprest-router") as sp:
                sp.tag(replica=replica.name, dead=dead,
                       consecutive_failures=fails, error=str(exc)[:200])
            self._ensure_probe()

    def eject(self, name: str, reason: str = "manual eject") -> None:
        """Administratively eject a replica from dispatch (the chaos
        harness's thread-replica kill switch; process replicas normally
        eject themselves through ReplicaDeadError).  In-flight work on
        the replica finishes; the probe rejoins it."""
        with self._lock:
            target = next((r for r in self._replicas if r.name == name),
                          None)
            if target is None:
                raise KeyError(f"no replica named {name!r}")
            h = self._health_locked(target)
            fresh = not h.ejected
            if fresh:
                h.ejected = True
                h.ejections += 1
                h.last_error = reason
        if fresh:
            self._m_ejections.inc(replica=name)
            with obs_spans.RECORDER.span("router.eject",
                                         component="deeprest-router") as sp:
                sp.tag(replica=name, reason=reason)
            self._ensure_probe()

    def _ensure_probe(self) -> None:
        with self._lock:
            if self._closed:
                return
            if (self._probe_thread is not None
                    and self._probe_thread.is_alive()):
                return
            self._probe_stop = threading.Event()
            stop = self._probe_stop
            t = threading.Thread(target=self._probe_loop, args=(stop,),
                                 daemon=True,
                                 name="deeprest-router-probe")
            self._probe_thread = t
        t.start()

    def _probe_loop(self, stop: threading.Event) -> None:
        """Background probe-and-rejoin: each tick tries to bring every
        ejected replica back — process replicas REBOOT via the existing
        reload-by-restart (a SIGKILLed worker comes back as a fresh
        spawn from the same spec), thread replicas rejoin directly
        (in-process stacks cannot die separately from the plane; their
        ejections are administrative or transient).  A replica whose
        reboot fails stays ejected and is retried next tick — the tick
        interval is the backoff (graftlint RS004's discharge).  The
        thread parks once every replica is live; the next ejection
        starts a fresh one."""
        while not stop.wait(self.config.probe_interval_s):
            with self._lock:
                targets = [r for r in self._replicas
                           if self._health_locked(r).ejected]
            for r in targets:
                if stop.is_set():
                    return
                try:
                    self._revive(r)
                except Exception as exc:
                    with self._lock:
                        self._health_locked(r).last_error = \
                            f"rejoin failed: {exc}"
            with self._lock:
                if not any(self._health_locked(r).ejected
                           for r in self._replicas):
                    return              # park until the next ejection

    def _revive(self, replica) -> None:
        restart = getattr(replica, "restart", None)
        if callable(restart):
            restart()       # reboot-by-restart; raises when the boot fails
        with self._lock:
            h = self._health_locked(replica)
            if not h.ejected:
                return
            h.ejected = False
            h.consecutive_failures = 0
            h.rejoins += 1
        self._m_rejoins.inc(replica=replica.name)
        with obs_spans.RECORDER.span("router.rejoin",
                                     component="deeprest-router") as sp:
            sp.tag(replica=replica.name)

    # -- replica plane management ----------------------------------------

    @property
    def replicas(self) -> list:
        with self._lock:
            return list(self._replicas)

    def enable_batching(self, config) -> None:
        """Per-replica-stack MicroBatchers (one per distinct stack)."""
        with self._lock:
            replicas = list(self._replicas)
            self._batching = config
        seen = set()
        for r in replicas:
            backend = getattr(r, "backend", None)
            key = id(backend()) if callable(backend) else id(r)
            if key in seen:
                continue
            seen.add(key)
            r.set_batching(config)

    def rolling_reload_from(self, fresh_backend,
                            reason: str = "watch") -> None:
        """Zero-downtime reload: drain → swap → re-admit, one stack at a
        time.  Replicas sharing a stack (same device) drain together and
        swap once.  Never takes the router lock across a drain wait —
        requests keep flowing to the other replicas.

        ``reason`` labels the reload's obs counter and span — "watch"
        (checkpoint-dir follower), "drift" (DriftController hot-swap), or
        "manual" — so the drift→retrain→reload loop is distinguishable
        from cadence reloads on /metrics."""
        with obs_spans.RECORDER.span("router.rolling_reload",
                                     component="deeprest-router") as sp:
            sp.tag(reason=reason)
            self._rolling_reload_inner(fresh_backend)
        with self._lock:
            self._last_reload_reason = reason
        self._m_reloads_by_reason.inc(reason=reason)

    def _rolling_reload_inner(self, fresh_backend) -> None:
        with self._lock:
            replicas = list(self._replicas)
        groups: dict[int, list] = {}
        for r in replicas:
            backend = getattr(r, "backend", None)
            key = id(backend()) if callable(backend) else id(r)
            groups.setdefault(key, []).append(r)
        for group in groups.values():
            for r in group:
                r.drain()
            try:
                for r in group:
                    if not r.wait_idle(timeout_s=60.0):
                        raise ServingError(
                            f"replica {r.name} failed to drain for reload",
                            status=503)
                lead = group[0]
                fresh = (clone_backend(fresh_backend, device=lead.device)
                         if callable(getattr(lead, "backend", None))
                         else fresh_backend)
                lead.reload_backend(fresh)
                for r in group[1:]:
                    r.reload_backend(fresh)
            finally:
                for r in group:
                    r.resume()
        with self._lock:
            self._reloads += 1
            # metadata may legitimately change shape-compatibly (fresh
            # normalization stats); re-probe from the reloaded lead
            self._meta = self._probe_meta(replicas[0])

    def scale_to(self, n: int, backend_factory=None) -> int:
        """Grow/shrink the replica plane to ``n`` (the autoscaler's
        actuator).  Growth clones from the first live replica's stack (or
        ``backend_factory()``); shrink drains and closes the tail."""
        import jax

        if n < 1:
            raise ValueError(f"replica count {n} must be >= 1")
        with self._lock:
            replicas = list(self._replicas)
        if n == len(replicas):
            return n
        if n < len(replicas):
            with self._lock:
                keep, drop = self._replicas[:n], self._replicas[n:]
                self._replicas = keep
            for r in drop:
                # graftlint: disable=RS002 -- designed sink: a dropped replica sharing its stack with a survivor stays drained (the survivor owns the stack); non-shared drops are closed below on every path
                r.drain()
            errors = []
            for r in drop:
                # one replica's failing drain-wait/close must not leave
                # the REST of the shrink set drained-but-live (graftlint
                # EX002: stranded between publish points) — reclaim every
                # replica, then report the failures together
                try:
                    r.wait_idle(timeout_s=30.0)
                    # shared-stack replicas must not close the
                    # survivors' stack
                    shared = any(
                        callable(getattr(k, "backend", None))
                        and callable(getattr(r, "backend", None))
                        and k.backend() is r.backend() for k in keep)
                    if not shared:
                        r.close()
                except Exception as exc:
                    errors.append(f"{r.name}: {type(exc).__name__}: {exc}")
            if errors:
                raise ServingError(
                    "scale_to shrink could not reclaim every replica: "
                    + "; ".join(errors), status=500)
            return n
        lead = replicas[0]
        with self._lock:
            batching = self._batching
        if callable(getattr(lead, "backend", None)):       # thread plane
            devices = list(jax.devices())
            base = backend_factory() if backend_factory else lead.backend()
            from deeprest_tpu.serve.batcher import MicroBatcher

            stacks = {}
            for r in replicas:
                if callable(getattr(r, "backend", None)) \
                        and r.device is not None:
                    stacks[id(r.device)] = r.backend()
            fresh = []
            for i in range(len(replicas), n):
                dev = devices[i % len(devices)]
                stack = stacks.get(id(dev))
                if stack is None:
                    stack = clone_backend(base, device=dev)
                    if batching is not None and stack.batcher is None:
                        stack.attach_batcher(
                            MicroBatcher(stack.ladder, batching))
                    stacks[id(dev)] = stack
                fresh.append(EngineReplica(stack, name=f"r{i}", device=dev,
                                           batching=batching))
        else:                                              # process plane
            from deeprest_tpu.serve.replica import ProcessReplica

            fresh = []
            try:
                for i in range(len(replicas), n):
                    fresh.append(ProcessReplica(
                        lead.spec, name=f"p{i}",
                        request_timeout_s=self.config.replica_timeout_s))
            except Exception:
                # a failing Nth boot must not leak the N-1 workers
                # already spawned (their subprocesses outlive the call)
                for r in fresh:
                    r.close()
                raise
        with self._lock:
            # revalidate before the act (graftrace RC003): the length
            # check at the top ran under an EARLIER acquire, and a
            # concurrent scale_to may have grown the plane while the new
            # stacks were building off-lock — blindly extending would
            # overshoot the target.  Cap at the room actually left.
            room = max(0, n - len(self._replicas))
            publish, surplus = fresh[:room], fresh[room:]
            self._replicas.extend(publish)
        for r in surplus:
            # unpublished process workers own live subprocesses; thread
            # replicas may share a cloned stack with a published
            # survivor, so they are dropped (GC reclaims unshared
            # stacks), never closed
            if not callable(getattr(r, "backend", None)):
                r.close()
        return n

    def note_autoscaler(self, decision: dict) -> None:
        """Latest control-loop decision, surfaced on /healthz."""
        with self._lock:
            self._autoscaler_decision = dict(decision)

    def close(self) -> None:
        # Deregister the render-time collector FIRST: the process-wide
        # registry would otherwise hold this router (and every replica
        # stack's device-resident params) alive forever — the leak the
        # chaos storm's device-buffer census caught.  Conditional on the
        # bound method so a rebuilt plane's newer registration survives.
        obs_metrics.REGISTRY.unregister_collector("router",
                                                  self._collect_metrics)
        with self._lock:
            self._closed = True
            replicas = list(self._replicas)
            probe, stop = self._probe_thread, self._probe_stop
        stop.set()
        if probe is not None:
            probe.join(timeout=5)
        seen = set()
        for r in replicas:
            backend = getattr(r, "backend", None)
            key = id(backend()) if callable(backend) else id(r)
            if key in seen:
                # graftlint: disable=RS002 -- designed shutdown sink: shared-stack duplicates drain forever; the stack (and its batcher) is closed once, via the first replica of the group
                r.drain()
                continue
            seen.add(key)
            r.close()

    # -- observability ---------------------------------------------------

    def demand_totals(self) -> dict[str, int]:
        """Cumulative plane demand off the obs counters: requests served
        by any replica plus requests shed by admission.  The autoscaler's
        observation source (one source of truth with /healthz and
        /metrics — the counters behind all three are the same objects)."""
        with self._lock:
            replicas = list(self._replicas)
        served = sum(int(r.served_requests()) for r in replicas)
        return {"served": served,
                "shed": self.admission.counts()["rejected"]}

    def _collect_metrics(self, sink) -> None:
        """The /metrics view of the replica plane (render-time only)."""
        with self._lock:
            replicas = list(self._replicas)
            dispatched = self._dispatched
            reloads = self._reloads
            decision = self._autoscaler_decision
        sink.gauge("deeprest_router_replicas", len(replicas),
                   help="live replica count behind the routing front")
        sink.counter("deeprest_router_dispatched_total", dispatched,
                     help="requests dispatched by the router")
        sink.counter("deeprest_router_rolling_reloads_total", reloads,
                     help="zero-downtime rolling reloads completed")
        with self._lock:
            ejected = sum(1 for r in replicas
                          if self._health_locked(r).ejected)
        sink.gauge("deeprest_router_ejected_replicas", ejected,
                   help="replicas currently ejected from dispatch "
                        "(awaiting probe-and-rejoin)")
        for r in replicas:
            labels = {"replica": r.name}
            sink.gauge("deeprest_replica_outstanding_windows",
                       r.outstanding(),
                       help="windows currently dispatched to the replica",
                       labels=labels)
            sink.counter("deeprest_replica_served_requests_total",
                         r.served_requests(),
                         help="requests served by the replica",
                         labels=labels)
            sink.counter("deeprest_replica_served_windows_total",
                         r.served_windows(),
                         help="windows served by the replica",
                         labels=labels)
        if decision is not None:
            sink.gauge("deeprest_autoscaler_desired_replicas",
                       decision.get("desired", 0),
                       help="latest autoscaler decision")
        cache = self.jit_cache_size()
        if cache is not None:
            sink.gauge("deeprest_plane_jit_executables", cache,
                       help="compiled executables across distinct stacks")

    def health_totals(self) -> dict[str, int]:
        """Cumulative ejection/retry/rejoin counts off the obs counters
        (one source of truth with /metrics and the chaos gate)."""
        return {
            "ejections": int(sum(self._m_ejections.series().values())),
            "retries": int(sum(self._m_retries.series().values())),
            "rejoins": int(sum(self._m_rejoins.series().values())),
        }

    def router_stats(self) -> dict:
        with self._lock:
            replicas = list(self._replicas)
            reloads = self._reloads
            last_reload_reason = self._last_reload_reason
            dispatched = self._dispatched
            decision = self._autoscaler_decision
            health = {
                id(r): dataclasses.replace(self._health_locked(r))
                for r in replicas
            }
        entries = []
        for r in replicas:
            s = r.stats()
            h = health[id(r)]
            s["health"] = {
                "ejected": h.ejected,
                "consecutive_failures": h.consecutive_failures,
                "ejections": h.ejections,
                "rejoins": h.rejoins,
                "last_error": h.last_error,
            }
            entries.append(s)
        return {
            "replicas": entries,
            "num_replicas": len(replicas),
            "live_replicas": sum(
                1 for r in replicas
                if r.available() and not health[id(r)].ejected),
            "dispatched": dispatched,
            "rolling_reloads": reloads,
            "last_reload_reason": last_reload_reason,
            "admission": self.admission.stats(),
            "health": self.health_totals(),
            "autoscaler": decision,
        }

    def params_digest(self) -> str | None:
        """The lead replica's params digest (the /healthz fleet view's
        single-tenant fallback; per-tenant digests live on the pool)."""
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            return None
        backend = getattr(replicas[0], "backend", None)
        if callable(backend):
            probe = getattr(backend(), "params_digest", None)
            return probe() if callable(probe) else None
        fleet_meta = getattr(replicas[0], "fleet_meta", None)
        if callable(fleet_meta):     # ProcessReplica boot handshake
            meta = fleet_meta() or {}
            default = meta.get("tenants", {}).get("default", {})
            return default.get("params_digest")
        return None

    def jit_cache_size(self) -> int | None:
        """Total executables across DISTINCT stacks (shared stacks count
        once — the zero-new-executables-per-replica-beyond-first probe)."""
        sizes, seen = [], set()
        for r in self.replicas:
            backend = getattr(r, "backend", None)
            if not callable(backend):
                continue
            b = backend()
            if id(b) in seen:
                continue
            seen.add(id(b))
            probe = getattr(b, "jit_cache_size", None)
            if callable(probe):
                s = probe()
                if s is not None:
                    sizes.append(s)
        return sum(sizes) if sizes else None
