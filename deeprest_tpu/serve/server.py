"""The prediction service: predict / what-if / anomaly over HTTP.

The reference's serving story is a Dash demo over a *precomputed* results
pickle (reference: web-demo/app.py:13-16, dataloader.py:30-32) — no live
model behind a wire.  This server is the missing piece the north star
names (BASELINE.json: "... for the Go gRPC server"): a process any client
can call with JSON over HTTP, backed by either the in-process Predictor
(checkpoint) or the portable exported artifact (serve/export.py) — both
expose the same serving protocol, so the wire format is identical.

Routes (all JSON):

    GET  /healthz             liveness + model dims
    GET  /v1/meta             metric names, quantiles, window, endpoints
    GET  /metrics             Prometheus text exposition (deeprest_tpu/obs)
    GET  /v1/spans            retained spans as Jaeger query-API JSON
    POST /v1/predict          {"traffic": [[F floats] x T]}          → [T,E,Q]
    POST /v1/whatif           {"expected_traffic": [{endpoint: n}xT]} → series
    POST /v1/whatif/scaling   {"baseline_traffic", "hypothetical_traffic"}
    POST /v1/whatif/surface   {"base_traffic", "scales"|"factor"}     → peaks
    POST /v1/anomaly          {"traffic", "observed", "tolerance"?, "min_run"?}
    POST /v1/profile          {"seconds"?, "out_dir"?} → jax.profiler window

Built on the stdlib ThreadingHTTPServer: one small dependency-free binary
surface.  Concurrent requests do NOT each pay a device dispatch: the
service attaches a cross-request MicroBatcher (serve/batcher.py) to the
backend, so windows from simultaneous /v1/predict, /v1/whatif*, and
/v1/anomaly calls coalesce into shared shape-laddered device batches and
demultiplex back per request — the wire protocol is unchanged, and
``/healthz`` exposes queue depth and ladder hit statistics.

The backend may also be a multi-replica routing front
(serve/router.ReplicaRouter) — same serving protocol, plus an admission
hook the POST handlers call per request: a saturated plane answers a
fast 429 with a ``Retry-After`` header (AdmissionError), tenants are
metered by the ``X-Tenant`` request header, and ``/healthz`` grows a
``router`` key (per-replica outstanding work, admission counters,
autoscaler decision).  Single-engine backends admit everything — the
wire behavior is unchanged when no router is configured.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans
from deeprest_tpu.serve.anomaly import AnomalyDetector
from deeprest_tpu.serve.batcher import BatcherConfig, MicroBatcher
from deeprest_tpu.serve.surface import CapacitySurfaceManager
from deeprest_tpu.serve.whatif import WhatIfEstimator


class ServingError(ValueError):
    """Client error carrying an HTTP status (and optional extra response
    headers — e.g. ``Retry-After`` on admission-control 429s)."""

    def __init__(self, message: str, status: int = 400,
                 headers: dict[str, str] | None = None):
        super().__init__(message)
        self.status = status
        self.headers = dict(headers) if headers else {}


class CheckpointReloader:
    """Follow a checkpoint directory being written by a live trainer
    (e.g. the streaming retrain loop): ``poll()`` returns a fresh
    Predictor when a newer complete step has appeared, else None.

    Assumes the architecture is fixed across steps (true for streaming —
    the model config freezes at the first refresh), so a mid-request swap
    only changes params/normalization stats, which are internally
    consistent within each Predictor.
    """

    def __init__(self, ckpt_dir: str, min_interval_s: float = 2.0,
                 ladder: tuple[int, ...] | None = None,
                 fused: bool = True, page_windows: int | None = None,
                 coalesce_pages: int | None = None,
                 coalesce_groups: int = 1,
                 sparse_feed: bool = False,
                 sparse_nnz_cap: int = 64,
                 mesh_config=None,
                 quant: str = "off"):
        from deeprest_tpu.train.checkpoint import latest_step

        self.ckpt_dir = ckpt_dir
        self.min_interval_s = min_interval_s
        self.ladder = ladder      # reloaded predictors keep the serving ladder
        self.fused = fused        # ... and the fused-inference config
        self.page_windows = page_windows
        self.coalesce_pages = coalesce_pages
        self.coalesce_groups = coalesce_groups
        self.sparse_feed = sparse_feed   # ... and the sparse-feed plane
        self.sparse_nnz_cap = sparse_nnz_cap
        self.mesh_config = mesh_config   # ... and the serving mesh (TP)
        self.quant = quant        # ... and the quant mode (parity-gated
        #                           per reload against the stored envelope)
        self._last_step = latest_step(ckpt_dir)
        self._next_check = 0.0
        self._pending = None       # loaded Predictor awaiting pickup
        self._loading = False
        self._lock = threading.Lock()

    def poll(self):
        import time

        from deeprest_tpu.train.checkpoint import latest_step

        # The seconds-long checkpoint load runs on a background thread —
        # the request that notices a new step must not stall on it (a
        # /healthz probe with a short timeout would flap on every refresh).
        # poll() itself only does cheap bookkeeping: hand over a finished
        # load, or kick one off.
        with self._lock:
            if self._pending is not None:
                fresh, self._pending = self._pending, None
                return fresh
            if self._loading:
                return None
            now = time.monotonic()
            if now < self._next_check:
                return None
            self._next_check = now + self.min_interval_s
        # The directory listing stays OUTSIDE the lock: on a slow filesystem
        # (NFS/gcsfuse checkpoint dirs) a listing held under the lock would
        # serialize every concurrent request behind it.
        step = latest_step(self.ckpt_dir)
        with self._lock:
            if self._loading or step is None or step == self._last_step:
                return None
            self._loading = True
        threading.Thread(target=self._load, args=(step,), daemon=True).start()
        return None

    def _load(self, step: int) -> None:
        from deeprest_tpu.serve.predictor import Predictor

        fresh = None
        try:
            fresh = Predictor.from_checkpoint(
                self.ckpt_dir, step=step, ladder=self.ladder,
                fused=self.fused, page_windows=self.page_windows,
                coalesce_pages=self.coalesce_pages,
                coalesce_groups=self.coalesce_groups,
                sparse_feed=self.sparse_feed,
                sparse_nnz_cap=self.sparse_nnz_cap,
                mesh_config=self.mesh_config,
                quant=self.quant)
        except Exception as e:
            # Mid-write/pruned steps are expected (FileNotFoundError/
            # ValueError); anything else is logged but must never wedge
            # the reloader — _loading MUST be cleared or the server would
            # silently never reload again.  A violated quant parity
            # envelope is a ValueError subclass but is NEVER benign: the
            # new step's quantized weights fall outside the pinned
            # budget, the server keeps serving the old step, and the
            # operator must hear about it.
            from deeprest_tpu.ops.quantize import QuantParityError

            if isinstance(e, QuantParityError) or not isinstance(
                    e, (FileNotFoundError, ValueError)):
                import sys

                print(f"checkpoint reload of step {step} failed: {e!r}",
                      file=sys.stderr)
        finally:
            with self._lock:
                if fresh is not None:
                    self._last_step = step
                    self._pending = fresh
                self._loading = False


def _as_array(payload: dict, key: str, ndim: int) -> np.ndarray:
    if key not in payload:
        raise ServingError(f"missing field {key!r}")
    try:
        arr = np.asarray(payload[key], dtype=np.float32)
    except (TypeError, ValueError) as e:
        raise ServingError(f"field {key!r} is not numeric: {e}") from None
    if arr.ndim != ndim:
        raise ServingError(f"field {key!r} must be {ndim}-d, got {arr.ndim}-d")
    return arr


class PredictionService:
    """Route handlers over a serving backend (Predictor or
    ExportedPredictor) — transport-free, so tests can call it directly.

    ``reloader`` (optional) makes the service follow a live training
    process: before each request it is asked for a fresh backend (or None
    to keep the current one) — see :class:`CheckpointReloader`.

    ``batching`` (optional :class:`~deeprest_tpu.serve.batcher.BatcherConfig`)
    attaches a cross-request MicroBatcher to the backend: windows from
    concurrent requests coalesce into shared device batches.  None (the
    default) keeps the per-request dispatch path — each request still
    goes through the backend's shape ladder, so the jit cache stays
    rung-bounded either way.

    ``surface`` (optional :class:`~deeprest_tpu.config.SurfaceConfig`
    with ``enabled=True``) attaches the capacity-surface plane
    (serve/surface.py): in-space ``/v1/whatif`` reads answer by
    interpolation over precomputed surfaces, ``/v1/whatif/surface``
    serves sweep-style peak queries, and every backend reload
    invalidates the cache eagerly with its reason label.
    """

    def __init__(self, predictor, synthesizer=None, backend: str = "",
                 reloader=None, batching: BatcherConfig | None = None,
                 surface=None):
        self.backend = backend
        self._synthesizer = synthesizer
        self._reloader = reloader
        # HTTP-plane metrics (per-service objects, exposed replace-by-name
        # into the default registry so the newest plane owns /metrics).
        self._m_requests = obs_metrics.REGISTRY.expose(obs_metrics.Counter(
            "deeprest_http_requests_total",
            "requests by route and status code",
            labelnames=("route", "code")))
        self._m_latency = obs_metrics.REGISTRY.expose(obs_metrics.Histogram(
            "deeprest_http_request_seconds",
            "wall time handling a request, by route",
            labelnames=("route",)))
        # Guards the SWAPPABLE serving state below: ThreadingHTTPServer
        # runs every request on its own thread, and maybe_reload() swaps
        # these mid-flight (found by graftlint TH001: /healthz read the
        # reload counter and backend refs while maybe_reload wrote them).
        # Handlers snapshot the references under the lock and then work
        # on locals, so no device dispatch ever runs while holding it;
        # batcher drains (seconds) also happen OUTSIDE the lock.
        self._lock = threading.Lock()
        self.predictor = predictor
        self.reloads = 0
        self.batcher: MicroBatcher | None = None
        self.batching = None
        # Streaming verdict surface (obs/quality.py): attach_quality wires
        # a QualityMonitor (+ optional VerdictIngestor feeding it from the
        # collector JSONL); GET /v1/verdict renders its state.
        self.quality = None
        self._quality_ingestor = None
        # Wire firehose (data/wire.py): attach_wire registers a started
        # SpanFirehoseReceiver so /healthz renders its drop/backpressure
        # accounting.  Lifecycle stays with whoever polls it (the
        # VerdictIngestor's stop() closes its tailer).
        self._wire = None
        # Fleet tier (serve/fleet.py): attach_fleet installs a
        # PredictorPool — X-Tenant then selects the MODEL (pool entry),
        # not just the fairness bucket, on /v1/predict and /v1/verdict.
        self.fleet = None
        self.whatif = (WhatIfEstimator(predictor, synthesizer)
                       if synthesizer is not None else None)
        # Capacity-surface plane: needs the what-if pipeline (a surface
        # is built THROUGH the estimator), so it silently stays off
        # without a synthesizer — the CLI errors on that combination up
        # front.
        self.surface = (CapacitySurfaceManager(surface)
                        if surface is not None
                        and getattr(surface, "enabled", False)
                        and self.whatif is not None else None)
        if batching is not None:
            self.enable_batching(batching)
        # Registered LAST: the render-time collector snapshots state the
        # lines above create (replace-by-name — the newest plane owns the
        # /metrics exposition).
        obs_metrics.REGISTRY.register_collector(
            "serving", self._collect_metrics)

    # -- swappable-state management (all writes under self._lock) --------

    def _snapshot(self):
        """One consistent view of the serving backend for a request:
        ``(predictor, whatif, batcher, reloads)``.  A reload that lands
        mid-request affects the NEXT request; this one keeps serving the
        internally-consistent backend it started with."""
        with self._lock:
            return self.predictor, self.whatif, self.batcher, self.reloads

    def enable_batching(self, config: BatcherConfig) -> None:
        """(Re)build the cross-request MicroBatcher over the current
        backend's shape ladder and route its traffic through it.

        A multi-replica router backend owns one batcher PER replica, so
        the config is delegated there and the service-level batcher slot
        stays empty (``/healthz`` reports per-replica batcher stats under
        the ``router`` key instead)."""
        with self._lock:
            pred = self.predictor
        if hasattr(pred, "replicas"):          # ReplicaRouter backend
            pred.enable_batching(config)
            with self._lock:
                self.batching = config
            return
        fresh = MicroBatcher(pred.ladder, config)
        pred.attach_batcher(fresh)
        with self._lock:
            old, self.batcher = self.batcher, fresh
            self.batching = config
        if old is not None:
            old.close()               # drain outside the lock

    def attach_fleet(self, pool) -> None:
        """Wire the fleet tier: ``pool`` (serve/fleet.PredictorPool)
        resolves ``X-Tenant`` to a per-tenant predictor on /v1/predict,
        serves per-tenant verdicts on /v1/verdict, and reports under the
        /healthz ``fleet`` key.  A router backend learns the pool too,
        so tenant resolution happens exactly once per request — on the
        dispatch path, inside the router."""
        with self._lock:
            pred = self.predictor
        attach = getattr(pred, "attach_fleet", None)
        if callable(attach):
            attach(pool)
        with self._lock:
            self.fleet = pool

    @staticmethod
    def _fleet_entry(pool, tenant: str | None, touch: bool):
        """Tenant → pool entry, as HTTP: 404 for a tenant the pool never
        admitted.  ``touch`` picks the dispatch-path resolve (LRU touch +
        restore-if-spilled) vs the metadata peek — metadata reads must
        not perturb the eviction order, and the router path resolves
        inside the router, so the service only ever PEEKS there (one
        touch per request, never two)."""
        from deeprest_tpu.serve.fleet import UnknownTenantError

        try:
            return pool.resolve(tenant) if touch else pool.peek(tenant)
        except UnknownTenantError as exc:
            raise ServingError(
                f"unknown tenant {exc.args[0]!r}: not admitted to the "
                "fleet pool", status=404) from None

    def attach_quality(self, monitor, ingestor=None) -> None:
        """Wire the streaming verdict surface: ``monitor`` backs
        ``GET /v1/verdict`` (and the deeprest_quality_* /metrics gauges
        it publishes); ``ingestor`` (a started VerdictIngestor) is owned
        by the service from here — close() stops it."""
        with self._lock:
            self.quality = monitor
            old, self._quality_ingestor = self._quality_ingestor, ingestor
        if old is not None:
            old.stop()

    def attach_wire(self, receiver) -> None:
        """Register a started SpanFirehoseReceiver (data/wire.py) for
        observability: /healthz gains an additive ``wire`` key with its
        span/drop/backpressure accounting.  The receiver's lifecycle is
        NOT owned here — its poller (the VerdictIngestor) closes it."""
        with self._lock:
            self._wire = receiver

    def close(self) -> None:
        """Release the batcher's worker thread (idempotent).  Tolerates
        minimal test/protocol backends that implement only the read-side
        serving surface (``predict_series`` + metadata) and carry no
        batcher attachment point or replica plane."""
        # Drop our render-time collector (conditionally — a rebuilt
        # service re-registers the name): a registered bound method in
        # the process-wide registry pins the closed service, its
        # predictor stack, and the device buffers behind it forever.
        obs_metrics.REGISTRY.unregister_collector("serving",
                                                  self._collect_metrics)
        with self._lock:
            old, self.batcher = self.batcher, None
            self.batching = None
            pred = self.predictor
            ingestor, self._quality_ingestor = self._quality_ingestor, None
            surface, self.surface = self.surface, None
        if surface is not None:
            surface.close()       # join warm-builder threads
        if ingestor is not None:
            ingestor.stop()
        detach = getattr(pred, "attach_batcher", None)
        if callable(detach):
            detach(None)
        if old is not None:
            old.close()
        shutdown = getattr(pred, "close", None)   # router: drain replicas
        if callable(shutdown):
            shutdown()

    def maybe_reload(self) -> None:
        """Swap in a newer backend if the reloader has one (serving a
        continuously-retrained checkpoint dir must not go stale)."""
        if self._reloader is None:
            return
        fresh = self._reloader.poll()
        if fresh is None:
            return
        self.reload_from(fresh, reason="watch")

    def reload_from(self, fresh, reason: str = "manual") -> None:
        """Swap in ``fresh`` NOW.  ``reason`` labels the reload end to
        end: the router's per-reason reload counter, and the capacity-
        surface invalidation it forces — "watch" for the checkpoint-dir
        cadence, "drift" when the DriftController pulled the trigger,
        "manual" for operator swaps.

        The surface cache is bracketed around the swap (``begin_reload``
        → swap → ``end_reload``): while the backend is mid-swap no
        cached surface is readable, and afterwards the store is empty —
        so no response can ever interpolate a surface built from
        pre-reload params (the round-13 no-mixed-params discipline,
        extended to cached answers).  Drift-triggered reloads therefore
        invalidate EAGERLY, not on next touch.
        """
        with self._lock:
            current = self.predictor
            surface = self.surface
        if surface is not None:
            surface.begin_reload()
        try:
            self._swap_backend(current, fresh, reason)
        finally:
            if surface is not None:
                surface.end_reload(reason=reason)

    def _swap_backend(self, current, fresh, reason: str) -> None:
        if hasattr(current, "rolling_reload_from"):
            # Multi-replica router: drain and re-image one replica at a
            # time (zero downtime; no request ever observes mixed old/new
            # params — each request is served end-to-end by the single
            # backend its replica held when it was dispatched).
            fresh_whatif = (WhatIfEstimator(current, self._synthesizer)
                            if self._synthesizer is not None else None)
            current.rolling_reload_from(fresh, reason=reason)
            with self._lock:
                self.whatif = fresh_whatif
                self.reloads += 1
            return
        # Build the fresh backend's batcher/estimator BEFORE publishing,
        # so other threads only ever see fully-wired backends; the old
        # batcher drains and closes after the swap — a request that
        # raced the swap falls back to the direct laddered path
        # (BatcherClosed is handled in apply_windows).
        with self._lock:
            batching = self.batching
        fresh_batcher = None
        if batching is not None:
            fresh_batcher = MicroBatcher(fresh.ladder, batching)
            fresh.attach_batcher(fresh_batcher)
        fresh_whatif = (WhatIfEstimator(fresh, self._synthesizer)
                        if self._synthesizer is not None else None)
        with self._lock:
            old, self.batcher = self.batcher, fresh_batcher
            self.predictor = fresh
            self.whatif = fresh_whatif
            self.reloads += 1
        if old is not None:
            old.close()

    # -- GET ------------------------------------------------------------

    def admission(self, tenant: str | None):
        """Admission gate for one POST request: the router backend meters
        in-flight requests globally and per tenant (fast 429 +
        ``Retry-After`` when the plane is saturated); single-engine
        backends admit everything.  The HTTP handler enters this BEFORE
        parsing the request body, so shed load costs the plane a header
        read, not a JSON parse — overload rejection must stay cheap or
        the 429 path itself collapses the host."""
        with self._lock:
            pred = self.predictor
        admit = getattr(pred, "admit", None)
        if callable(admit):
            return admit(tenant)
        import contextlib

        return contextlib.nullcontext()

    def _note_request(self, route: str, status: int) -> None:
        """One row in the HTTP request counter (called by the handler as
        each response is written; metric objects carry their own locks)."""
        self._m_requests.inc(route=route, code=str(status))

    def _observe_latency(self, route: str, stopwatch) -> None:
        stopwatch.observe_into(self._m_latency, route=route)

    def _collect_metrics(self, sink) -> None:
        """Render-time /metrics view of serving state already counted
        elsewhere (reload counter, batcher queue, fused-engine pages, jit
        cache) — no hot-path cost, one source of truth with /healthz."""
        pred, _, batcher, reloads = self._snapshot()
        sink.counter("deeprest_serving_reloads_total", reloads,
                     help="backend hot reloads")
        if batcher is not None:
            s = batcher.stats()
            sink.gauge("deeprest_batcher_queue_windows",
                       s["queue_depth_windows"],
                       help="windows pending in the micro-batcher queue")
        fused = getattr(pred, "fused", None)
        if fused is not None:
            s = fused.stats()
            sink.counter("deeprest_fused_pages_total", s["pages"],
                         help="fused rolled-inference pages dispatched")
            sink.counter("deeprest_fused_windows_total", s["windows"],
                         help="windows through the fused engine")
        cache = getattr(pred, "jit_cache_size", None)
        if callable(cache):
            n = cache()
            if n is not None:
                sink.gauge("deeprest_plane_jit_executables", n,
                           help="compiled executables across distinct "
                                "stacks")
        rec = obs_spans.RECORDER.stats()
        sink.gauge("deeprest_obs_spans_retained", rec["retained"],
                   help="spans currently in the recorder ring")
        sink.counter("deeprest_obs_spans_recorded_total", rec["recorded"],
                     help="spans committed since process start")
        with self._lock:
            pool = self.fleet
        if pool is not None:
            s = pool.stats()
            sink.gauge("deeprest_fleet_tenants", s["tenants"],
                       help="tenants admitted to the predictor pool")
            sink.gauge("deeprest_fleet_resident_tenants", s["resident"],
                       help="tenants with device-resident params (<= "
                            "hbm_budget)")
            sink.counter("deeprest_fleet_spills_total", s["spills"],
                         help="tenant weight sets spilled to host memory")
            sink.counter("deeprest_fleet_restores_total", s["restores"],
                         help="tenant weight sets restored by device_put")
            sink.counter("deeprest_fleet_aot_loaded_total",
                         s["aot"]["loaded"],
                         help="AOT executables deserialized at admission")
            sink.counter("deeprest_fleet_compile_fallbacks_total",
                         s["aot"]["compile_fallbacks"],
                         help="admissions that had to compile (missing or "
                              "stale AOT artifact)")
            # Per-tenant quality gauges, DISTINCT names from the global
            # deeprest_quality_* family (those carry a ``metric`` label;
            # these roll metrics up per tenant) — cardinality bounded to
            # the top-K tenants by serve count + one __other__ row.
            for label, q in pool.quality_rollup():
                labels = {"tenant": label}
                sink.counter("deeprest_quality_tenant_sweeps_total",
                             q["sweeps"],
                             help="quality sweeps per tenant",
                             labels=labels)
                sink.gauge("deeprest_quality_tenant_verdict", q["verdict"],
                           help="worst verdict state across the tenant's "
                                "metrics (0 ok, 1 drift, 2 anomaly)",
                           labels=labels)
                sink.gauge("deeprest_quality_tenant_anomaly_score",
                           q["anomaly_score"],
                           help="worst anomaly score across the tenant's "
                                "metrics", labels=labels)
                if q["coverage"] is not None:
                    sink.gauge("deeprest_quality_tenant_band_coverage",
                               q["coverage"],
                               help="mean q-band coverage across the "
                                    "tenant's metrics", labels=labels)
                if q["pinball"] is not None:
                    sink.gauge("deeprest_quality_tenant_pinball_loss",
                               q["pinball"],
                               help="mean pinball loss across the "
                                    "tenant's metrics", labels=labels)

    def metrics_text(self) -> str:
        """The Prometheus text exposition (``GET /metrics``)."""
        return obs_metrics.REGISTRY.render()

    def spans_jaeger(self) -> dict:
        """Retained spans as Jaeger query-API JSON (``GET /v1/spans``) —
        the payload ``deeprest ingest --traces`` consumes for the
        self-ingestion loop (obs/export.py)."""
        from deeprest_tpu.obs.export import spans_to_jaeger

        return spans_to_jaeger(obs_spans.RECORDER.snapshot())

    def profile(self, payload: dict) -> dict:
        """On-demand ``jax.profiler`` capture window (``POST
        /v1/profile``): the handler blocks for the window while the other
        handler threads keep serving — the trace captures the plane under
        its live load.  One window at a time (409 when busy)."""
        import tempfile

        from deeprest_tpu.obs import profiler

        try:
            seconds = float(payload.get("seconds", 1.0))
        except (TypeError, ValueError) as e:
            raise ServingError(f"bad seconds: {e}") from None
        out_dir = payload.get("out_dir") or tempfile.mkdtemp(
            prefix="deeprest-profile-")
        try:
            return profiler.capture(out_dir, seconds)
        except profiler.ProfilerBusy as e:
            raise ServingError(str(e), status=409) from None
        except ValueError as e:
            raise ServingError(str(e)) from None

    def healthz(self) -> dict:
        pred, _, batcher, reloads = self._snapshot()
        out = {
            "ok": True,
            "backend": self.backend,
            "num_metrics": len(pred.metric_names),
            "window_size": pred.window_size,
            "reloads": reloads,
        }
        router_stats = getattr(pred, "router_stats", None)
        if callable(router_stats):
            # replica plane observability: per-replica outstanding work,
            # admission counters, per-tenant grants, autoscaler decision
            # (additive key; existing wire fields untouched)
            out["router"] = router_stats()
        # Queue depth + shape-ladder hit stats ride on the liveness probe
        # (additive keys: the wire protocol's existing fields are
        # untouched).  Batching disabled still reports the backend's
        # ladder so compile behavior is observable either way.
        if batcher is not None:
            out["batcher"] = batcher.stats()
        elif getattr(pred, "ladder", None) is not None:
            out["batcher"] = None
            out["shape_ladder"] = pred.ladder.stats()
        fused = getattr(pred, "fused", None)
        if fused is not None:
            # page/dispatch counters of the fused rolled-inference engine
            # (additive key; the wire protocol's existing fields are
            # untouched)
            out["fused_infer"] = fused.stats()
        # quantized-serving surface (additive key): the active quant
        # mode plus the stored parity envelope's worst measured cell —
        # operators see at a glance whether this plane serves narrow
        # weights and how far from the f32 reference it sits
        quant = getattr(pred, "quant", "off")
        envelope = getattr(pred, "parity_envelope", None)
        out["quant"] = {"mode": quant}
        if envelope is not None:
            measured = envelope.get("measured", {})
            out["quant"]["parity_max"] = (max(measured.values())
                                          if measured else None)
            out["quant"]["parity_cells"] = len(measured)
        # Wire firehose accounting (additive key): span/batch/drop/
        # backpressure totals of an attached push receiver — the same
        # counter shapes the obs registry exports at /metrics, so the
        # two views stay consistent (tests/test_wire.py pins it).
        with self._lock:
            wire = self._wire
        if wire is not None:
            out["wire"] = wire.stats()
        # Fleet view (additive key): per-tenant {quant, params_digest,
        # resident} instead of the single global pair above — existing
        # key shapes untouched.  With a pool attached it is the pool's
        # live map + counters; without one it is a one-tenant view over
        # the SAME objects the global keys render (round-14 style), so
        # consumers can read fleet.tenants[...] unconditionally.
        with self._lock:
            pool = self.fleet
        if pool is not None:
            out["fleet"] = {"tenants": pool.tenant_meta(
                limit=pool.top_k_tenants), "pool": pool.stats()}
        else:
            digest = getattr(pred, "params_digest", None)
            out["fleet"] = {"tenants": {"default": {
                "quant": out["quant"]["mode"],
                "params_digest": digest() if callable(digest) else None,
                "resident": True,
            }}, "pool": None}
        # span-recorder health (additive key): enabled flag, ring
        # retention, eviction pressure — the JSON twin of the /metrics
        # deeprest_obs_* gauges
        out["obs"] = obs_spans.RECORDER.stats()
        with self._lock:
            quality = self.quality
        if quality is not None:
            # model-quality surface summary (additive key; the full
            # per-metric verdict table lives at GET /v1/verdict)
            v = quality.verdicts()
            out["quality"] = {"armed": v.get("armed", False),
                              "sweeps": v.get("sweeps", 0),
                              "states": v.get("states")}
        with self._lock:
            surface = self.surface
        if surface is not None:
            # capacity-surface plane: resident set, byte budget, hit/
            # miss/build/invalidation ledger, measured parity envelope
            # (additive key; absent when the plane is off)
            out["surface"] = surface.stats()
        return out

    def verdict(self, tenant: str | None = None) -> dict:
        """``GET /v1/verdict`` — the streaming per-(component,resource)
        ``ok|drift|anomaly`` surface (obs/quality.py), replacing the
        batch-only anomaly CLI path for live planes.  503 when no monitor
        is attached (serve with --verdict-raw).

        With a fleet pool attached, ``X-Tenant`` selects the tenant's OWN
        monitor (one per pool entry) — the verdict surface is per-model
        state, so it must never blend tenants."""
        with self._lock:
            pool = self.fleet
        if pool is not None:
            entry = self._fleet_entry(pool, tenant, touch=False)
            monitor = entry.quality()
            if monitor is None:
                raise ServingError(
                    f"tenant {entry.tenant!r} has no quality monitor: "
                    "build the pool with quality enabled "
                    "(FleetConfig.quality)", status=503)
            out = monitor.verdicts()
            out["tenant"] = {"name": entry.tenant,
                             "params_digest": entry.key[1],
                             "invalidations": entry.invalidations()}
            return out
        with self._lock:
            quality = self.quality
        if quality is None:
            raise ServingError(
                "no quality monitor attached: start the server with "
                "--verdict-raw <collector jsonl> (or attach_quality) to "
                "enable the streaming verdict surface", status=503)
        out = quality.verdicts()
        # The quant parity envelope joins the verdict surface (additive
        # key): it is a model-quality contract — per-(metric, quantile)
        # measured deviation vs the f32 reference and the stored budget
        # it is gated against at every (re)load.
        pred, _, _, _ = self._snapshot()
        envelope = getattr(pred, "parity_envelope", None)
        if envelope is not None:
            out["quant_parity"] = {
                "mode": getattr(pred, "quant", "off"),
                "measured": dict(envelope.get("measured", {})),
                "budget": dict(envelope.get("budget", {})),
            }
        return out

    def meta(self) -> dict:
        pred, whatif, _, _ = self._snapshot()
        return {
            "backend": self.backend,
            "metric_names": pred.metric_names,
            "quantiles": list(pred.quantiles),
            "window_size": pred.window_size,
            "feature_dim": pred.feature_dim,
            "whatif_endpoints": (whatif.endpoints
                                 if whatif is not None else None),
        }

    # -- POST -----------------------------------------------------------

    @staticmethod
    def _traffic_array(payload: dict, pred) -> np.ndarray:
        traffic = _as_array(payload, "traffic", 2)
        if traffic.shape[1] != pred.feature_dim:
            raise ServingError(
                f"traffic feature dim {traffic.shape[1]} != model "
                f"{pred.feature_dim}")
        if len(traffic) < pred.window_size:
            raise ServingError(
                f"traffic length {len(traffic)} < window_size "
                f"{pred.window_size}")
        return traffic

    def predict(self, payload: dict, tenant: str | None = None) -> dict:
        pred, _, _, _ = self._snapshot()
        with self._lock:
            pool = self.fleet
        if pool is not None:
            # Fleet tier: X-Tenant selects the MODEL.  Router backends
            # resolve tenant → entry themselves (on the dispatch path,
            # exactly once); the service peeks only for the response
            # metadata.  Single-engine backends resolve here.
            router = callable(getattr(pred, "attach_fleet", None))
            entry = self._fleet_entry(pool, tenant, touch=not router)
            model = entry.predictor()
            traffic = self._traffic_array(payload, model)
            preds = (pred.predict_series(traffic, tenant=tenant)
                     if router else model.predict_series(traffic))
            pred = model               # response metadata is per-tenant
        else:
            traffic = self._traffic_array(payload, pred)
            preds = pred.predict_series(traffic)              # [T, E, Q]
        dm = getattr(pred, "delta_mask", None)
        out = {
            "metric_names": pred.metric_names,
            "quantiles": list(pred.quantiles),
            "predictions": preds.tolist(),
            # Delta-trained metrics are a RELATIVE (rollout-from-zero)
            # level series — clients must re-anchor them to an observed
            # level before treating values as absolute utilization.
            "relative_metrics": [
                m for e, m in enumerate(pred.metric_names)
                # graftlint: disable=JX003 -- host data: dm is the numpy delta mask, not a device array
                if dm is not None and bool(dm[e])
            ],
        }
        if pool is not None:
            # additive key: which pool entry answered (tenant +
            # params_digest) — clients can pin responses to a weight
            # generation across hot-swaps
            out["tenant"] = {"name": entry.tenant,
                             "params_digest": entry.key[1]}
        return out

    def _require_whatif(self, whatif) -> WhatIfEstimator:
        if whatif is None:
            raise ServingError(
                "what-if estimation unavailable: server started without a "
                "corpus to fit the trace synthesizer (--raw)", status=503)
        return whatif

    @staticmethod
    def _traffic_program(payload: dict, key: str, pred) -> list[dict]:
        prog = payload.get(key)
        if (not isinstance(prog, list) or not prog
                or not all(isinstance(p, dict) for p in prog)):
            raise ServingError(
                f"field {key!r} must be a non-empty list of "
                "{endpoint: count} objects")
        if len(prog) < pred.window_size:
            raise ServingError(
                f"{key!r} length {len(prog)} < window_size "
                f"{pred.window_size}")
        return prog

    @staticmethod
    def _seed(payload: dict) -> int:
        try:
            return int(payload.get("seed", 0))
        except (TypeError, ValueError) as e:
            raise ServingError(f"bad seed: {e}") from None

    def whatif_estimate(self, payload: dict) -> dict:
        pred, whatif, _, _ = self._snapshot()
        est = self._require_whatif(whatif)
        prog = self._traffic_program(payload, "expected_traffic", pred)
        with self._lock:
            surface = self.surface
        if surface is not None:
            # Capacity-surface interception: a program that is an
            # int-rounded scaling of a cached surface's base answers by
            # interpolation (microseconds, no dispatch).  The response
            # grows an additive "surface" key; the existing wire fields
            # are untouched.  Misses warm a surface anchored at this
            # program so the NEXT scaled variant hits.
            hit = surface.lookup_program(pred, prog,
                                         seed=self._seed(payload))
            if hit is not None:
                series_arr, meta = hit
                return {"estimates": self._bands_payload(est, series_arr),
                        "surface": meta}
            surface.note_miss()
            surface.maybe_warm(pred, est, prog, seed=self._seed(payload))
        try:
            series = est.estimate(prog, seed=self._seed(payload))
        except KeyError as e:   # unknown endpoint in the traffic program
            raise ServingError(str(e)) from None
        out = {"estimates": {
            metric: {q: v.tolist() for q, v in bands.items()}
            for metric, bands in series.items()
        }}
        if surface is not None:
            out["surface"] = {"hit": False}
        return out

    @staticmethod
    def _bands_payload(est, series_arr) -> dict:
        # one C-level transpose+tolist instead of metrics*quantiles
        # slice/tolist pairs — same payload as est._bands + tolist,
        # on the cached read path's serialization budget
        nested = np.asarray(series_arr).transpose(1, 2, 0).tolist()
        pred = est.predictor
        qkeys = [f"q{int(q * 100):02d}" for q in pred.quantiles]
        return {metric: dict(zip(qkeys, rows))
                for metric, rows in zip(pred.metric_names, nested)}

    def whatif_surface(self, payload: dict) -> dict:
        """``POST /v1/whatif/surface`` — sweep-semantics peaks at one
        point of a mix space around ``base_traffic`` (``scales`` per
        endpoint or a uniform ``factor``), answered from the capacity
        surface when resident (building it synchronously when ``wait``
        is set) and from a direct frontier estimate otherwise."""
        pred, whatif, _, _ = self._snapshot()
        est = self._require_whatif(whatif)
        with self._lock:
            surface = self.surface
        if surface is None:
            raise ServingError(
                "capacity surfaces disabled: start the server with "
                "--surface (requires --raw for the trace synthesizer)",
                status=503)
        base = self._traffic_program(payload, "base_traffic", pred)
        try:
            return surface.query(
                pred, est, base,
                scales=payload.get("scales"),
                factor=payload.get("factor"),
                seed=self._seed(payload),
                wait=bool(payload.get("wait", False)))
        except (KeyError, ValueError) as e:
            if isinstance(e, ServingError):
                raise
            raise ServingError(str(e)) from None

    def whatif_scaling(self, payload: dict) -> dict:
        pred, whatif, _, _ = self._snapshot()
        est = self._require_whatif(whatif)
        base = self._traffic_program(payload, "baseline_traffic", pred)
        hypo = self._traffic_program(payload, "hypothetical_traffic", pred)
        try:
            factors = est.scaling_factor(base, hypo, seed=self._seed(payload))
        except KeyError as e:   # unknown endpoint in either program
            raise ServingError(str(e)) from None
        return {"scaling_factors": factors}

    def anomaly(self, payload: dict) -> dict:
        pred, _, _, _ = self._snapshot()
        traffic = self._traffic_array(payload, pred)
        observed = _as_array(payload, "observed", 2)
        if len(traffic) != len(observed):
            raise ServingError("traffic and observed must have equal length")
        if observed.shape[1] != len(pred.metric_names):
            raise ServingError(
                f"observed has {observed.shape[1]} metrics, model has "
                f"{len(pred.metric_names)}")
        try:
            tolerance = float(payload.get("tolerance", 0.10))
            min_run = int(payload.get("min_run", 5))
        except (TypeError, ValueError) as e:
            raise ServingError(f"bad tolerance/min_run: {e}") from None
        detector = AnomalyDetector(pred, tolerance=tolerance,
                                   min_run=min_run)
        reports = detector.check(traffic, observed)
        return {"reports": [{
            "metric": r.metric,
            "score": r.score,
            "flagged": r.flagged,
            "first_flag_index": r.first_flag_index,
        } for r in reports], "flagged": [r.metric for r in reports if r.flagged]}


class VerdictIngestor:
    """Feed the serving plane's QualityMonitor from the collector's raw
    JSONL — the serve-side half of the streaming verdict surface.

    A daemon thread tails the same growing file the streaming trainer
    tails (train/stream.BucketTailer), featurizes each bucket against the
    SERVED model's call-path space (``predictor.space()`` — column-exact
    with training by construction), and feeds the monitor; every
    ``sweep_every_buckets`` buckets it runs a quality sweep THROUGH the
    current serving backend snapshot (single predictor or the replica
    router — the sweep's model calls ride the ordinary dispatch path, so
    the ≤3% monitor budget covers real serving cost).

    Reference handling: the drift reference auto-arms from the first
    ``live_window`` tailed buckets ("the stream you trusted at attach
    time"), and RE-ANCHORS whenever the service hot-reloads a new
    checkpoint (the fresh params trained on recent data, so recent data
    is the new no-drift baseline) — which also restarts the
    model-conditioned calibration/anomaly streams via
    ``on_model_refresh``, making post-reload band-coverage recovery
    visible instead of averaged into the stale model's tail.
    """

    def __init__(self, service: PredictionService, tailer, space, monitor,
                 poll_interval_s: float = 0.5):
        self._service = service
        self._tailer = tailer               # ingestor-thread-owned
        self._space = space
        self.monitor = monitor              # carries its own lock
        self._poll_interval_s = float(poll_interval_s)
        self._stop = threading.Event()
        # Guards the error counter (read by tests/healthz from handler
        # threads while the ingestor thread increments) and the thread
        # handle across start/stop (TH001 discipline).
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._errors = 0

    def start(self) -> "VerdictIngestor":
        t = threading.Thread(target=self._loop, daemon=True,
                             name="deeprest-verdict-ingest")
        with self._lock:
            self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        close = getattr(self._tailer, "close", None)
        if callable(close):
            close()

    @property
    def errors(self) -> int:
        with self._lock:
            return self._errors

    # -- the loop (ingestor thread only; cross-iteration state lives in
    # locals so nothing here is shared off-lock) -------------------------

    def _loop(self) -> None:
        since_sweep = 0
        last_reloads: int | None = None
        while not self._stop.is_set():
            try:
                got = self._tailer.poll()
                for bucket in got:
                    cols, vals = self._space.extract_sparse(bucket.traces)
                    self.monitor.observe(
                        cols, vals,
                        {m.key: m.value for m in bucket.metrics})
                    since_sweep += 1
                last_reloads = self._maybe_rebase(last_reloads)
                if (self.monitor.drift.ready and since_sweep
                        >= self.monitor.config.sweep_every_buckets):
                    since_sweep = 0
                    pred = self._service._snapshot()[0]
                    self.monitor.sweep(pred)
            except Exception as exc:
                # A malformed bucket or a mid-reload model error must not
                # kill the surface; count it (scrapeable) and keep
                # tailing — the first occurrence is printed for triage.
                with self._lock:
                    self._errors += 1
                    first = self._errors == 1
                obs_metrics.REGISTRY.counter(
                    "deeprest_verdict_ingest_errors_total",
                    "verdict-ingest loop errors (kept running)").inc()
                if first:
                    print(f"verdict-ingest: {type(exc).__name__}: {exc}")
            if not getattr(self._tailer, "backlog", False):
                self._stop.wait(self._poll_interval_s)

    def _maybe_rebase(self, last_reloads: int | None) -> int:
        cfg = self.monitor.config
        reloads = self._service._snapshot()[3]   # lock-protected read
        if last_reloads is not None and reloads != last_reloads:
            # a fresh checkpoint rolled in: recent traffic is the new
            # no-drift baseline, and calibration/anomaly restart against
            # the fresh band
            if self.monitor.observed_buckets >= cfg.min_sweep_buckets:
                self.monitor.rebase_reference()
            self.monitor.on_model_refresh()
            return reloads
        if (not self.monitor.drift.ready
                and self.monitor.observed_buckets >= cfg.live_window):
            self.monitor.rebase_reference()     # auto-arm
        return reloads


_GET_ROUTES = {"/healthz": "healthz", "/v1/meta": "meta",
               "/v1/spans": "spans_jaeger", "/v1/verdict": "verdict"}
_POST_ROUTES = {
    "/v1/predict": "predict",
    "/v1/whatif": "whatif_estimate",
    "/v1/whatif/scaling": "whatif_scaling",
    "/v1/whatif/surface": "whatif_surface",
    "/v1/anomaly": "anomaly",
}
# Ops routes skip the admission gate: shedding a profiler request under
# serving overload would make the plane unobservable exactly when it is
# interesting, and a capture window must not hold an admission slot for
# its whole (seconds-long) duration.
_POST_OPS_ROUTES = {"/v1/profile": "profile"}


class PredictionServer:
    """ThreadingHTTPServer wrapper owning a PredictionService.

    >>> srv = PredictionServer(service, port=0).start()
    >>> ... http requests against srv.address ...
    >>> srv.stop()

    ``batching`` forwards a :class:`BatcherConfig` to the service (the
    CLI's knob surface); None leaves the service's own setting alone.
    """

    def __init__(self, service: PredictionService, host: str = "127.0.0.1",
                 port: int = 0, batching: BatcherConfig | None = None):
        self.service = service
        if batching is not None:
            service.enable_batching(batching)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                pass

            def _reply(self, status: int, body: dict,
                       headers: dict | None = None):
                self._reply_raw(status, json.dumps(body).encode(),
                                "application/json", headers)

            def _reply_raw(self, status: int, blob: bytes,
                           content_type: str,
                           headers: dict | None = None):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(blob)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(blob)
                outer.service._note_request(self.path, status)

            def do_GET(self):
                if self.path == "/metrics":
                    # Prometheus text exposition (0.0.4) — the scrape
                    # target the reference deploys a whole Prometheus to
                    # feed from (deploy/README.md has the scrape-config
                    # snippet for this plane).
                    try:
                        return self._reply_raw(
                            200, outer.service.metrics_text().encode(),
                            obs_metrics.PROMETHEUS_CONTENT_TYPE)
                    except Exception as e:
                        return self._reply(500, {"error": f"internal: {e}"})
                name = _GET_ROUTES.get(self.path)
                if name is None:
                    return self._reply(404, {"error": f"no route {self.path}"})
                try:
                    outer.service.maybe_reload()
                    if name == "verdict":
                        # the verdict surface is per-tenant under a
                        # fleet pool — same header as the WRR front
                        body = outer.service.verdict(
                            self.headers.get("X-Tenant"))
                    else:
                        body = getattr(outer.service, name)()
                    self._reply(200, body)
                except ServingError as e:   # e.g. /v1/verdict unattached
                    self._reply(e.status, {"error": str(e)},
                                headers=e.headers)
                except Exception as e:  # never drop the connection silently
                    self._reply(500, {"error": f"internal: {e}"})

            def do_POST(self):
                ops_name = _POST_OPS_ROUTES.get(self.path)
                name = ops_name or _POST_ROUTES.get(self.path)
                if name is None:
                    return self._reply(404, {"error": f"no route {self.path}"})
                sw = obs_metrics.Stopwatch()
                try:
                    # the request-scoped trace root: every span recorded
                    # below it (router dispatch, replica, batcher worker,
                    # fused engine — across threads and worker processes)
                    # shares this request's trace id
                    with obs_spans.RECORDER.span(
                            self.path,
                            component="deeprest-predictor") as root:
                        outer.service.maybe_reload()
                        length = int(self.headers.get("Content-Length", 0))
                        # the body must be drained either way (keep-alive
                        # framing), but it stays UNPARSED until admission:
                        # a shed request costs a read, not a JSON decode
                        raw = self.rfile.read(length)
                        # multi-tenant fairness key (weighted round-robin
                        # in the router's admission gate); absent header =
                        # the shared default tenant
                        tenant = self.headers.get("X-Tenant")
                        root.tag(tenant=tenant or "default")
                        if ops_name is not None:
                            # ops route: no admission gate (see
                            # _POST_OPS_ROUTES)
                            payload = json.loads(raw or b"{}")
                            if not isinstance(payload, dict):
                                raise ServingError(
                                    "request body must be a JSON object")
                            self._reply(
                                200, getattr(outer.service, name)(payload))
                        else:
                            with outer.service.admission(tenant):
                                payload = json.loads(raw or b"{}")
                                if not isinstance(payload, dict):
                                    raise ServingError(
                                        "request body must be a JSON object")
                                if name == "predict":
                                    # tenant → model under a fleet pool
                                    # (no pool: the kwarg is ignored)
                                    body = outer.service.predict(
                                        payload, tenant=tenant)
                                else:
                                    body = getattr(
                                        outer.service, name)(payload)
                                self._reply(200, body)
                except ServingError as e:
                    self._reply(e.status, {"error": str(e)},
                                headers=e.headers)
                except json.JSONDecodeError as e:
                    self._reply(400, {"error": f"bad JSON: {e}"})
                except Exception as e:  # handler bug: 500, not a dead socket
                    self._reply(500, {"error": f"internal: {e}"})
                finally:
                    outer.service._observe_latency(self.path, sw)

        class _Server(ThreadingHTTPServer):
            # The stdlib default listen backlog (5) drops SYNs when a
            # fleet of clients connects at once; the kernel's ~1s
            # retransmit then shows up as a phantom p99 latency cliff.
            request_queue_size = 128

        self._httpd = _Server((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> "PredictionServer":
        # graftlint: disable=TH001 -- lifecycle handle: start/stop run on the owning driver thread only, never in a request handler
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.service.close()       # drain + join the batcher worker
