"""Capacity surfaces: precomputed what-if answers with microsecond reads.

The reference ships its interactive demo over a PRECOMPUTED results
pickle (web-demo/dataloader.py) — the honest admission that users ask
capacity questions faster than models answer them.  This module makes
that precomputation a first-class serving subsystem instead of an
offline artifact, in the Clipper mold (PAPERS.md [2]): a cache and a
batching layer between the user and the model, so what-if rps decouples
from model latency.

Shape of the thing:

- A :class:`MixSpace` is a per-endpoint scale grid around one base
  traffic program (plus Monte-Carlo jitter probes for the parity
  envelope).  Its vertices are scaled copies of the base, built with the
  exact ``int(round(n * s))`` convention :meth:`WhatIfEstimator.sweep`
  uses, so a surface vertex IS a sweep point.
- Building a :class:`CapacitySurface` estimates every vertex and every
  jitter probe in ONE folded batch through
  ``WhatIfEstimator.estimate_many_raw`` — thousands of mixes amortize
  into the fused scenario×window device axis (serve/fused.py), paging
  through already-compiled executables.
- The surface stores per-(component, resource, quantile) prediction
  series as one host-resident float32 grid; queries inside the mix
  space answer by multilinear interpolation over that grid (no lock, no
  dispatch, microseconds).  Queries outside it fall back to a direct
  model call at the cache frontier while the surface warms
  asynchronously.
- :class:`CapacitySurfaceManager` holds surfaces in an LRU keyed by
  ``(params_hash, mix_space_hash)`` with bounded byte accounting, and
  invalidates EAGERLY on backend reloads (``begin_reload``/
  ``end_reload(reason=...)`` bracketing ``rolling_reload_from``): the
  reason label — "watch" cadence vs the DriftController's "drift"/
  "manual" triggers — rides into the invalidation counter, and a stale
  capacity answer can never outlive the model that produced it.

Parity is measured, not assumed: every build interpolates its held-out
jitter probes and compares against their direct estimates from the SAME
folded batch; the resulting envelope is stored on the surface, exposed
on /healthz, and pinned by tests and benchmarks/whatif_bench.py.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict

import numpy as np

from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans

# Shared-axis sentinel: a MixSpace over more endpoints than max_axes
# collapses to ONE scale axis applied to every endpoint (grid**k vertex
# counts are exponential; beyond the cap a uniform scale is the honest
# sweep, exactly what WhatIfEstimator.sweep's scalar factor does).
SHARED_AXIS = "*"

# At most this many warm builds in flight at once: each build is a real
# folded prediction train, and an unbounded thread fleet would let one
# misbehaving client turn cache warming into a denial of service.
_MAX_INFLIGHT_WARMS = 2


def _canonical_program(base_traffic) -> list[dict[str, int]]:
    out: list[dict[str, int]] = []
    prev: dict[str, int] | None = None
    for step in base_traffic:
        cur = {str(ep): int(n) for ep, n in step.items()}
        if prev is not None and cur == prev:
            cur = prev       # share the object: repeated ticks (the
        out.append(cur)      # common shape) dedupe by identity downstream
        prev = cur
    return out


class MixSpace:
    """A per-endpoint scale grid around one base traffic program.

    ``axes`` are the base program's active (nonzero-total) endpoints,
    sorted, capped at ``max_axes`` (beyond which one shared axis scales
    everything together); ``grid`` is the per-axis scale ladder.  The
    vertex at scales ``(s_0, ..., s_k)`` is the program
    ``{ep: int(round(n * s_axis(ep)))}`` per tick — byte-identical to
    what ``WhatIfEstimator.sweep`` would estimate at that factor.
    """

    def __init__(self, base_traffic, grid, max_axes: int = 3,
                 seed: int = 0):
        self.base = _canonical_program(base_traffic)
        if not self.base:
            raise ValueError("mix space needs a non-empty base program")
        # graftlint: disable=JX003 -- host data: grid scales are python floats from config, never device values
        self.grid = tuple(float(g) for g in grid)
        if len(self.grid) < 2 or list(self.grid) != sorted(set(self.grid)):
            raise ValueError(
                f"grid must be >=2 strictly-increasing scales, got "
                f"{self.grid}")
        if self.grid[0] < 0:
            raise ValueError(f"grid scales must be >= 0, got {self.grid}")
        totals: dict[str, int] = {}
        for step in self.base:
            for ep, n in step.items():
                totals[ep] = totals.get(ep, 0) + n
        active = sorted(ep for ep, n in totals.items() if n > 0)
        if not active:
            raise ValueError(
                "mix space needs at least one endpoint with traffic")
        self.axes: tuple[str, ...] = (tuple(active)
                                      if len(active) <= int(max_axes)
                                      else (SHARED_AXIS,))
        self.seed = int(seed)
        self.key = hashlib.sha1(json.dumps(
            {"base": self.base, "grid": self.grid, "axes": self.axes,
             "seed": self.seed},
            sort_keys=True, separators=(",", ":")).encode()).hexdigest()[:16]

    @property
    def num_vertices(self) -> int:
        return len(self.grid) ** len(self.axes)

    def _axis_of(self, ep: str) -> int:
        if self.axes == (SHARED_AXIS,):
            return 0
        return self.axes.index(ep)       # axes are tiny (<= max_axes)

    def program_at(self, scales) -> list[dict[str, int]]:
        """The traffic program at one point of the scale space —
        sweep()'s exact rounding convention."""
        # graftlint: disable=JX003 -- host data: scales are python floats from the request payload
        scales = tuple(float(s) for s in scales)
        if len(scales) != len(self.axes):
            raise ValueError(
                f"{len(scales)} scales for {len(self.axes)} axes")
        return [
            {ep: int(round(n * scales[self._axis_of(ep)]))
             for ep, n in step.items()}
            for step in self.base
        ]

    def vertices(self) -> list[tuple[float, ...]]:
        """All grid vertices as scale tuples, in the flat (C-order)
        enumeration the surface's value grid is stacked in."""
        g = self.grid
        shape = (len(g),) * len(self.axes)
        return [tuple(g[i] for i in idx) for idx in np.ndindex(*shape)]

    def jitter_scales(self, count: int) -> list[tuple[float, ...]]:
        """``count`` Monte-Carlo probe points strictly inside the hull —
        the held-out mixes the parity envelope is measured on.
        Deterministic per (space key, seed): rebuilding the same space
        re-measures the same probes."""
        rng = np.random.default_rng(
            (self.seed & 0xFFFFFFFF) ^ int(self.key[:8], 16))
        lo, hi = self.grid[0], self.grid[-1]
        # graftlint: disable=JX003 -- host data: host-RNG jitter points, never device values
        return [tuple(float(x) for x in rng.uniform(lo, hi, len(self.axes)))
                for _ in range(int(count))]

    def contains(self, scales) -> bool:
        lo, hi = self.grid[0], self.grid[-1]
        # graftlint: disable=JX003 -- host data: scales are python floats from the request payload
        return all(lo <= float(s) <= hi for s in scales)

    def match(self, program) -> tuple[float, ...] | None:
        """Is ``program`` an int-rounded scaling of this space's base?

        Returns the per-axis scales (inside the grid hull) when it is,
        else None.  Rounding makes the scale a FEASIBLE INTERVAL per
        count (``m == round(n*s)`` ⇒ ``s ∈ [(m-.5)/n, (m+.5)/n]``); the
        intervals intersect across every tick and endpoint of an axis,
        and the returned scale snaps to a grid vertex whenever one lies
        in the intersection (so vertex queries read stored values
        bit-exactly).  A miss here only costs a frontier fallback —
        correctness never depends on matching — so this runs allocation-
        free on the raw request program (string endpoint keys, the
        /v1/whatif wire format): a tick identical to its predecessor
        contributes the same interval and is skipped outright, making
        uniform programs O(ticks) dict comparisons instead of O(ticks *
        endpoints) interval math — the /v1/whatif interception budget.
        """
        steps = list(program)
        if len(steps) != len(self.base):
            return None
        k = len(self.axes)
        lo = [self.grid[0]] * k
        hi = [self.grid[-1]] * k
        prev_b = prev_p = None
        for b_step, p_step in zip(self.base, steps):
            if b_step is prev_b and p_step == prev_p:
                continue
            prev_b, prev_p = b_step, p_step
            if len(p_step) != len(b_step):
                return None
            for ep, n in b_step.items():
                try:
                    m = int(p_step[ep])
                except (KeyError, TypeError, ValueError):
                    return None
                if n == 0:
                    if m != 0:
                        return None
                    continue
                a = self._axis_of(ep)
                lo[a] = max(lo[a], (m - 0.5) / n)
                hi[a] = min(hi[a], (m + 0.5) / n)
        scales = []
        for a in range(k):
            if lo[a] > hi[a]:
                return None
            snapped = None
            for g in self.grid:
                if lo[a] <= g <= hi[a]:
                    snapped = g
                    break
            scales.append(snapped if snapped is not None
                          else (lo[a] + hi[a]) / 2.0)
        return tuple(scales)

    def to_meta(self) -> dict:
        return {"key": self.key, "axes": list(self.axes),
                "grid": list(self.grid), "seed": self.seed,
                "ticks": len(self.base), "vertices": self.num_vertices}


def _bracket(grid: tuple[float, ...], s: float) -> tuple[int, float]:
    """Cell index + weight for one coordinate: ``grid[i] <= s <=
    grid[i+1]``, ``w`` the fractional position.  Out-of-hull coordinates
    clamp to the boundary (callers gate on :meth:`MixSpace.contains`
    before trusting the answer)."""
    if s <= grid[0]:
        return 0, 0.0
    if s >= grid[-1]:
        return len(grid) - 2, 1.0
    for i in range(len(grid) - 1):
        if s == grid[i]:
            return i, 0.0
        if grid[i] < s < grid[i + 1]:
            return i, (s - grid[i]) / (grid[i + 1] - grid[i])
    return len(grid) - 2, 1.0


class CapacitySurface:
    """One built surface: the full ``[g]*k + [T, E, Q]`` prediction grid
    for a mix space, host-resident and immutable."""

    __slots__ = ("space", "params_hash", "values", "parity", "build_s",
                 "programs_folded", "_meta")

    def __init__(self, space: MixSpace, params_hash: str,
                 values: np.ndarray, parity: dict, build_s: float,
                 programs_folded: int):
        self.space = space
        self.params_hash = params_hash
        self.values = values            # read-only float32
        self.parity = parity            # measured envelope (see build)
        self.build_s = build_s
        self.programs_folded = programs_folded
        self._meta = None       # built lazily: see meta()

    @property
    def nbytes(self) -> int:
        return int(self.values.nbytes)

    def interpolate(self, scales) -> np.ndarray:
        """Multilinear interpolation at one point of the scale space →
        the ``[T, E, Q]`` prediction series.  Pure host numpy over a few
        tiny slices — this is the microsecond read path.  Exact grid
        coordinates take the stored slice directly, so vertex reads are
        bit-identical to the direct estimate they were built from."""
        vals = self.values
        for s in scales:
            # graftlint: disable=JX003 -- host data: the query point is python floats; values is host numpy by design
            i, w = _bracket(self.space.grid, float(s))
            if w == 0.0:
                vals = vals[i]
            elif w == 1.0:
                vals = vals[i + 1]
            else:
                vals = vals[i] * (1.0 - w) + vals[i + 1] * w
        return vals

    def meta(self, scales=None) -> dict:
        # the static half is snapshotted on first use (after the build
        # finishes measuring parity) and shallow-copied per hit — the
        # microsecond read path allocates one small dict, not four
        base = self._meta
        if base is None:
            base = self._meta = {
                "hit": True, "params_hash": self.params_hash,
                "space": self.space.to_meta(),
                "parity": dict(self.parity)}
        out = dict(base)
        if scales is not None:
            # graftlint: disable=JX003 -- host data: response metadata built from python floats
            out["scales"] = [float(s) for s in scales]
        return out


def peaks_from_series(series: np.ndarray, metric_names, quantiles,
                      delta_mask) -> dict[str, dict[str, float]]:
    """``[T, E, Q]`` series → sweep()-convention peaks: delta-trained
    metrics report peak GROWTH over the program (peak minus start, the
    demo's post-re-anchor semantics), absolute metrics the plain peak."""
    peaks: dict[str, dict[str, float]] = {}
    for e, metric in enumerate(metric_names):
        # graftlint: disable=JX003 -- host data: delta_mask is a small host numpy vector
        relative = delta_mask is not None and bool(delta_mask[e])
        per_q = {}
        for qi, q in enumerate(quantiles):
            col = series[:, e, qi]
            key = f"q{int(q * 100):02d}"
            if relative:
                # graftlint: disable=JX003 -- host data: estimate_many_raw series are host numpy by design
                per_q[key] = max(float(np.max(col) - col[0]), 0.0)
            else:
                # graftlint: disable=JX003 -- host data: same host-resident series
                per_q[key] = float(np.max(col))
        peaks[metric] = per_q
    return peaks


def _relative_err(interp: np.ndarray, direct: np.ndarray,
                  scale: np.ndarray) -> float:
    """Parity metric between two ``[T, E, Q]`` series: the worst
    absolute gap, normalized per (metric, quantile) by ``scale`` — the
    peak |value| of that capacity series over the WHOLE surface.
    Normalizing by the signal's dynamic range — not pointwise values —
    is deliberate: a 1e-6-clipped quantile would otherwise turn an
    absolutely-negligible gap into an unbounded ratio."""
    a = np.asarray(interp, np.float64)
    b = np.asarray(direct, np.float64)
    # graftlint: disable=JX003 -- host data: parity check over host-resident surface grids
    return float(np.max(np.abs(a - b) / (scale + 1e-6)))


class CapacitySurfaceManager:
    """LRU of capacity surfaces keyed ``(params_hash, mix_space_hash)``
    with bounded memory, async warming, and reload-eager invalidation.

    Locking (TH001/TH002 discipline): ``_lock`` guards the store, byte
    count, epoch, in-flight set, and stats dict — and NOTHING that
    dispatches.  Surface builds (seconds) run entirely outside it;
    lookups copy the surface reference out and interpolate lock-free on
    the immutable value grid.

    Reload safety: reload paths bracket the backend swap with
    ``begin_reload()``/``end_reload(reason)``.  While a reload is in
    flight, lookups miss (direct answers ride the backend's own
    per-request consistency) and warm builds are refused; ``end_reload``
    clears the store and bumps the epoch.  Builds additionally record
    the epoch they started under and are DISCARDED on insert if a reload
    landed meanwhile — so even a router backend (same object identity
    across reloads, no params to hash) can never serve a surface built
    from pre-reload params after the swap.
    """

    def __init__(self, config):
        self.config = config
        self._lock = threading.Lock()
        self._surfaces: OrderedDict[tuple[str, str], CapacitySurface] = \
            OrderedDict()
        self._bytes = 0
        self._epoch = 0
        self._reload_depth = 0
        self._inflight: set[tuple[str, str]] = set()
        self._threads: list[threading.Thread] = []
        self._closed = False
        self._stats = {"hits": 0, "misses": 0, "frontier": 0, "builds": 0,
                       "invalidations": 0, "evictions": 0,
                       "stale_builds_dropped": 0, "build_errors": 0}
        # Prometheus twins (replace-by-name: the newest plane owns the
        # exposition; each instance keeps counting for its own healthz)
        self._m_reads = obs_metrics.REGISTRY.expose(obs_metrics.Counter(
            "deeprest_surface_reads_total",
            "what-if surface reads by outcome",
            labelnames=("outcome",)))
        self._m_builds = obs_metrics.REGISTRY.expose(obs_metrics.Counter(
            "deeprest_surface_builds_total",
            "capacity surface builds by mode",
            labelnames=("mode",)))
        self._m_build_seconds = obs_metrics.REGISTRY.expose(
            obs_metrics.Histogram(
                "deeprest_surface_build_seconds",
                "wall time building one capacity surface"))
        self._m_invalidations = obs_metrics.REGISTRY.expose(
            obs_metrics.Counter(
                "deeprest_surface_invalidations_total",
                "surface cache invalidations by reload reason",
                labelnames=("reason",)))
        self._m_evictions = obs_metrics.REGISTRY.expose(obs_metrics.Counter(
            "deeprest_surface_evictions_total",
            "surfaces evicted by the LRU bounds"))
        self._m_cached = obs_metrics.REGISTRY.expose(obs_metrics.Gauge(
            "deeprest_surface_cached",
            "capacity surfaces currently resident"))
        self._m_bytes = obs_metrics.REGISTRY.expose(obs_metrics.Gauge(
            "deeprest_surface_bytes",
            "host bytes held by resident capacity surfaces"))

    # -- keys ------------------------------------------------------------

    def params_hash_of(self, predictor) -> str:
        """Cache key half #1.  Predictors fingerprint their own params
        (:meth:`Predictor.params_digest`); backends without one (the
        replica router) key on the invalidation epoch + object identity,
        which the reload bracket bumps — staleness is structurally
        impossible either way.

        The serving quant mode is recorded IN the key (round 22): a
        surface built from int8 predictions carries that mode's parity
        envelope, so an f32 (or bf16) predictor must never answer from
        it — the digest already differs leaf-wise, the explicit suffix
        makes the contract auditable in the key itself."""
        quant = getattr(predictor, "quant", "off")
        suffix = "" if quant == "off" else f":{quant}"
        digest = getattr(predictor, "params_digest", None)
        if callable(digest):
            try:
                return str(digest()) + suffix
            # graftlint: disable=EX003 -- designed fallback: an undigestable backend degrades to epoch keying, which is strictly safe (reload bumps the epoch)
            except Exception:
                pass
        with self._lock:
            epoch = self._epoch
        return f"epoch{epoch}:{id(predictor):x}"

    # -- reads -----------------------------------------------------------

    def _get(self, key: tuple[str, str]) -> CapacitySurface | None:
        with self._lock:
            if self._reload_depth:
                return None
            surf = self._surfaces.get(key)
            if surf is not None:
                self._surfaces.move_to_end(key)
            return surf

    def lookup_program(self, predictor, program, seed: int = 0):
        """The ``/v1/whatif`` interception: if ``program`` is an
        int-rounded scaling of any cached surface's base (for the
        CURRENT params, at the request's synthesis ``seed``), answer it
        by interpolation.

        Returns ``(series [T,E,Q], meta dict)`` or None.  One lock
        section covers the scan, the LRU touch, and the stats bump —
        matching is allocation-free and bounded by ``max_surfaces``, and
        a single crossing beats three under 16-thread contention (each
        contended acquire is a scheduler handoff on the microsecond read
        path); interpolation runs outside on the immutable surface."""
        phash = self.params_hash_of(predictor)
        seed = int(seed)
        found = None
        with self._lock:
            if self._reload_depth:
                return None
            for key, surf in self._surfaces.items():
                if key[0] != phash or surf.space.seed != seed:
                    continue
                scales = surf.space.match(program)
                if scales is not None:
                    found = (key, surf, scales)
                    break
            if found is None:
                return None
            self._surfaces.move_to_end(found[0])
            self._stats["hits"] += 1
        _, surf, scales = found
        self._m_reads.inc(outcome="hit")
        return surf.interpolate(scales), surf.meta(scales)

    def query(self, predictor, estimator, base_traffic, scales=None,
              factor=None, seed: int = 0, wait: bool = False) -> dict:
        """The ``/v1/whatif/surface`` handler body: peaks (sweep
        semantics) at one point of a mix space around ``base_traffic``.

        In-cache + in-hull → interpolated, microseconds.  Cache miss →
        frontier fallback (ONE direct estimate for the queried point)
        plus an async warm of the whole surface — unless ``wait`` is set
        or async warming is disabled, in which case the build runs
        synchronously and the answer comes off the fresh surface.
        Out-of-hull points always answer from the frontier (the surface
        cannot honestly extrapolate) but still warm the space for the
        in-hull queries that follow.
        """
        cfg = self.config
        space = MixSpace(base_traffic, cfg.grid, max_axes=cfg.max_axes,
                         seed=seed)
        point = self._point_of(space, scales, factor)
        phash = self.params_hash_of(predictor)
        key = (phash, space.key)
        surf = self._get(key)
        in_hull = space.contains(point)
        if surf is None and in_hull:
            if wait or not cfg.warm_async:
                surf = self._build(predictor, estimator, space, mode="sync")
            else:
                self.maybe_warm(predictor, estimator, space)
        elif surf is None:
            self.maybe_warm(predictor, estimator, space)
        if surf is not None and in_hull:
            series = surf.interpolate(point)
            self._note_read("hit")
            meta = surf.meta(point)
        else:
            # frontier fallback: one direct (memoized) estimate of the
            # exact queried program — full model fidelity, no surface
            series = estimator.estimate_many_raw(
                [space.program_at(point)], seeds=[space.seed])[0]
            self._note_read("frontier")
            meta = {"hit": False, "frontier": True, "in_hull": in_hull,
                    "params_hash": phash, "space": space.to_meta(),
                    # graftlint: disable=JX003 -- host data: response metadata built from python floats
                    "scales": [float(s) for s in point]}
        peaks = peaks_from_series(series, predictor.metric_names,
                                  predictor.quantiles,
                                  getattr(predictor, "delta_mask", None))
        return {"peaks": peaks, "surface": meta}

    def _point_of(self, space: MixSpace, scales, factor):
        if (scales is None) == (factor is None):
            raise ValueError(
                "provide exactly one of 'scales' (per-endpoint) or "
                "'factor' (uniform)")
        if factor is not None:
            try:
                f = float(factor)
            except (TypeError, ValueError):
                raise ValueError(f"bad factor: {factor!r}") from None
            return (f,) * len(space.axes)
        if not isinstance(scales, dict):
            raise ValueError("'scales' must be {endpoint: scale}")
        point = [1.0] * len(space.axes)
        for ep, s in scales.items():
            try:
                # graftlint: disable=JX003 -- host data: payload scale values are python scalars
                v = float(s)
            except (TypeError, ValueError):
                raise ValueError(f"bad scale for {ep!r}: {s!r}") from None
            if space.axes == (SHARED_AXIS,):
                point[0] = v      # shared axis: last writer wins
                continue
            if ep not in space.axes:
                raise KeyError(
                    f"endpoint {ep!r} not an axis of this mix space "
                    f"(axes: {list(space.axes)})")
            point[space.axes.index(ep)] = v
        return tuple(point)

    # -- builds ----------------------------------------------------------

    def estimated_bytes(self, space: MixSpace, predictor) -> int:
        t = len(space.base)
        e = len(predictor.metric_names)
        q = len(predictor.quantiles)
        return space.num_vertices * t * e * q * 4

    def _build(self, predictor, estimator, space: MixSpace,
               mode: str) -> CapacitySurface | None:
        """Estimate every vertex + jitter probe in one folded batch and
        publish the surface (unless a reload landed meanwhile)."""
        cfg = self.config
        phash = self.params_hash_of(predictor)
        key = (phash, space.key)
        with self._lock:
            epoch0 = self._epoch
        if self.estimated_bytes(space, predictor) > cfg.max_bytes:
            raise ValueError(
                f"mix space too large for the surface budget: "
                f"{space.num_vertices} vertices x {len(space.base)} ticks "
                f"would exceed max_bytes={cfg.max_bytes}")
        sw = obs_metrics.Stopwatch()
        with obs_spans.RECORDER.span("surface.build",
                                     component="deeprest-surface") as sp:
            verts = space.vertices()
            probes = space.jitter_scales(cfg.jitter)
            programs = ([space.program_at(v) for v in verts]
                        + [space.program_at(p) for p in probes])
            # One folded prediction train for the WHOLE surface, sized to
            # page through the fused engine instead of looping the host.
            # Every program synthesizes at the SAME seed (the space's):
            # a vertex is then bit-identical to a direct estimate at that
            # seed, and synthesis noise is CORRELATED across vertices, so
            # interpolation error measures model nonlinearity — not
            # decorrelated noise.
            raws = estimator.estimate_many_raw(
                programs, seeds=[space.seed] * len(programs), cache=False)
            nv = len(verts)
            gshape = (len(space.grid),) * len(space.axes)
            values = np.stack(raws[:nv]).reshape(
                gshape + raws[0].shape).astype(np.float32)
            values.setflags(write=False)
            surf = CapacitySurface(space, phash, values,
                                   parity={}, build_s=0.0,
                                   programs_folded=len(programs))
            # parity envelope: the held-out probes were estimated
            # directly in the SAME batch; interpolate them off the fresh
            # surface and record the worst gap relative to each capacity
            # series' dynamic range over the surface
            flat = values.reshape(-1, *raws[0].shape)
            # graftlint: disable=JX003 -- host data: the surface grid is host numpy by design
            scale = np.max(np.abs(flat), axis=(0, 1))       # [E, Q]
            errs = [_relative_err(surf.interpolate(p), raws[nv + j], scale)
                    for j, p in enumerate(probes)]
            surf.parity = {
                "probes": len(probes),
                "max_rel_err": max(errs) if errs else 0.0,
                "mean_rel_err": (sum(errs) / len(errs)) if errs else 0.0,
            }
            surf.build_s = sw.elapsed()
            sp.tag(space=space.key, vertices=nv, probes=len(probes),
                   mode=mode)
        self._m_build_seconds.observe(surf.build_s)
        self._m_builds.inc(mode=mode)
        published = self._insert(key, surf, epoch0)
        with self._lock:
            self._stats["builds"] += 1
            if not published:
                self._stats["stale_builds_dropped"] += 1
        return surf if published else None

    def _insert(self, key, surf: CapacitySurface, epoch0: int) -> bool:
        evicted = 0
        with self._lock:
            if (self._closed or self._reload_depth
                    or self._epoch != epoch0):
                return False          # built from pre-reload params: drop
            if key not in self._surfaces:
                self._surfaces[key] = surf
                self._bytes += surf.nbytes
            self._surfaces.move_to_end(key)
            cfg = self.config
            while (len(self._surfaces) > 1
                   and (len(self._surfaces) > cfg.max_surfaces
                        or self._bytes > cfg.max_bytes)):
                _, old = self._surfaces.popitem(last=False)
                self._bytes -= old.nbytes
                evicted += 1
            self._stats["evictions"] += evicted
            n, b = len(self._surfaces), self._bytes
        if evicted:
            self._m_evictions.inc(evicted)
        self._m_cached.set(n)
        self._m_bytes.set(b)
        return True

    def maybe_warm(self, predictor, estimator, space_or_program,
                   seed: int = 0) -> bool:
        """Kick off one async build of a surface (deduplicated against
        resident surfaces and in-flight builds; bounded concurrency).
        Accepts a MixSpace or a raw traffic program to anchor one at
        (``seed`` applies only in the latter case)."""
        cfg = self.config
        space = space_or_program
        if not isinstance(space, MixSpace):
            try:
                space = MixSpace(space_or_program, cfg.grid,
                                 max_axes=cfg.max_axes, seed=seed)
            except ValueError:
                return False
        if self.estimated_bytes(space, predictor) > cfg.max_bytes:
            return False
        phash = self.params_hash_of(predictor)
        key = (phash, space.key)
        with self._lock:
            if (self._closed or self._reload_depth
                    or key in self._surfaces or key in self._inflight
                    or len(self._inflight) >= _MAX_INFLIGHT_WARMS):
                return False
            self._inflight.add(key)
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._warm_one,
                args=(predictor, estimator, space, key),
                daemon=True, name="deeprest-surface-warm")
            self._threads.append(t)
        t.start()
        return True

    def _warm_one(self, predictor, estimator, space, key) -> None:
        try:
            self._build(predictor, estimator, space, mode="warm")
        except Exception as exc:
            with self._lock:
                self._stats["build_errors"] += 1
                first = self._stats["build_errors"] == 1
            if first:
                import sys

                print(f"surface warm failed: {type(exc).__name__}: {exc}",
                      file=sys.stderr)
        finally:
            with self._lock:
                self._inflight.discard(key)

    # -- invalidation ----------------------------------------------------

    def begin_reload(self) -> None:
        """Enter the reload bracket: lookups miss and builds are refused
        until :meth:`end_reload` — no reader can observe a surface while
        the backend underneath it is mid-swap."""
        with self._lock:
            self._reload_depth += 1

    def end_reload(self, reason: str = "manual") -> None:
        """Leave the reload bracket and invalidate eagerly: the store is
        cleared and the epoch bumped, labeled with the reload ``reason``
        ("watch" cadence, the DriftController's "drift", or "manual")."""
        with self._lock:
            self._reload_depth = max(0, self._reload_depth - 1)
        self.invalidate(reason=reason)

    def invalidate(self, reason: str = "manual") -> int:
        """Drop every resident surface NOW (reason-labeled).  Returns
        the number dropped.  In-flight builds that started before this
        point are discarded at insert (epoch check)."""
        with self._lock:
            n = len(self._surfaces)
            self._surfaces.clear()
            self._bytes = 0
            self._epoch += 1
            self._stats["invalidations"] += 1
        self._m_invalidations.inc(reason=reason)
        self._m_cached.set(0)
        self._m_bytes.set(0)
        return n

    # -- lifecycle / observability ---------------------------------------

    def _note_read(self, outcome: str) -> None:
        with self._lock:
            if outcome == "hit":
                self._stats["hits"] += 1
            elif outcome == "frontier":
                self._stats["frontier"] += 1
                self._stats["misses"] += 1
            else:
                self._stats["misses"] += 1
        self._m_reads.inc(outcome=outcome)

    def note_miss(self) -> None:
        """A /v1/whatif request no cached surface could answer."""
        self._note_read("miss")

    def stats(self) -> dict:
        """The /healthz "surface" key: resident set, byte budget, and
        the full hit/miss/build/invalidation ledger, plus the parity
        envelope of the worst resident surface (honesty on the probe)."""
        with self._lock:
            surfaces = list(self._surfaces.values())
            out = {"enabled": True,
                   "surfaces": len(surfaces),
                   "bytes": self._bytes,
                   "max_surfaces": self.config.max_surfaces,
                   "max_bytes": self.config.max_bytes,
                   "inflight_warms": len(self._inflight),
                   "epoch": self._epoch,
                   **dict(self._stats)}
        out["parity_max_rel_err"] = max(
            (s.parity.get("max_rel_err", 0.0) for s in surfaces),
            default=None)
        return out

    def close(self) -> None:
        """Refuse new builds, drop the store, and JOIN the warm threads
        (idempotent) — a leaked builder would pin the estimator stack and
        trip the chaos tests' thread census."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            threads = list(self._threads)
            self._threads = []
            self._surfaces.clear()
            self._bytes = 0
        for t in threads:
            t.join(timeout=30.0)
        self._m_cached.set(0)
        self._m_bytes.set(0)
