"""Application sanity checking: utilization not justified by traffic.

The second DeepRest use case (reference: README.md:5): compare *observed*
per-component utilization against the model's traffic-conditioned
prediction interval; sustained usage above the upper quantile means some
consumer other than the API traffic is at work (cryptojacking CPU burners,
ransomware-style IO).  The reference demonstrates this experimentally
(crypto locust scenario + pow.py) but ships no detector; this module is
that missing piece."""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.serve.predictor import Predictor


@dataclasses.dataclass
class AlignedBands:
    """The detector's aligned comparison space — everything downstream of
    the model call and upstream of the excess/flag logic.  Shared by the
    batch :meth:`AnomalyDetector.check` path and the streaming quality
    monitor (obs/quality.py), which additionally reads band coverage and
    pinball loss off the same aligned arrays, so the continuous verdict
    surface and the batch CLI agree by construction."""

    preds: np.ndarray          # [T, E, Q] monotone-rearranged, re-anchored
    observed: np.ndarray       # [T, E] adjusted (delta metrics differenced)
    upper: np.ndarray          # [T, E] the band's upper envelope
    scale: np.ndarray          # [T, E] the floored normalization scale


@dataclasses.dataclass
class AnomalyReport:
    metric: str
    score: float               # mean normalized excess above the upper band
    flagged: bool
    first_flag_index: int | None   # start of the first sustained excess run
    excess: np.ndarray         # [T] per-step normalized excess

    def __repr__(self) -> str:  # compact, log-friendly
        state = "ANOMALOUS" if self.flagged else "ok"
        return (f"AnomalyReport({self.metric}: {state}, score={self.score:.4f}, "
                f"first_flag={self.first_flag_index})")


class AnomalyDetector:
    """Flags sustained utilization above the traffic-justified upper band."""

    def __init__(self, predictor: Predictor, tolerance: float = 0.10,
                 min_run: int = 5,
                 reanchor_resources: tuple[str, ...] = ("usage", "memory")):
        """tolerance: fractional headroom over the upper quantile before a
        step counts as excess; min_run: consecutive excess steps required to
        flag (rules out single-scrape spikes); reanchor_resources: level-type
        resources whose absolute value depends on history the traffic can't
        see (cumulative disk usage, resident memory) — their prediction bands
        are shifted to start at the first observed value, the reference
        demo's re-anchoring trick (web-demo/dataloader.py:143-156).

        Tolerance direction is explicit: the threshold is always
        ``upper + tolerance * scale`` with ``scale > 0``, i.e. headroom
        strictly ABOVE the band.  For re-anchored metrics the band can go
        negative (a small first observation anchors predictions below
        zero); there ``scale`` is floored at the per-metric train-split
        level range, so "tolerance" keeps meaning a fraction of a
        NORMAL-sized level — matching the increment-space floor delta
        metrics already get — instead of shrinking toward zero (and
        tightening the threshold) as the band crosses zero.  Behavior
        change vs the earlier ``|upper|``-only scale: near-zero or
        negative re-anchored bands now get a wider, stable margin."""
        self.predictor = predictor
        self.tolerance = tolerance
        self.min_run = min_run
        self.reanchor_resources = reanchor_resources

    def check(self, traffic: np.ndarray,
              observed: np.ndarray) -> list[AnomalyReport]:
        """``aligned`` + ``reports`` in one call (the batch CLI path;
        the streaming monitor calls the halves separately so calibration
        can read the same aligned bands without a second model pass)."""
        return self.reports(self.aligned(traffic, observed))

    def aligned(self, traffic: np.ndarray,
                observed: np.ndarray) -> AlignedBands:
        """traffic: [T, F] feature series; observed: [T, E] de-normalized
        utilization aligned with ``predictor.metric_names``.

        Delta-trained metrics (``predictor.delta_mask``) are checked in
        INCREMENT space: the observed series is differenced and compared
        against the model's raw per-bucket increment band — abnormal
        write RATE is the ransomware signal, and a level comparison would
        dilute it with rollout drift accumulated over the whole series.

        ``integrate=False`` rides the fused device pipeline
        (serve/fused.py): the same per-rung executable serves both the
        integrated and increment-space requests (the integrate switch is
        a traced flag, not a recompile), and its raw-increment output is
        bit-exact with the host reference loop on CPU — so detector
        thresholds are unchanged by the serving-path migration
        (tests/test_fused_infer.py pins this).
        """
        dm = getattr(self.predictor, "delta_mask", None)
        preds = self.predictor.predict_series(
            traffic, integrate=False)                       # [T, E, Q]
        # Monotone quantile rearrangement (Chernozhukov/Fernández-Val/
        # Galichon): sort the quantile axis so the band edge is the upper
        # ENVELOPE of the predicted quantiles.  The heads are trained
        # independently under pinball loss and can cross — an undertrained
        # upper head can sit at the normalized clamp floor, BELOW the
        # median — and ``preds[..., -1]`` then reads the band's floor as
        # its ceiling: every ordinary observation becomes "excess" and the
        # detector false-flags from the first buckets (the flag_at=7
        # incident; tests/test_serve.py pins flag_at inside the injected
        # anomaly window).  Rearrangement restores valid, non-crossing
        # quantiles without touching the wire predictions.
        preds = np.sort(np.asarray(preds, np.float32), axis=-1)
        # after value-sorting, quantile level i lives at its RANK among
        # the configured levels (identity for the ascending default)
        qs = list(self.predictor.quantiles)
        med = sorted(range(len(qs)), key=lambda i: qs[i]).index(
            self.predictor.median_index())
        observed = np.array(observed, np.float32, copy=True)
        reanchored: list[int] = []
        for e, metric in enumerate(self.predictor.metric_names):
            if dm is not None and dm[e]:
                # increment space: diff the observation; first bucket has
                # no predecessor → zero increment (never flags).
                observed[1:, e] = np.diff(observed[:, e])
                observed[0, e] = 0.0
                continue
            resource = metric.rsplit("_", 1)[-1]
            if resource in self.reanchor_resources:
                preds[:, e, :] += observed[0, e] - preds[0, e, med]
                reanchored.append(e)
        upper = preds[..., -1]                               # highest quantile
        scale = np.maximum(np.abs(upper), 1e-6)
        if reanchored:
            # Re-anchored bands can dip to/below zero, where an |upper|
            # scale degenerates (any noise reads as huge normalized excess
            # and the tolerance margin tightens toward nothing).  Floor at
            # the per-metric train-split level range — model-anchored, so
            # an attacker cannot inflate it — with the same degenerate-
            # range fallback the delta branch uses.
            rng_all = np.asarray(self.predictor.y_stats.range,
                                 np.float32).reshape(-1)
            floor = rng_all[reanchored]
            fallback = float(np.max(floor)) if np.max(floor) > 0 else 1.0
            floor = np.where(floor > 0, floor, fallback)
            scale[:, reanchored] = np.maximum(scale[:, reanchored], floor)
        if dm is not None and dm.any():
            # A quiet store's predicted increment band sits near zero,
            # making a MULTIPLICATIVE tolerance meaningless (any scrape
            # noise reads as huge normalized excess).  Floor the scale of
            # increment-space metrics at the train split's increment
            # range — model-anchored (an attacker cannot inflate it), and
            # "tolerance" then means a fraction of a NORMAL-sized
            # increment, matching its meaning for level metrics.
            rng_e = np.asarray(self.predictor.y_stats.range,
                               np.float32).reshape(-1)
            # A train-split-idle store has a degenerate (zero) increment
            # range — fall back to the largest increment range across
            # delta metrics so a few benign bytes of first-ever activity
            # don't read as ransomware (the 1e-6 scale would make any
            # noise an enormous normalized excess).
            floor = rng_e[dm]
            fallback = float(np.max(floor)) if np.max(floor) > 0 else 1.0
            floor = np.where(floor > 0, floor, fallback)
            scale[:, dm] = np.maximum(scale[:, dm], floor)
        return AlignedBands(preds=preds, observed=observed, upper=upper,
                            scale=scale)

    def reports(self, bands: AlignedBands) -> list[AnomalyReport]:
        """The excess/flag half over an aligned comparison space."""
        observed, upper, scale = bands.observed, bands.upper, bands.scale
        excess = np.maximum(observed - upper - self.tolerance * scale,
                            0.0) / scale

        reports = []
        for e, metric in enumerate(self.predictor.metric_names):
            ex = excess[:, e]
            run, first, longest = 0, None, 0
            for t, v in enumerate(ex):
                run = run + 1 if v > 0 else 0
                longest = max(longest, run)
                if run >= self.min_run and first is None:
                    first = t - self.min_run + 1
            reports.append(AnomalyReport(
                metric=metric,
                # graftlint: disable=JX003 -- host data: `excess` was materialized to numpy before this loop; no device sync here
                score=float(ex.mean()),
                flagged=longest >= self.min_run,
                first_flag_index=first,
                excess=ex,
            ))
        return reports
