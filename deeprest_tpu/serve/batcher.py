"""Cross-request micro-batching: many tiny requests → few device batches.

The serving layer's original shape was one jit call per request: N
concurrent clients meant N serialized device dispatches of tiny window
batches, a fresh host→device transfer each, and a recompile whenever a
series length produced a new ragged last-batch shape in
``rolled_prediction``.  That is the request-level twin of the small-batch
MXU under-occupancy PERF.md diagnoses inside the recurrence — and the
fix is the classic model-server one (Clipper/ClockWork-style adaptive
batching, PAPERS.md): coalesce concurrent requests into shared batches
behind a bounded queue.

Two pieces, usable separately:

``ShapeLadder``
    Pads every batch up the fixed rung ladder (default {8, 16, 32, 64}
    windows) before it reaches the jit-compiled apply, so the jit cache
    holds a handful of executables — one per rung — instead of one per
    ragged shape.  Oversized batches split into max-rung chunks.  Padding
    rows are zeros and their outputs are dropped (pad-and-mask); the
    model maps rows independently, so valid rows are unaffected.

``MicroBatcher``
    A worker thread drains a bounded queue of submitted window batches,
    concatenates them into one ladder dispatch, and demultiplexes the
    results back to per-request futures — the wire protocol never sees
    the coalescing.  Flush policy: a batch goes out when ``max_batch``
    windows are pending or ``max_linger_s`` has elapsed since the first
    pending arrived, whichever is first.  Host→device staging is
    double-buffered: while the device executes batch k, the worker is
    already assembling/staging batch k+1 (JAX dispatch is asynchronous;
    only the result readback blocks), so host prep overlaps device
    execution.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans

DEFAULT_LADDER = (8, 16, 32, 64)


class BatcherClosed(RuntimeError):
    """Raised by submit() after close(); callers fall back to the direct
    shape-laddered path (a hot-reload swaps batchers between requests, and
    a request that lost that race must not fail)."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Flush-policy and queue knobs for :class:`MicroBatcher`.

    ``max_batch`` is in WINDOWS (the device-batch row unit), not requests:
    one request's chunk may carry many windows.  It should normally equal
    the top ladder rung so a full flush compiles nothing new.
    """

    max_batch: int = 64
    max_linger_s: float = 0.002
    max_queue: int = 1024        # pending-window bound (submit backpressure)

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch {self.max_batch} must be >= 1")
        if self.max_linger_s < 0:
            raise ValueError(f"max_linger_s {self.max_linger_s} must be >= 0")
        if self.max_queue < self.max_batch:
            raise ValueError(f"max_queue {self.max_queue} must be >= "
                             f"max_batch {self.max_batch}")


class ShapeLadder:
    """Pad-and-mask batches onto a fixed shape ladder in front of a
    batched apply function ``[n, W, F] -> [n, W, E, Q]``.

    ``dispatch``/``materialize`` are split so a caller (the MicroBatcher's
    double buffer) can overlap the host-side staging + async device
    dispatch of one batch with the result readback of another;
    ``__call__`` is the synchronous composition.
    """

    def __init__(self, apply_fn, ladder=DEFAULT_LADDER,
                 coalesce_groups: int = 1, apply_sparse_fn=None):
        base = tuple(sorted({int(r) for r in ladder}))
        if not base or base[0] < 1:
            raise ValueError(f"bad shape ladder {ladder!r}")
        if coalesce_groups < 1:
            raise ValueError(
                f"coalesce_groups {coalesce_groups} must be >= 1")
        self._apply = apply_fn
        # Sparse staging (round 15): an optional second apply taking RAW
        # padded-COO ``(cols[n, W, K], vals[n, W, K])`` window batches
        # (densify + normalize live on device — ops/densify.py); COO
        # chunks pad up the SAME rung ladder with zero rows, so the
        # sparse plane compiles one executable per dispatched rung,
        # exactly like the dense one.
        self._apply_sparse = apply_sparse_fn
        self.base_ladder = base
        self.coalesce_groups = int(coalesce_groups)
        # Coalesced super-rungs (round 11): top·{2..G} join the ladder so
        # a deep cross-request backlog dispatches ONE fat batch (top·G
        # recurrence rows) instead of G sequential top-rung dispatches —
        # the request-level face of the window-coalesced kernel batching.
        # Each super-rung is one extra executable, same as any rung.
        rungs = set(base)
        rungs.update(base[-1] * g for g in range(2, self.coalesce_groups + 1))
        self.ladder = tuple(sorted(rungs))
        self._lock = threading.Lock()
        self._compiled: set[int] = set()     # rungs dispatched at least once
        self._calls = 0
        self._windows = 0
        self._padded_windows = 0
        self._rung_hits = 0

    @property
    def max_rung(self) -> int:
        return self.ladder[-1]

    def rung_for(self, n: int) -> int:
        """Smallest rung >= n (callers chunk to max_rung first)."""
        for r in self.ladder:
            if n <= r:
                return r
        raise ValueError(f"batch of {n} windows exceeds top rung "
                         f"{self.max_rung}; chunk before dispatching")

    def dispatch(self, x: np.ndarray) -> list[tuple[object, int]]:
        """Stage + asynchronously dispatch ``x`` as ladder-padded chunks;
        returns ``[(device_result, valid_rows), ...]`` for materialize()."""
        x = np.ascontiguousarray(x, dtype=np.float32)
        parts: list[tuple[object, int]] = []
        for lo in range(0, len(x), self.max_rung):
            chunk = x[lo:lo + self.max_rung]
            rung = self.rung_for(len(chunk))
            padded = chunk
            if rung > len(chunk):
                padded = np.zeros((rung, *chunk.shape[1:]), np.float32)
                padded[:len(chunk)] = chunk
            with self._lock:
                self._calls += 1
                self._windows += len(chunk)
                self._padded_windows += rung - len(chunk)
                if rung in self._compiled:
                    self._rung_hits += 1
                else:
                    self._compiled.add(rung)
            parts.append((self._apply(padded), len(chunk)))
        return parts

    def dispatch_sparse(self, cols: np.ndarray,
                        vals: np.ndarray) -> list[tuple[object, int]]:
        """COO staging twin of :meth:`dispatch`: stage + asynchronously
        dispatch raw ``(cols[n, W, K], vals[n, W, K])`` padded-COO window
        batches as ladder-padded chunks (padding rows are all-zero COO
        rows, whose densified windows are all-zero — dropped by
        materialize exactly like dense padding).  Host→device bytes per
        window are ``W·K·8`` instead of ``W·F·4``."""
        if self._apply_sparse is None:
            raise ValueError("this ladder has no sparse apply; construct "
                             "it with apply_sparse_fn (sparse_feed)")
        cols = np.ascontiguousarray(cols, dtype=np.int32)
        vals = np.ascontiguousarray(vals, dtype=np.float32)
        if cols.shape != vals.shape:
            raise ValueError(f"padded-COO halves disagree: cols "
                             f"{cols.shape} vs vals {vals.shape}")
        parts: list[tuple[object, int]] = []
        for lo in range(0, len(cols), self.max_rung):
            c = cols[lo:lo + self.max_rung]
            v = vals[lo:lo + self.max_rung]
            n = len(c)
            rung = self.rung_for(n)
            if rung > n:
                pc = np.zeros((rung, *c.shape[1:]), np.int32)
                pv = np.zeros((rung, *v.shape[1:]), np.float32)
                pc[:n] = c
                pv[:n] = v
                c, v = pc, pv
            with self._lock:
                self._calls += 1
                self._windows += n
                self._padded_windows += rung - n
                if rung in self._compiled:
                    self._rung_hits += 1
                else:
                    self._compiled.add(rung)
            parts.append((self._apply_sparse(c, v), n))
        return parts

    @staticmethod
    def materialize(parts: list[tuple[object, int]]) -> np.ndarray:
        """Block on the device results and strip the padding rows."""
        # graftlint: disable=JX003 -- designed sink: materialize IS the one readback point the dispatch/materialize split exists to isolate
        outs = [np.asarray(y)[:n] for y, n in parts]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.materialize(self.dispatch(x))

    def stats(self) -> dict:
        with self._lock:
            return {
                "ladder": list(self.ladder),
                "coalesce_groups": self.coalesce_groups,
                "calls": self._calls,
                "windows": self._windows,
                "padded_windows": self._padded_windows,
                "rung_hits": self._rung_hits,
                "rung_compiles": len(self._compiled),
                "compiled_rungs": sorted(self._compiled),
            }


class _Pending:
    __slots__ = ("x", "future", "ctx")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.future: Future = Future()
        # The submitting request's trace context: the worker thread's
        # coalesced-dispatch span parents onto the first submitter so a
        # request's trace reaches across the thread boundary.
        self.ctx = obs_spans.current_context()


def _inflight_ready(inflight) -> bool:
    """True once every device part of an in-flight dispatch has finished
    (jax.Array.is_ready; results without the probe count as finished)."""
    if inflight is None:
        return True
    for y, _ in inflight[0]:
        probe = getattr(y, "is_ready", None)
        if callable(probe) and not probe():
            return False
    return True


class MicroBatcher:
    """Coalesces concurrent window-batch submissions into shared ladder
    dispatches on a single worker thread (see module docstring)."""

    def __init__(self, ladder: ShapeLadder,
                 config: BatcherConfig | None = None):
        self.config = config or BatcherConfig()
        self._ladder = ladder
        self._cv = threading.Condition()
        self._pending: collections.deque[_Pending] = collections.deque()
        self._pending_windows = 0
        self._running = True
        # Batch accounting lives in obs metrics (per-instance objects —
        # the /healthz JSON view and the /metrics exposition read the
        # SAME counters; the newest plane's batcher owns the exposition
        # binding via the serving collector).
        self._m = obs_metrics.Counter(
            "deeprest_batcher_events_total",
            "micro-batcher accounting by event kind",
            labelnames=("event",))
        self._m_max_batch = obs_metrics.Gauge(
            "deeprest_batcher_max_batch_windows",
            "widest coalesced batch dispatched (high-water mark)")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="microbatcher")
        self._thread.start()

    # -- client side ----------------------------------------------------

    def submit(self, x: np.ndarray) -> Future:
        """Enqueue a ``[n, W, F]`` normalized window batch; the future
        resolves to the ``[n, W, E, Q]`` result.  Blocks (backpressure)
        while ``max_queue`` windows are already pending."""
        x = np.asarray(x, dtype=np.float32)
        if x.ndim != 3 or len(x) == 0:
            raise ValueError(f"expected non-empty [n, W, F] windows, "
                             f"got shape {x.shape}")
        p = _Pending(x)
        with self._cv:
            while (self._running
                   and self._pending_windows + len(x) > self.config.max_queue
                   and self._pending_windows > 0):
                self._cv.wait()
            if not self._running:
                raise BatcherClosed("micro-batcher is closed")
            self._pending.append(p)
            self._pending_windows += len(x)
            self._m.inc(event="submitted")
            self._cv.notify_all()
        return p.future

    def apply(self, x: np.ndarray) -> np.ndarray:
        """Synchronous submit — the rolled_prediction-compatible entry."""
        return self.submit(x).result()

    def stats(self) -> dict:
        # Same JSON shape as the historical dict — now a VIEW over the
        # obs counters (one source of truth with /metrics).
        events = self._m.series()

        def ev(name: str) -> int:
            return int(events.get((name,), 0.0))

        out = {"submitted": ev("submitted"), "batches": ev("batches"),
               "windows": ev("windows"),
               "max_batch_windows": int(self._m_max_batch.value()),
               "coalesced_batches": ev("coalesced_batches"),
               "flush_full": ev("flush_full"),
               "flush_linger": ev("flush_linger"),
               "flush_pipeline": ev("flush_pipeline"),
               "errors": ev("errors")}
        with self._cv:
            out["queue_depth_windows"] = self._pending_windows
            out["queue_depth_requests"] = len(self._pending)
        out["max_batch"] = self.config.max_batch
        out["max_linger_ms"] = self.config.max_linger_s * 1e3
        out["shape_ladder"] = self._ladder.stats()
        return out

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, drain what is queued, join the worker."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)

    # -- worker side ----------------------------------------------------

    def _collect(self, block: bool, inflight_ready=None) -> list[_Pending]:
        """Take up to ``max_batch`` windows of pending submissions.

        ``block=True`` (nothing in flight): wait indefinitely for the
        first submission, then linger up to ``max_linger_s`` for
        co-arrivals, flushing early once ``max_batch`` windows are
        pending.  ``block=False`` (a batch is executing on the device):
        the device busy time IS the coalescing window, so waiting up to
        ``max_linger_s`` here is free overlap — but the wait breaks the
        moment ``inflight_ready()`` reports the device done, so a
        finished batch is never held hostage to the linger clock.
        """
        cfg = self.config
        with self._cv:
            if block:
                while self._running and not self._pending:
                    self._cv.wait()
            if self._running and cfg.max_linger_s > 0:
                deadline = time.monotonic() + cfg.max_linger_s
                while (self._running
                       and self._pending_windows < cfg.max_batch
                       and (self._pending or not block)):
                    if (not block and inflight_ready is not None
                            and inflight_ready()):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left if block else min(left, 5e-4))
            group: list[_Pending] = []
            take = 0
            while self._pending and take < cfg.max_batch:
                n = len(self._pending[0].x)
                if group and take + n > cfg.max_batch:
                    break
                group.append(self._pending.popleft())
                take += n
            if group:
                self._pending_windows -= take
                reason = ("flush_pipeline" if not block
                          else "flush_full" if take >= cfg.max_batch
                          else "flush_linger")
                self._m.inc(event=reason)
                self._m.inc(event="batches")
                self._m.inc(take, event="windows")
                if len(group) > 1:
                    self._m.inc(event="coalesced_batches")
                self._m_max_batch.set_max(take)
                self._cv.notify_all()      # wake back-pressured submitters
            return group

    def _dispatch(self, group: list[_Pending]):
        """Concatenate + stage + async-dispatch one coalesced batch.

        The dispatch span parents onto the FIRST submitter's captured
        trace context (request-scoped ids cross the worker-thread
        boundary) and tags how many requests coalesced.
        """
        sizes = [len(p.x) for p in group]
        try:
            with obs_spans.RECORDER.span(
                    "batch.dispatch", component="deeprest-batcher",
                    parent=group[0].ctx) as sp:
                sp.tag(requests=len(group), windows=sum(sizes))
                x = (group[0].x if len(group) == 1
                     else np.concatenate([p.x for p in group], axis=0))
                parts = self._ladder.dispatch(x)
        except Exception as exc:
            self._m.inc(event="errors")
            for p in group:
                p.future.set_exception(exc)
            return None
        return parts, group, sizes

    def _resolve(self, inflight) -> None:
        parts, group, sizes = inflight
        try:
            y = ShapeLadder.materialize(parts)
        except Exception as exc:
            self._m.inc(event="errors")
            for p in group:
                p.future.set_exception(exc)
            return
        lo = 0
        for p, n in zip(group, sizes):
            p.future.set_result(y[lo:lo + n])
            lo += n

    def _run(self) -> None:
        inflight = None
        while True:
            # Double buffer: dispatch batch k+1 BEFORE blocking on batch
            # k's readback, so host concat/pad/staging overlaps device
            # execution of the previous batch.
            group = self._collect(
                block=inflight is None,
                inflight_ready=lambda: _inflight_ready(inflight))
            dispatched = self._dispatch(group) if group else None
            if inflight is not None:
                self._resolve(inflight)
            inflight = dispatched
            if inflight is None:
                with self._cv:
                    if not self._running and not self._pending:
                        return


class BatchedBackendMixin:
    """Shared by Predictor and ExportedPredictor: the shape-laddered batch
    entry point plus an optional attached MicroBatcher that ALL
    predict_series traffic (predict / what-if / anomaly) routes through.
    """

    def _init_batching(self, apply_fn, ladder=None,
                       coalesce_groups: int = 1,
                       apply_sparse_fn=None) -> None:
        self.ladder = ShapeLadder(apply_fn, ladder or DEFAULT_LADDER,
                                  coalesce_groups=coalesce_groups,
                                  apply_sparse_fn=apply_sparse_fn)
        self._batcher: MicroBatcher | None = None

    @property
    def batcher(self) -> MicroBatcher | None:
        return self._batcher

    def attach_batcher(self, batcher: MicroBatcher | None) -> None:
        """Route this backend's window batches through ``batcher`` (None
        detaches).  The batcher must wrap this backend's ``ladder``."""
        self._batcher = batcher

    def apply_windows(self, x: np.ndarray) -> np.ndarray:
        """[n, W, F] normalized windows → [n, W, E, Q] de-padded results.

        The single batch entry point behind ``predict_series``: via the
        attached MicroBatcher when one is present (cross-request
        coalescing), else a direct shape-laddered dispatch.  Either way
        the jit cache sees only ladder-rung shapes.
        """
        b = self._batcher
        if b is not None:
            try:
                return b.apply(x)
            except BatcherClosed:
                pass      # hot-reload race: fall through to the direct path
        return self.ladder(x)

    def apply_windows_sparse(self, cols: np.ndarray,
                             vals: np.ndarray) -> np.ndarray:
        """Padded-COO batch entry: RAW ``(cols[n, W, K], vals[n, W, K])``
        windows → ``[n, W, E, Q]`` de-padded results, with densify AND
        normalization on device (the dense entry takes pre-normalized
        windows; the sparse one ships raw counts, the point of the form).

        Dispatches straight through the shape ladder's sparse staging —
        cross-request MicroBatcher coalescing stays a dense-plane
        feature (long sparse series route through the fused engine, the
        same routing argument as ``_route_fused``); backends without a
        sparse apply densify on host, bit-exact by construction.
        """
        if self.ladder._apply_sparse is None:
            from deeprest_tpu.ops.densify import densify_rows
            from deeprest_tpu.data.windows import minmax_apply

            dense = densify_rows(cols, vals, self.feature_dim)
            return self.apply_windows(
                minmax_apply(dense, self.x_stats).astype(np.float32))
        return ShapeLadder.materialize(
            self.ladder.dispatch_sparse(cols, vals))
