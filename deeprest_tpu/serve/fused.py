"""Device-resident rolled inference: one dispatch per window page.

``rolled_prediction_reference`` (serve/predictor.py) is a host-bound loop:
it stacks windows in numpy, normalizes on host, reads every ``[n, W, E, Q]``
batch back, de-normalizes on host, and integrates delta-trained metrics
with a *sequential* per-window Python carry loop.  That was the last pure-
Python hot path between a traffic series and a prediction after the
micro-batched server (PR 1), superstep training (PR 2) and vectorized ETL
(PR 3) — and it caps month-scale and multi-scenario what-if throughput.

:class:`FusedRolledEngine` fuses the whole pipeline into a single
jit-compiled device program per page:

- windows are tiled on host as zero-copy-adjacent slices and shipped raw
  (un-normalized) once per page;
- ``x_stats`` normalization, the model forward pass, and the ``1e-6``
  clamp run on device;
- delta-mask columns are de-normalized on device and integrated with a
  PARALLEL prefix sum: per-window ``jnp.cumsum`` over the window axis,
  then an exclusive cumsum of per-window carry increments over the window
  (batch) axis replaces the sequential cross-window median carry.  Ragged
  right-aligned last windows and multi-scenario folds are expressed
  uniformly via per-window *carry offsets* (``g``: which in-window step
  the NEXT window's carry reads) and *segment starts* (``seg``: windows
  where the carry resets to zero — a new series/scenario);
- long series page through a fixed-size executable (one per ShapeLadder
  rung — zero new executables beyond the rung set) with the carry
  threaded between pages as a device-resident ``[E]`` array, never read
  back to host.

Numerics contract (pinned by tests/test_fused_infer.py):

- Non-delta metrics are BIT-EXACT vs the host reference on CPU.  XLA CPU
  contracts ``p * range + min`` into a single-rounding FMA inside fusions
  (1-ulp drift vs numpy's two-rounding, and neither ``optimization_barrier``
  nor ``xla_allow_excess_precision`` prevents it), so the fused program
  returns non-delta columns NORMALIZED and the host applies the exact
  reference ``y_stats.invert`` after readback — bit-exact by construction.
  Normalization stats enter the program as runtime arguments, not baked
  constants: a baked constant range lets XLA strength-reduce the divide
  into a multiply-by-reciprocal, which also breaks bit parity.
- Delta-mask columns carry a documented <= 1e-5 relative tolerance: the
  on-device invert may contract to FMA and the prefix-sum carry
  re-associates the reference's left-to-right float32 adds.
"""

from __future__ import annotations

import threading

import numpy as np

from deeprest_tpu.obs import spans as obs_spans

DEFAULT_FUSED_RUNGS = (8, 16, 32, 64)


def plan_windows(lengths: list[int], window_size: int):
    """Global window plan over a list of series lengths.

    Returns ``[(series_idx, start, carry_offset, seg_start), ...]`` in
    dispatch order.  ``carry_offset`` is the in-window step index the NEXT
    window's carry reads from this window's integrated median (``W - 1``
    for regular tiling; ``t - W - 1 - start`` when the next window is the
    ragged right-aligned tail).  ``seg_start`` marks the first window of
    each series: the integration carry resets to zero there (what-if
    rollouts are relative-from-zero per scenario).
    """
    w = window_size
    metas: list[tuple[int, int, int, bool]] = []
    for si, t in enumerate(lengths):
        if t < w:
            raise ValueError(f"series length {t} < window_size {w}")
        starts = list(range(0, t - w + 1, w))
        if starts[-1] != t - w:
            starts.append(t - w)
        for j, s in enumerate(starts):
            if j + 1 < len(starts):
                g = starts[j + 1] - 1 - s
            else:
                g = w - 1          # last window of the series: carry unused
            metas.append((si, s, g, j == 0))
    return metas


class FusedRolledEngine:
    """One-dispatch-per-page rolled prediction over a batched apply.

    ``apply_fn(params, x)`` must be traceable under ``jax.jit`` and map
    normalized ``[n, W, F]`` windows to ``[n, W, E, Q]`` predictions (the
    in-process model apply, or ``jax.export``'s ``Exported.call`` with
    ``params = ()``).  ``params`` is threaded through the jit as a runtime
    ARGUMENT, never a closure: baked-constant weights let XLA constant-fold
    parameter subgraphs (e.g. the soft feature mask) with its compile-time
    evaluator, whose rounding differs ~1 ulp from the runtime kernels —
    which would break bit parity with the ladder path's standalone apply.
    """

    # Accelerator default for coalesce_pages (InferConfig.coalesce_pages
    # None): 4 consecutive pages of the window plan fold into one
    # dispatch — 256 recurrence rows at the default rung-64 page, and the
    # bf16 inference kernel's VMEM block plan still fits at that width
    # (ops/pallas_gru.block_plan, re-validated round 11).  CPU stays at 1:
    # the per-window cost there is cache-bound and MINIMIZED at small
    # pages (PERF.md "rolled inference").
    ACCEL_COALESCE_PAGES = 4

    def __init__(self, apply_fn, x_stats, y_stats, window_size: int,
                 params=(),
                 delta_mask: np.ndarray | None = None,
                 median_index: int | None = None,
                 rungs=DEFAULT_FUSED_RUNGS,
                 page_windows: int | None = None,
                 coalesce_pages: int | None = None,
                 sparse_nnz_cap: int | None = None,
                 feature_dim: int | None = None,
                 quant: str = "off"):
        import jax

        # Quantized serving (round 22): the engine itself needs no quant
        # branch — ``params`` may be a quantized tree (ops/quantize.py)
        # and the owning backend's apply_fn dequantizes at use inside
        # the SAME jitted executables, so the per-rung executable count
        # is identical across modes.  The mode is recorded here so
        # ``stats()`` (the /healthz fused_infer block the flat-compile
        # probes read) names which mode its counters were measured at.
        self.quant = str(quant)

        rung_set = {int(r) for r in rungs}
        if page_windows is not None:
            if page_windows < 1:
                raise ValueError(f"page_windows {page_windows} must be >= 1")
            rung_set.add(int(page_windows))
        if coalesce_pages is None:
            coalesce_pages = (1 if jax.default_backend() == "cpu"
                              else self.ACCEL_COALESCE_PAGES)
        if coalesce_pages < 1:
            raise ValueError(f"coalesce_pages {coalesce_pages} must be >= 1")
        self.coalesce_pages = int(coalesce_pages)
        base_rungs = tuple(sorted(rung_set))
        if page_windows is not None:
            page = int(page_windows)
        elif jax.default_backend() == "cpu":
            # Measured on XLA CPU (PERF.md "rolled inference"): GRU
            # per-window cost is MINIMIZED at small batch — the recurrence
            # state stays cache-resident — and grows ~2x by rung 32/64.
            # Page at the smallest rung >= 8 so pages stay in cache;
            # larger rungs still serve explicit overrides.
            at_least_8 = [r for r in base_rungs if r >= 8]
            page = at_least_8[0] if at_least_8 else base_rungs[-1]
        else:
            # Accelerators want the widest batch the ladder offers (MXU
            # row occupancy; the CPU cache argument does not apply).
            page = base_rungs[-1]
        self.page = page
        # Page coalescing (round 11): up to ``coalesce_pages`` consecutive
        # pages of the window plan dispatch as ONE batch, so multi-series
        # and multi-scenario folds fill page·G recurrence rows instead of
        # paging thin.  The carry/segment-reset machinery already handles
        # any fold inside one batch, so this adds only the super-rungs
        # page·{2..G} to the jit ladder (one executable each, same as any
        # rung) and widens the dispatch loop's stride.
        rung_set.update(page * g for g in range(2, self.coalesce_pages + 1))
        self.rungs = tuple(sorted(rung_set))
        if not self.rungs or self.rungs[0] < 1:
            raise ValueError(f"bad fused rung set {rungs!r}")
        self._apply_fn = apply_fn
        self._params = params
        self.window_size = int(window_size)
        self.x_stats = x_stats
        self.y_stats = y_stats
        dm = (np.asarray(delta_mask, bool)
              if delta_mask is not None else None)
        self._has_delta = dm is not None and bool(dm.any())
        if self._has_delta and median_index is None:
            raise ValueError("delta_mask requires median_index for the "
                             "cross-window carry")
        self._delta = dm
        self._median = int(median_index) if median_index is not None else 0
        # Stats staged on device ONCE as runtime arguments (see module
        # docstring: baked constants break bit parity via strength
        # reduction).  x stats broadcast over the feature axis, y stats
        # over the metric axis of [R, W, E, Q].
        import jax.numpy as jnp

        self._x_mn = jnp.asarray(np.asarray(x_stats.min, np.float32).reshape(-1))
        self._x_rg = jnp.asarray(np.asarray(x_stats.range, np.float32).reshape(-1))
        y_mn = np.asarray(y_stats.min, np.float32).reshape(-1)
        y_rg = np.asarray(y_stats.range, np.float32).reshape(-1)
        self._y_mn = jnp.asarray(y_mn.reshape(1, 1, -1, 1))
        self._y_rg = jnp.asarray(y_rg.reshape(1, 1, -1, 1))
        n_carry = len(self._delta) if self._has_delta else 1
        self._carry0 = jnp.zeros((n_carry,), jnp.float32)
        if self._has_delta:
            self._delta_dev = jnp.asarray(self._delta)[None, None, :, None]
        self._jit = jax.jit(self._program)
        # Sparse-first entry (InferConfig.sparse_feed): windows arrive as
        # padded-COO ``(cols[R, W, K], vals[R, W, K])`` pages — ~F/(2K)
        # fewer host→device bytes at 10k-endpoint width — and densify via
        # ONE scatter-add (ops/densify.py) before the identical program
        # body, so outputs match the dense pages bit-for-bit and the
        # executable count stays flat: one sparse program per dispatched
        # rung (rung × K-cap), same as the dense ladder.
        self._sparse_nnz_cap = (int(sparse_nnz_cap)
                                if sparse_nnz_cap is not None else None)
        self._feature_dim = int(feature_dim) if feature_dim is not None \
            else None
        if self._sparse_nnz_cap is not None and self._feature_dim is None:
            raise ValueError("sparse_nnz_cap requires feature_dim (the "
                             "static dense width the scatter targets)")
        self._jit_sparse = (jax.jit(self._program_sparse)
                            if self._sparse_nnz_cap is not None else None)
        # AOT-deserialized executables (serve/aot.py): ``(kind, rung)``
        # (kind in {"dense", "sparse"}) -> a loaded ``Compiled`` taking
        # the SAME argument tree as the jitted program.  Dispatch prefers
        # these, so an AOT-warmed plane serves its rungs without ever
        # touching the jit cache — the executable ledger stays at zero
        # for AOT-served rungs, which is what the fleet bench's
        # zero-post-warmup-compiles gate asserts.  The dict OBJECT is
        # shared across every engine adopted from this one
        # (adopt_executables), so one load warms the whole fleet.
        self._aot: dict[tuple[str, int], object] = {}
        self._lock = threading.Lock()
        self._pages = 0
        self._sparse_pages = 0
        self._windows = 0
        self._padded_windows = 0
        self._series = 0
        self._max_dispatch_rows = 0
        self._aot_pages = 0
        self._compiled: set[int] = set()

    def adopt_executables(self, donor: "FusedRolledEngine") -> None:
        """Serve through the donor's compiled-program set (fleet tier,
        serve/fleet.py): params and normalization stats are runtime
        ARGUMENTS of the fused program (see module docstring — baked
        constants break bit parity), so engines of the same geometry can
        serve different tenants' weights through ONE executable ladder
        and ``jit_cache_size`` stays flat in the number of tenants.

        Only trace-time constants must match: the donor's program baked
        the window/delta/median/rung geometry and the params TREE
        structure (quant mode decides leaf dtypes), so each is checked
        loudly.  The dispatched-rung ledger and its lock are shared too
        — ``cache_size()``/``stats()`` read plane-wide truth from any
        adopted engine."""
        import jax

        if donor is self:
            return
        mine = dict(window_size=self.window_size, rungs=self.rungs,
                    page=self.page, quant=self.quant,
                    has_delta=self._has_delta, median=self._median,
                    sparse_nnz_cap=self._sparse_nnz_cap,
                    feature_dim=self._feature_dim)
        theirs = dict(window_size=donor.window_size, rungs=donor.rungs,
                      page=donor.page, quant=donor.quant,
                      has_delta=donor._has_delta, median=donor._median,
                      sparse_nnz_cap=donor._sparse_nnz_cap,
                      feature_dim=donor._feature_dim)
        if mine != theirs:
            diff = {k: (mine[k], theirs[k]) for k in mine
                    if mine[k] != theirs[k]}
            raise ValueError(
                "cannot share fused executables across mismatched engine "
                f"geometry (mine vs donor): {diff}")
        if not ((self._delta is None and donor._delta is None)
                or (self._delta is not None and donor._delta is not None
                    and np.array_equal(self._delta, donor._delta))):
            raise ValueError(
                "cannot share fused executables: delta masks differ "
                "(the mask is a trace-time constant of the program)")
        same_struct = (jax.tree_util.tree_structure(self._params)
                       == jax.tree_util.tree_structure(donor._params))
        if not same_struct:
            raise ValueError(
                "cannot share fused executables: params tree structures "
                "differ (a different tree re-traces a new executable)")
        # swap under our own (pre-adoption) lock so a concurrent dispatch
        # on this engine never sees a half-adopted program set; the
        # ``with`` holds the ORIGINAL lock object, so reassigning
        # self._lock last is safe — after this block every path uses the
        # donor's shared lock
        with self._lock:
            self._jit = donor._jit
            self._jit_sparse = donor._jit_sparse
            self._aot = donor._aot
            self._compiled = donor._compiled
            self._lock = donor._lock

    # -- device program -------------------------------------------------

    def _program(self, params, x, x_mn, x_rg, y_mn, y_rg, carry_in, g, seg,
                 n_valid, integrate):
        """``[R, W, F]`` raw windows -> (``[R, W, E, Q]``, carry ``[E]``).

        Output columns: delta metrics (when ``integrate``) de-normalized
        and integrated on device; everything else clamped NORMALIZED
        predictions (the host applies the reference invert — see module
        docstring).
        """
        import jax
        import jax.numpy as jnp

        r = x.shape[0]
        # mirror MinMaxStats.apply exactly (degenerate ranges pass through)
        xn = jnp.where(x_rg == 0.0, x,
                       (x - x_mn) / jnp.where(x_rg == 0.0, 1.0, x_rg))
        preds = self._apply_fn(params, xn)                 # [R, W, E, Q]
        preds = jnp.maximum(preds, 1e-6)
        if not self._has_delta:
            return preds, carry_in

        # De-normalize ON DEVICE for the integration arithmetic only (the
        # delta tolerance absorbs the FMA contraction); mirror
        # MinMaxStats.invert including the degenerate-range guard.
        denorm = jnp.where(y_rg == 0.0, preds, preds * y_rg + y_mn)
        csum = jnp.cumsum(denorm, axis=1)                  # [R, W, E, Q]
        med = csum[..., self._median]                      # [R, W, E]
        # per-window carry increment: the integrated median value the NEXT
        # window's base reads (full-window total for regular tiling, the
        # mid-window value feeding a ragged right-aligned tail)
        totals = jnp.take_along_axis(med, g[:, None, None], axis=1)[:, 0, :]
        valid = jnp.arange(r)[:, None] < n_valid
        totals = jnp.where(valid, totals, 0.0)             # [R, E]
        # segmented EXCLUSIVE prefix sum over the window axis: base_k is
        # the carry accumulated since the segment start (series/scenario
        # boundary), or carry_in + prefix for the page-continuing segment
        excl = jnp.cumsum(totals, axis=0) - totals
        idx = jnp.arange(r)
        start_pos = jax.lax.cummax(jnp.where(seg, idx, -1))
        seg_base = jnp.take(excl, jnp.clip(start_pos, 0, r - 1), axis=0)
        base = jnp.where(start_pos[:, None] >= 0,
                         excl - seg_base, excl + carry_in[None, :])
        last = jnp.clip(n_valid - 1, 0, r - 1)
        carry_out = (jnp.take(base, last, axis=0)
                     + jnp.take(totals, last, axis=0))
        integrated = base[:, None, :, None] + csum
        out = jnp.where(jnp.logical_and(self._delta_dev, integrate),
                        integrated, preds)
        return out, carry_out

    def _program_sparse(self, params, cols, vals, x_mn, x_rg, y_mn, y_rg,
                        carry_in, g, seg, n_valid, integrate):
        """Padded-COO twin of :meth:`_program`: one on-device scatter-add
        rebuilds the raw ``[R, W, F]`` page, then the SAME body runs —
        the densify is bit-exact (unique real columns + zero padding, see
        ops/densify.py), so sparse pages match dense pages bit-for-bit.
        """
        from deeprest_tpu.ops.densify import densify_coo

        x = densify_coo(cols, vals, self._feature_dim)
        return self._program(params, x, x_mn, x_rg, y_mn, y_rg, carry_in,
                             g, seg, n_valid, integrate)

    # -- host paging ----------------------------------------------------

    @property
    def page_windows(self) -> int:
        return self.page

    @property
    def sparse_enabled(self) -> bool:
        with self._lock:
            return self._jit_sparse is not None

    def rung_for(self, n: int) -> int:
        for r in self.rungs:
            if n <= r:
                return r
        raise ValueError(f"page of {n} windows exceeds top rung "
                         f"{self.rungs[-1]}; chunk before dispatching")

    def predict_many(self, series_list, integrate: bool = True):
        """Raw ``[T_i, F]`` series -> de-normalized ``[T_i, E, Q]`` each.

        All series fold into one window stream (segment resets at series
        boundaries), paged through the fused executable with the carry
        chained between pages on device.  ``integrate=False`` leaves
        delta-trained columns as raw per-bucket increments, matching
        ``rolled_prediction_reference(delta_mask=None)`` bit-exactly.
        """
        import jax.numpy as jnp

        w = self.window_size
        arrays = [np.ascontiguousarray(s, dtype=np.float32)
                  for s in series_list]
        if not arrays:
            return []
        metas = plan_windows([len(a) for a in arrays], w)
        # One span for the whole fused train of dispatches (per-page
        # spans would put recorder traffic inside the hot paging loop);
        # inherits the request's trace context from the calling thread.
        with obs_spans.RECORDER.span("fused.predict",
                                     component="deeprest-engine") as sp:
            sp.tag(series=len(arrays), windows=len(metas))
            return self._predict_many_inner(arrays, metas, integrate, jnp)

    def predict_many_sparse(self, sparse_series_list, integrate: bool = True):
        """Sparse-first entry: each series is a padded-COO
        ``(cols[T_i, K], vals[T_i, K])`` pair (``CallPathSpace.
        extract_sparse`` rows, or ``ops.densify.sparsify_rows`` output)
        instead of dense ``[T_i, F]``; results are identical de-normalized
        ``[T_i, E, Q]`` arrays, bit-for-bit equal to :meth:`predict_many`
        on the equivalent dense series (tests/test_sparse.py).  Pages
        ship as ``(cols, vals)`` — the ~F/(2K) feed-byte cut this entry
        exists for — and densify inside the fused executable."""
        if not self.sparse_enabled:
            raise ValueError(
                "sparse feed is not enabled on this engine; construct it "
                "with sparse_nnz_cap/feature_dim (InferConfig.sparse_feed)")
        import jax.numpy as jnp

        w = self.window_size
        k = self._sparse_nnz_cap
        arrays = []
        for cols, vals in sparse_series_list:
            cols = np.ascontiguousarray(cols, dtype=np.int32)
            vals = np.ascontiguousarray(vals, dtype=np.float32)
            if cols.shape != vals.shape or cols.ndim != 2:
                raise ValueError(
                    f"sparse series must be matching [T, K] cols/vals "
                    f"pairs, got {cols.shape} vs {vals.shape}")
            if cols.shape[1] != k:
                raise ValueError(
                    f"sparse series K={cols.shape[1]} != engine nnz cap "
                    f"{k}; pad rows to the configured --sparse-nnz-cap "
                    f"(a per-request K would compile per-request "
                    f"executables)")
            arrays.append((cols, vals))
        if not arrays:
            return []
        metas = plan_windows([len(c) for c, _ in arrays], w)
        with obs_spans.RECORDER.span("fused.predict_sparse",
                                     component="deeprest-engine") as sp:
            sp.tag(series=len(arrays), windows=len(metas))
            return self._predict_many_inner(arrays, metas, integrate, jnp,
                                            sparse=True)

    def _predict_many_inner(self, arrays, metas, integrate, jnp,
                            sparse: bool = False):
        w = self.window_size
        # Coalesced dispatch stride: up to coalesce_pages pages per batch
        # (the super-rungs are in self.rungs, so rung_for always fits).
        page = self.page * self.coalesce_pages
        # snapshot the program tables once — adopt_executables swaps them
        # under the same lock, so the whole dispatch below runs against
        # one coherent (jit, aot) generation
        with self._lock:
            aot_table = self._aot
            jit_dense = self._jit
            jit_sparse = self._jit_sparse
            params = self._params
        # A concurrent LRU spill (serve/fleet.py) swaps the tree for host
        # numpy copies between resolve() and this dispatch; numpy leaves
        # key a DIFFERENT executable signature than device arrays, so
        # dispatching them would mint a second cache entry and trip the
        # pool's frozen ledger.  Normalize to device arrays — the exact
        # device_put a restore would have done, so values and the
        # executable signature are both unchanged.
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        if leaves and not isinstance(leaves[0], jax.Array):
            params = jax.tree_util.tree_map(jax.device_put, params)
        carry = self._carry0
        dispatched = []
        pages = padded = aot_pages = 0
        lengths = [len(a[0]) if sparse else len(a) for a in arrays]
        for lo in range(0, len(metas), page):
            chunk = metas[lo:lo + page]
            rung = self.rung_for(len(chunk))
            g = np.full((rung,), w - 1, np.int32)
            seg = np.zeros((rung,), np.bool_)
            if sparse:
                k = self._sparse_nnz_cap
                xc = np.zeros((rung, w, k), np.int32)
                xv = np.zeros((rung, w, k), np.float32)
                for row, (si, s, gg, is_first) in enumerate(chunk):
                    cols_i, vals_i = arrays[si]
                    xc[row] = cols_i[s:s + w]
                    xv[row] = vals_i[s:s + w]
                    g[row] = gg
                    seg[row] = is_first
                # AOT-deserialized executable for this (kind, rung) when
                # one is loaded (serve/aot.py); the lazily-jitted program
                # otherwise — identical lowering, identical results.
                fn = aot_table.get(("sparse", rung))
                aot_pages += fn is not None
                out, carry = (fn or jit_sparse)(
                    params, jnp.asarray(xc), jnp.asarray(xv),
                    self._x_mn, self._x_rg, self._y_mn, self._y_rg,
                    carry, jnp.asarray(g), jnp.asarray(seg),
                    np.int32(len(chunk)), np.bool_(integrate))
            else:
                feat = arrays[0].shape[1]
                x = np.zeros((rung, w, feat), np.float32)
                for row, (si, s, gg, is_first) in enumerate(chunk):
                    x[row] = arrays[si][s:s + w]
                    g[row] = gg
                    seg[row] = is_first
                fn = aot_table.get(("dense", rung))
                aot_pages += fn is not None
                out, carry = (fn or jit_dense)(
                    params, jnp.asarray(x), self._x_mn, self._x_rg,
                    self._y_mn, self._y_rg, carry, jnp.asarray(g),
                    jnp.asarray(seg), np.int32(len(chunk)),
                    np.bool_(integrate))
            dispatched.append((out, chunk))
            pages += 1
            padded += rung - len(chunk)
        with self._lock:
            self._pages += pages
            self._aot_pages += aot_pages
            if sparse:
                self._sparse_pages += pages
            self._windows += len(metas)
            self._padded_windows += padded
            self._series += len(arrays)
            if dispatched:
                self._max_dispatch_rows = max(
                    self._max_dispatch_rows,
                    max(self.rung_for(len(c)) for _, c in dispatched))
            self._compiled.update(self.rung_for(len(c)) for _, c in dispatched)

        out_dims = None
        use_device_delta = self._has_delta and integrate
        outs: list[np.ndarray | None] = [None] * len(arrays)
        for out_dev, chunk in dispatched:
            # graftlint: disable=JX003 -- designed sink: every page was already dispatched async above; this loop is the pipeline's readback phase
            arr = np.asarray(out_dev)                      # [R, W, E, Q]
            # host-side invert, in the reference's exact op order/layout,
            # for the columns the device left normalized (bit parity)
            inv = self.y_stats.invert(
                arr.transpose(0, 1, 3, 2)).transpose(0, 1, 3, 2)
            if use_device_delta:
                arr = np.where(self._delta[None, None, :, None], arr, inv)
            else:
                arr = inv
            if out_dims is None:
                out_dims = arr.shape[2:]                   # (E, Q)
                for si, t in enumerate(lengths):
                    outs[si] = np.empty((t, *out_dims), np.float32)
            for row, (si, s, _, _) in enumerate(chunk):
                outs[si][s:s + w] = arr[row]   # later (ragged) window wins
        return outs

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "rungs": list(self.rungs),
                "page_windows": self.page,
                "coalesce_pages": self.coalesce_pages,
                "pages": self._pages,
                "sparse_pages": self._sparse_pages,
                "windows": self._windows,
                "padded_windows": self._padded_windows,
                "series": self._series,
                "max_dispatch_rows": self._max_dispatch_rows,
                "dispatched_rungs": sorted(self._compiled),
                # AOT serving surface (serve/aot.py): which rungs hold a
                # deserialized executable, and how many pages they served
                # (those pages never touched the jit cache)
                "aot_rungs": sorted(r for _, r in self._aot),
                "aot_pages": self._aot_pages,
                "sparse_nnz_cap": self._sparse_nnz_cap,
                "quant": self.quant,
            }

    def cache_size(self) -> int | None:
        """Compiled-executable count across the dense AND sparse fused
        programs (None when the running jax version has no cache probe)."""
        with self._lock:
            programs = (self._jit, self._jit_sparse)
        sizes = []
        for fn in programs:
            probe = getattr(fn, "_cache_size", None) if fn is not None \
                else None
            if callable(probe):
                sizes.append(int(probe()))
        return sum(sizes) if sizes else None


class FusedInferenceMixin:
    """Shared by Predictor and ExportedPredictor: the fused device-resident
    ``predict_series`` / ``predict_series_many`` entry points, layered over
    the shape-laddered host path (serve/batcher.BatchedBackendMixin).

    Routing: the fused engine serves every series when no cross-request
    MicroBatcher is attached.  With a batcher attached, series that fit a
    single ladder dispatch keep routing through it (coalescing tiny
    concurrent requests is the batcher's win), while longer series — which
    would monopolize coalesced batches anyway — take the fused path.
    """

    _fused: FusedRolledEngine | None = None

    def _init_fused(self, apply_fn, params=(), enabled: bool = True,
                    page_windows: int | None = None,
                    coalesce_pages: int | None = None,
                    sparse_nnz_cap: int | None = None) -> None:
        if not enabled:
            self._fused = None
            return
        self._fused = FusedRolledEngine(
            apply_fn, self.x_stats, self.y_stats, self.window_size,
            params=params,
            delta_mask=self.delta_mask, median_index=self.median_index(),
            rungs=self.ladder.base_ladder, page_windows=page_windows,
            coalesce_pages=coalesce_pages,
            sparse_nnz_cap=sparse_nnz_cap,
            feature_dim=(self.feature_dim if sparse_nnz_cap is not None
                         else None),
            quant=getattr(self, "quant", "off"))

    @property
    def fused(self) -> FusedRolledEngine | None:
        return self._fused

    def _num_windows(self, t: int) -> int:
        w = self.window_size
        n = (t - w) // w + 1
        return n + (1 if (t - w) % w != 0 else 0)

    def _route_fused(self, t: int) -> bool:
        if self._fused is None:
            return False
        if getattr(self, "_batcher", None) is None:
            return True
        return self._num_windows(t) > self.ladder.max_rung

    def predict_series(self, traffic: np.ndarray,
                       integrate: bool = True) -> np.ndarray:
        """[T, F] raw traffic -> de-normalized [T, E, Q] predictions.

        Fused device path by default (see :class:`FusedRolledEngine`);
        falls back to the pinned host loop
        (:func:`~deeprest_tpu.serve.predictor.rolled_prediction_reference`)
        through ``apply_windows`` when the engine is disabled or when a
        MicroBatcher should coalesce this request (see class docstring).
        ``integrate=False`` leaves delta-trained columns as raw per-bucket
        increments — the sharper domain for anomaly detection.
        """
        traffic = np.asarray(traffic)
        if self._route_fused(len(traffic)):
            sparse = self._maybe_sparsify([traffic])
            if sparse is not None:
                return self._fused.predict_many_sparse(
                    sparse, integrate=integrate)[0]
            return self._fused.predict_many([traffic], integrate=integrate)[0]
        from deeprest_tpu.serve.predictor import rolled_prediction_reference

        return rolled_prediction_reference(
            self.apply_windows, self.x_stats, self.y_stats,
            self.window_size, traffic,
            delta_mask=self.delta_mask if integrate else None,
            median_index=self.median_index())

    _warned_fat_rows = False

    def _maybe_sparsify(self, series_list):
        """Host-side dense→COO conversion for a sparse_feed backend: the
        wire format is dense (HTTP JSON, featurized corpora), but when
        the engine's sparse program is up the device should still get the
        ~F/(2K)-smaller padded-COO pages — outputs are bit-identical
        either way.  Returns None (caller ships dense) when the feature
        is off or any row overflows the K cap; the overflow is warned
        ONCE, not raised — an explicitly-sparse caller chose the format
        and gets the loud error, a dense caller never handed us COO and
        must not 500 because one bucket ran hot."""
        if not (getattr(self, "sparse_feed", False)
                and self._fused is not None
                and self._fused.sparse_enabled):
            return None
        from deeprest_tpu.ops.densify import sparsify_rows

        try:
            return [sparsify_rows(s, self._fused._sparse_nnz_cap)[:2]
                    for s in series_list]
        except ValueError as exc:
            if not FusedInferenceMixin._warned_fat_rows:
                FusedInferenceMixin._warned_fat_rows = True
                print(f"sparse-feed: dense fallback for a request "
                      f"({exc}); raise --sparse-nnz-cap to keep the "
                      "sparse feed (warned once)")
            return None

    def predict_series_sparse(self, cols: np.ndarray, vals: np.ndarray,
                              integrate: bool = True) -> np.ndarray:
        """Sparse-first twin of :meth:`predict_series`: ``(cols[T, K],
        vals[T, K])`` padded-COO raw traffic → de-normalized ``[T, E, Q]``
        predictions, bit-identical to the dense entry on the equivalent
        series.

        Routes through the fused engine's sparse program when the backend
        was built with ``sparse_feed`` (the ~F/(2K) feed-byte path);
        otherwise densifies ON HOST — bit-exact by construction — and
        falls back to the dense entry, so sparse callers work against any
        backend (e.g. exported artifacts, which bake a dense signature).
        """
        if (self._fused is not None and self._fused.sparse_enabled
                and np.asarray(cols).shape[-1]
                == self._fused._sparse_nnz_cap):
            return self._fused.predict_many_sparse(
                [(cols, vals)], integrate=integrate)[0]
        from deeprest_tpu.ops.densify import densify_rows

        return self.predict_series(
            densify_rows(cols, vals, self.feature_dim),
            integrate=integrate)

    def predict_series_many_sparse(self, sparse_series_list,
                                   integrate: bool = True
                                   ) -> list[np.ndarray]:
        """Batched sparse entry: S ``(cols[T_i, K], vals[T_i, K])`` pairs
        fold into the fused engine's scenario×window axis exactly like
        :meth:`predict_series_many` (shared pages, per-series carry
        resets), shipped as COO."""
        sparse_series_list = list(sparse_series_list)
        if (self._fused is not None and self._fused.sparse_enabled
                and all(np.shape(c)[-1] == self._fused._sparse_nnz_cap
                        for c, _ in sparse_series_list)):
            return self._fused.predict_many_sparse(sparse_series_list,
                                                   integrate=integrate)
        return [self.predict_series_sparse(c, v, integrate=integrate)
                for c, v in sparse_series_list]

    def predict_series_many(self, series_list,
                            integrate: bool = True) -> list[np.ndarray]:
        """Batched multi-series entry: S raw ``[T_i, F]`` series fold into
        the scenario×window batch axis of the fused engine (shared pages,
        per-series carry resets) — the backbone of
        ``WhatIfEstimator.estimate_many`` and capacity sweeps.  Falls back
        to per-series prediction when the fused engine is disabled."""
        if self._fused is not None:
            series_list = list(series_list)
            sparse = self._maybe_sparsify(
                [np.asarray(s) for s in series_list])  # graftlint: disable=JX003 -- host data: wire-format series are numpy arrays/lists, asarray never touches a device buffer
            if sparse is not None:
                return self._fused.predict_many_sparse(sparse,
                                                       integrate=integrate)
            return self._fused.predict_many(series_list,
                                            integrate=integrate)
        return [self.predict_series(s, integrate=integrate)
                for s in series_list]
