"""Portable inference artifact: checkpoint → serialized StableHLO on disk.

The north star names this path explicitly ("predictor/ exports via jax2tf
for the Go gRPC server" — BASELINE.json north_star; SURVEY.md §7.1 step 6):
an inference artifact a non-JAX consumer can load.  TensorFlow is not in
this image, so the artifact is ``jax.export``'s portable serialization —
versioned StableHLO with the trained parameters baked in as constants and a
*symbolic* batch dimension, executable by any PJRT-capable runtime (and by
``jax.export.deserialize`` here).  Everything else a consumer needs —
normalization statistics, metric names, quantiles, the call-path feature
space — rides next to it in a plain-JSON manifest, so serving state cannot
drift from training state (the same property Predictor gets from the
checkpoint sidecar).

Layout of an artifact directory::

    model.stablehlo   serialized jax.export artifact  (binary)
    manifest.json     stats + names + dims + model config  (JSON)
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jexport

from deeprest_tpu.data.windows import MinMaxStats
from deeprest_tpu.models.qrnn import resolve_params
from deeprest_tpu.serve.batcher import BatchedBackendMixin
from deeprest_tpu.serve.fused import FusedInferenceMixin
from deeprest_tpu.serve.predictor import Predictor

ARTIFACT_BLOB = "model.stablehlo"
ARTIFACT_MANIFEST = "manifest.json"
_FORMAT = "jax.export/stablehlo"
_PLATFORMS = ("cpu", "tpu")


def export_predictor(pred: Predictor, directory: str) -> str:
    """Serialize ``pred`` into ``directory`` (created if needed).

    The exported computation is the deterministic forward pass on
    *normalized* windows ``[b, W, F] -> [b, W, E, Q]`` with ``b``
    symbolic, lowered for both cpu and tpu so one artifact serves on
    either; normalization/de-normalization are host-side (manifest).
    """
    os.makedirs(directory, exist_ok=True)
    (b,) = jexport.symbolic_shape("b")
    spec = jax.ShapeDtypeStruct(
        (b, pred.window_size, pred.feature_dim), jnp.float32)
    # resolve_params: a quantized predictor's tree dequantizes at trace
    # time, so the artifact bakes the quantized-then-dequantized values —
    # the exported module reproduces the quantized numerics (and the
    # manifest carries the mode + its measured parity envelope below).
    fn = jax.jit(lambda x: pred.model.apply(
        # graftlint: disable=JX001 -- deliberate: the artifact's whole point is baking the trained params into the serialized module as constants; bit parity vs the in-process path is pinned by tests/test_export_serve.py
        {"params": resolve_params(pred.params)}, x, deterministic=True))
    exported = jexport.export(fn, platforms=_PLATFORMS)(spec)
    with open(os.path.join(directory, ARTIFACT_BLOB), "wb") as f:
        f.write(exported.serialize())
    manifest = {
        "format": _FORMAT,
        "platforms": list(_PLATFORMS),
        "metric_names": pred.metric_names,
        "window_size": pred.window_size,
        "feature_dim": pred.feature_dim,
        "quantiles": list(pred.quantiles),
        "x_stats": pred.x_stats.to_dict(),
        "y_stats": pred.y_stats.to_dict(),
        "model_config": dataclasses.asdict(pred.model_config),
        "space": pred.space_dict,
        "delta_mask": (np.asarray(pred.delta_mask, bool).tolist()
                       if pred.delta_mask is not None else None),
        # quantized-serving provenance (round 22): the mode the baked
        # weights were quantized at, plus the measured-at-quantize-time
        # parity envelope — restoring at a DIFFERENT mode raises
        # (ExportedPredictor.load), never silently serves other numerics
        "quant": getattr(pred, "quant", "off"),
        "quant_parity": getattr(pred, "parity_envelope", None),
    }
    with open(os.path.join(directory, ARTIFACT_MANIFEST), "w",
              encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return directory


def export_aot_sidecar(pred: Predictor, checkpoint_dir: str,
                       rungs=None) -> dict:
    """Compile + serialize the fused serving executables NEXT TO THE
    CHECKPOINT (``<ckpt>/aot/`` — serve/aot.py), the export-time half of
    fleet admission-by-deserialize: a pool admitting this checkpoint
    loads the artifacts instead of compiling the ladder.  Unlike the
    StableHLO artifact above, AOT sidecars are params-AGNOSTIC (params
    are runtime arguments) but platform-exact — the manifest fingerprint
    gates the load.  Returns a summary of what was written."""
    from deeprest_tpu.serve.aot import export_aot

    manifest = export_aot(pred, checkpoint_dir, rungs=rungs)
    entries = manifest["entries"]
    return {
        "dir": os.path.join(checkpoint_dir, "aot"),
        "executables": len(entries),
        "bytes": sum(e["bytes"] for e in entries),
        "rungs": sorted({e["rung"] for e in entries}),
        "platform": manifest["fingerprint"]["platform"],
    }


class ExportedPredictor(BatchedBackendMixin, FusedInferenceMixin):
    """Drop-in serving backend loaded from an artifact directory.

    Exposes the same serving protocol as :class:`Predictor`
    (``predict_series``, ``metric_names``, ``window_size``, ``quantiles``,
    ``feature_dim``, ``median_index``, ``space``, and the batched
    ``apply_windows`` entry point incl. MicroBatcher attachment), so
    AnomalyDetector, WhatIfEstimator, and the HTTP server work unchanged
    on either backend.  The artifact's symbolic batch dimension still
    compiles one executable per concrete shape it sees — the shape ladder
    bounds that set to the rungs, exactly as on the in-process path.
    """

    def __init__(self, exported: jexport.Exported, manifest: dict,
                 ladder: tuple[int, ...] | None = None,
                 fused: bool = True,
                 page_windows: int | None = None,
                 coalesce_pages: int | None = None,
                 coalesce_groups: int = 1,
                 quant: str = "off"):
        if manifest.get("format") != _FORMAT:
            raise ValueError(f"unknown artifact format {manifest.get('format')!r}")
        baked = str(manifest.get("quant", "off"))
        if str(quant) != baked:
            # A quantized artifact's weights are baked at export time; the
            # caller cannot change the numerics here, only acknowledge
            # them.  Refusing beats silently serving numerics the operator
            # did not opt into (the parity envelope belongs to ``baked``).
            raise ValueError(
                f"artifact was exported at quant={baked!r} but load was "
                f"asked for quant={quant!r}; pass --quant {baked} "
                f"(ExportedPredictor.load(..., quant={baked!r})) to serve "
                "it, or re-export at the mode you want")
        self.quant = baked
        self.parity_envelope = manifest.get("quant_parity")
        self._exported = exported
        self.manifest = manifest
        self.metric_names: list[str] = list(manifest["metric_names"])
        self.window_size: int = int(manifest["window_size"])
        self.feature_dim: int = int(manifest["feature_dim"])
        self.quantiles: tuple[float, ...] = tuple(manifest["quantiles"])
        self.x_stats = MinMaxStats.from_dict(manifest["x_stats"])
        self.y_stats = MinMaxStats.from_dict(manifest["y_stats"])
        self.space_dict = manifest.get("space")
        dm = manifest.get("delta_mask")
        self.delta_mask = np.asarray(dm, bool) if dm is not None else None
        self._init_batching(self._exported.call, ladder=ladder,
                            coalesce_groups=coalesce_groups)
        # Exported.call is traceable under jit, so the deserialized
        # StableHLO module composes into the same fused one-dispatch
        # pipeline the in-process Predictor uses (serve/fused.py).  The
        # artifact's weights are baked into the module; params stay ().
        self._init_fused(lambda _, x: self._exported.call(x),
                         enabled=fused, page_windows=page_windows,
                         coalesce_pages=coalesce_pages)

    @classmethod
    def load(cls, directory: str,
             ladder: tuple[int, ...] | None = None,
             fused: bool = True,
             page_windows: int | None = None,
             coalesce_pages: int | None = None,
             coalesce_groups: int = 1,
             quant: str = "off") -> "ExportedPredictor":
        with open(os.path.join(directory, ARTIFACT_MANIFEST),
                  encoding="utf-8") as f:
            manifest = json.load(f)
        with open(os.path.join(directory, ARTIFACT_BLOB), "rb") as f:
            exported = jexport.deserialize(f.read())
        return cls(exported, manifest, ladder=ladder, fused=fused,
                   page_windows=page_windows, coalesce_pages=coalesce_pages,
                   coalesce_groups=coalesce_groups, quant=quant)

    def jit_cache_size(self) -> int | None:
        """Fused-pipeline executable count (the artifact's own symbolic-
        batch apply has no probe); None when the engine is disabled."""
        return (self._fused.cache_size()
                if self._fused is not None else None)

    def median_index(self) -> int:
        diffs = [abs(q - 0.5) for q in self.quantiles]
        return diffs.index(min(diffs))

    def space(self):
        """The training corpus's CallPathSpace (see Predictor.space)."""
        if self.space_dict is None:
            return None
        from deeprest_tpu.data.featurize import CallPathSpace

        return CallPathSpace.from_dict(self.space_dict)

    # predict_series / predict_series_many come from FusedInferenceMixin —
    # identical tiling/integration/routing semantics to the in-process
    # Predictor (fused device pipeline by default, shape-laddered
    # rolled_prediction_reference through ``apply_windows`` otherwise).
