"""The two comparison baselines the evaluation harness co-runs.

Semantics replicate the reference line-for-line-comparable behavior
(reference: resource-estimation/baselines.py) so MAE tables stay
apples-to-apples (SURVEY.md §7.1 step 5):

- **ResourceAware** — history-only MLP: trains on (resource window at
  t−offset → resource window at t) pairs, then predicts a *single* window
  from a fixed train-time input and repeats it for every test step
  (reference: baselines.py:40-77).
- **ComponentAware** — linear rescaling of the component's invocation-count
  series onto the metric's train-split range
  (reference: baselines.py:80-110), falling back to the total request count
  when a component never appears in traces (reference: baselines.py:86).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprest_tpu.data.windows import minmax_fit


@dataclasses.dataclass
class ResourceAwareBaseline:
    """History-only MLP baseline (no traffic input)."""

    split: int
    window_size: int
    offset: int | None = None          # default: window_size - 1, as reference
    hidden_size: int = 128
    num_epochs: int = 100
    batch_size: int = 32
    learning_rate: float = 1e-3
    seed: int = 0

    def fit_and_estimate(self, y: np.ndarray) -> np.ndarray:
        """y: [N, W, 1] windowed metric series → [N - split, W, 1] estimates."""
        offset = self.offset if self.offset is not None else self.window_size - 1

        stats = minmax_fit(y, split=self.split)
        y_n = stats.apply(y).astype(np.float32)

        # (input window at i-offset, target window at i) pairs.
        inputs = y_n[:-offset, :, 0] if offset > 0 else y_n[:, :, 0]
        targets = y_n[offset:, :, 0]
        split_local = self.split - offset
        x_train, t_train = inputs[:split_local], targets[:split_local]

        params = self._train(x_train, t_train)

        # Predict one window from the fixed train-time input the reference
        # uses (pair index split_local - offset, i.e. series index
        # split - 2*offset; reference: baselines.py:69-71) and repeat it.
        probe_idx = max(split_local - offset, 0)
        pred = np.asarray(self._forward(params, inputs[probe_idx]))
        pred = np.maximum(stats.invert(pred), 1e-6)

        num_test = len(y) - self.split
        return np.tile(pred, (num_test, 1))[:, :, None]

    # -- internals ---------------------------------------------------------

    def _init_params(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        w_in, h = self.window_size, self.hidden_size
        s1, s2 = 1.0 / np.sqrt(w_in), 1.0 / np.sqrt(h)
        return {
            "w1": jax.random.uniform(k1, (w_in, h), jnp.float32, -s1, s1),
            "b1": jax.random.uniform(k2, (h,), jnp.float32, -s1, s1),
            "w2": jax.random.uniform(k3, (h, w_in), jnp.float32, -s2, s2),
            "b2": jax.random.uniform(k4, (w_in,), jnp.float32, -s2, s2),
        }

    @staticmethod
    def _forward(params, x):
        hidden = jax.nn.relu(x @ params["w1"] + params["b1"])
        return hidden @ params["w2"] + params["b2"]

    def _train(self, x_train: np.ndarray, t_train: np.ndarray):
        params = self._init_params(jax.random.PRNGKey(self.seed))
        tx = optax.adam(self.learning_rate)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, xb, tb):
            def loss_fn(p):
                return jnp.mean((self._forward(p, xb) - tb) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        n = len(x_train)
        if n == 0:
            return params
        rng = np.random.default_rng(self.seed)
        for _ in range(self.num_epochs):
            order = rng.permutation(n)
            for lo in range(0, n, self.batch_size):
                sel = order[lo:lo + self.batch_size]
                params, opt_state, _ = step(
                    params, opt_state, jnp.asarray(x_train[sel]), jnp.asarray(t_train[sel])
                )
        return params


def component_scaling_fit(inv_train: np.ndarray,
                          metric_train: np.ndarray) -> tuple:
    """The reference's four scaling weights from a train split
    (reference: baselines.py:97-104): min invocations, metric range,
    invocation range, metric floor."""
    return (
        float(np.min(inv_train)),
        float(np.max(metric_train) - np.min(metric_train)),
        float(np.max(inv_train) - np.min(inv_train)),
        float(np.min(metric_train)),
    )


def component_scaling_apply(inv: np.ndarray, weights: tuple) -> np.ndarray:
    """``(inv − w1)·w2/w3 + w4`` with the reference's branches
    (reference: baselines.py:105-109; the degenerate w3=0 case divides by
    zero there — pinned to the train-split floor instead)."""
    w1, w2, w3, w4 = weights
    if inv.sum() > 0 and w3 > 0:
        ts_hat = (inv - w1) * w2 / w3 + w4
    elif inv.sum() > 0:
        ts_hat = np.full_like(inv, w4)
    else:
        ts_hat = np.asarray(inv, dtype=np.float64)
    return np.maximum(ts_hat, 1e-6)


@dataclasses.dataclass
class ComponentAwareBaseline:
    """Linear invocation-count → metric-range rescaling baseline."""

    split: int
    window_size: int
    component: str
    invocations: Mapping[str, np.ndarray]

    def fit_and_estimate(self, y: np.ndarray) -> np.ndarray:
        """y: [N, W, 1] windowed metric series → [N - split, W, 1] estimates."""
        w = self.window_size
        inv = self.invocations[
            self.component if self.component in self.invocations else "general"
        ]
        inv = np.asarray(inv, dtype=np.float64)

        # Reassemble the un-windowed series: first element of every window
        # but the last, then the whole last window (reference:
        # baselines.py:95) — length T-1 for T raw buckets.
        ts = np.concatenate([y[:-1, 0, 0], y[-1, :, 0]])

        split_series = self.split + w - 1
        weights = component_scaling_fit(inv[:split_series], ts[:split_series])
        ts_hat = component_scaling_apply(inv, weights)

        windows = np.asarray([ts_hat[i - w:i] for i in range(w, len(ts) + 1)])
        return windows[self.split:][:, :, None]


def baseline_predictions(data, bundle, resource_epochs: int = 100) -> dict[str, np.ndarray]:
    """Both baselines on every metric, aligned with ``bundle``'s test windows.

    Returns ``{"resrc"|"comp": [N_test, W, E]}`` de-normalized predictions —
    the two comparison columns of the reference's per-epoch eval table
    (reference: estimate.py:31-39,112-122).
    """
    from deeprest_tpu.data.windows import sliding_windows

    w = bundle.window_size
    targets = data.targets()
    resrc, comp = [], []
    for idx, name in enumerate(bundle.metric_names):
        y_m = sliding_windows(targets[:, [idx]], w)     # [N, W, 1] raw scale
        component = name.rsplit("_", 1)[0]
        resrc.append(
            ResourceAwareBaseline(split=bundle.split, window_size=w,
                                  num_epochs=resource_epochs).fit_and_estimate(y_m)
        )
        comp.append(
            ComponentAwareBaseline(split=bundle.split, window_size=w,
                                   component=component,
                                   invocations=data.invocations).fit_and_estimate(y_m)
        )
    return {
        "resrc": np.concatenate(resrc, axis=-1),
        "comp": np.concatenate(comp, axis=-1),
    }
