"""The estimator: a multi-task, attention-masked, quantile GRU.

Capability parity with the reference model (reference:
resource-estimation/qrnn.py:6-55): per metric (component×resource) one
*expert* consisting of (a) a learned soft feature mask over the shared
traffic features (the "attention-based API-call encoder"), (b) a
bidirectional GRU over the time window, and (c) a quantile head fed with
``concat(mean of all other experts' GRU outputs, own GRU output)`` — the
cross-metric knowledge-sharing path.

TPU-first re-design (not a translation):

- **Experts are an array axis, not a ModuleList.**  All per-expert weights
  carry a leading ``E`` axis, so the whole model is one set of batched
  einsums — MXU-friendly, and expert parallelism is a sharding annotation
  on axis 0 (SURVEY.md §2.5/§7.1).
- **The mask is folded into the GRU input weights.**  ``(x ⊙ mask_e) @ W``
  ≡ ``x @ (mask_e[:,None] ⊙ W)``, so the masked input is never materialized
  per expert: the hoisted input projection reads ``x`` once — O(B·T·F)
  HBM traffic instead of O(E·B·T·F).
- **Cross-expert mixing is O(E), not O(E²).**  ``mean_{j≠i}(out_j)``
  = ``(Σ_j out_j − out_i) / (E−1)`` — the all-pairs stack/mean the
  reference materializes is computed from one global sum (SURVEY.md §7.3).

Deviation (documented): for ``num_metrics == 1`` the reference's mean over
the empty "others" set is undefined (it would crash); here the mix input
falls back to the expert's own output.

Coalescing plumbing (round 11): the window-coalesced trainer and the fused
serving engine both fold G independent window batches into the batch (row)
axis of ONE recurrence call.  Two hooks support that here:

- **Group axis**: ``__call__`` accepts ``[G, B, T, F]`` and flattens the
  group axis into the rows (``[G·B, T, F]``) around the shared pipeline —
  every op is row-independent, so each group's slice of the output is
  bit-identical to a standalone ``[B, T, F]`` call.
- **External mask fold**: :func:`feature_mask` / :func:`fold_feature_mask`
  lift the soft-mask computation and its fold into the layer-0 input
  weights out of the module (single source — ``__call__`` calls the same
  functions), and ``mask_folded=True`` tells ``__call__`` the caller
  already folded.  The coalesced trainer's exact-gradient mode needs
  this: the mask subgraph is params-only, so under ``jax.vmap`` its
  backward would otherwise run ONCE on a pre-summed cotangent (different
  float association than the per-microbatch loop it must match
  bit-for-bit); staging it through an explicit ``jax.vjp`` keeps the
  mask backward per-group and unbatched, exactly like the loop.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from deeprest_tpu.config import ModelConfig
from deeprest_tpu.ops.gru import GRUParams, bidirectional_gru, gru

MASK_PARAM_NAMES = ("mask_w1", "mask_b1", "mask_w2", "mask_b2")
# Layer-0 input weights the soft mask folds into ((x ⊙ m) @ W ≡ x @ (m ⊙ W));
# only the keys present in the params tree apply (bwd exists iff bidirectional).
MASKED_PARAM_NAMES = ("gru_fwd_w_ih", "gru_bwd_w_ih")


def resolve_params(params):
    """Weights-adapter hook (round 22): dequantize-at-use for a
    quantized serving param tree (ops/quantize.quantize_params),
    identity for f32/bf16 trees.

    The jitted serving wrappers (serve/predictor.py) call this BEFORE
    ``model.apply`` sees the tree: flax validates supplied param leaf
    shapes against init, so int8+scale ``QuantTensor`` pairs must
    resolve back to plain ``[.., K, C]`` arrays first.  The dequant
    still runs ON DEVICE inside the calling executable (this is traced
    code), through the one sanctioned site — ops/quantize.dequantize —
    shared with the ops-level ``gru.resolve_weights`` hook."""
    from deeprest_tpu.ops.quantize import dequantize_params

    return dequantize_params(params)


def feature_mask(params) -> jax.Array:
    """The learned soft feature mask ``[E, F]`` from the mask parameters.

    Single source of the mask math: ``QuantileGRU.__call__`` routes through
    this same function, so an externally computed mask (the coalesced
    trainer's ``jax.vjp`` prologue) is bit-identical to the in-module one.
    Mirrors the reference encoder: Linear(1→H) on a constant 1.0 input is
    just (weight + bias), then ReLU → Linear(H→F) → softmax
    (reference: resource-estimation/qrnn.py:20-26,33-36).
    """
    hidden_act = nn.relu(params["mask_w1"] + params["mask_b1"])     # [E, H]
    logits = (jnp.einsum("eh,ehf->ef", hidden_act, params["mask_w2"])
              + params["mask_b2"])
    return jax.nn.softmax(logits, axis=-1)                          # [E, F]


def fold_feature_mask(params):
    """Fold the soft mask into the layer-0 input weights, tree-level.

    Returns a new params mapping where every ``MASKED_PARAM_NAMES`` leaf is
    replaced by ``mask[:, :, None] * w_ih`` — exactly the fold
    ``__call__`` applies internally (``(x ⊙ m) @ W ≡ x @ (m ⊙ W)``).
    Apply the result with ``mask_folded=True``.  The coalesced trainer
    stages this through ``jax.vjp`` so the mask/fold backward runs
    per-microbatch and unbatched (see module docstring).
    """
    mask = feature_mask(params)
    out = dict(params)
    for name in MASKED_PARAM_NAMES:
        if name in out:
            out[name] = mask[:, :, None] * out[name]
    return out


class QuantileGRU(nn.Module):
    """Multi-task quantile GRU.

    Input ``[B, T, F]`` traffic-feature windows → output ``[B, T, E, Q]``
    per-metric quantile predictions.
    """

    config: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True,
                 mask_folded: bool = False) -> jax.Array:
        cfg = self.config
        e, f, h, q = cfg.num_metrics, cfg.feature_dim, cfg.hidden_size, len(cfg.quantiles)
        if x.shape[-1] != f:
            raise ValueError(f"input feature dim {x.shape[-1]} != config.feature_dim {f}")
        compute_dtype = jnp.dtype(cfg.compute_dtype)

        # Group axis (coalescing plumbing): [G, B, T, F] folds its groups
        # into the row axis for the whole pipeline — one fat recurrence
        # call instead of G thin ones — and unfolds on the way out.  Every
        # op maps rows independently, so each group's output slice is
        # bit-identical to a standalone [B, T, F] call (pinned by
        # tests/test_coalesce.py).
        group_shape = None
        if x.ndim == 4:
            group_shape = x.shape[:2]
            x = x.reshape(group_shape[0] * group_shape[1], *x.shape[2:])

        def uniform_pm(scale):
            def _init(key, shape, dtype=jnp.float32):
                return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)
            return _init

        # (a) learned soft feature mask — Linear(1→H) → ReLU → Linear(H→F)
        # → softmax, driven by a constant 1.0 (reference: qrnn.py:20-26,33-36).
        # Linear(1→H) on a constant input is just (weight + bias): one [E,H]
        # pre-activation per expert.  The math lives in the module-level
        # feature_mask() so external callers (the coalesced trainer's vjp
        # prologue) compute bit-identical values; with mask_folded=True the
        # caller already folded it into the layer-0 weights and the mask
        # subgraph is skipped entirely (its params then receive zero grads
        # from this apply — the prologue vjp supplies them).
        k_in = 1.0  # fan_in of the constant input
        mask_params = {
            "mask_w1": self.param("mask_w1", uniform_pm(1.0 / k_in ** 0.5), (e, h)),
            "mask_b1": self.param("mask_b1", uniform_pm(1.0 / k_in ** 0.5), (e, h)),
        }
        k_h = 1.0 / h ** 0.5
        mask_params["mask_w2"] = self.param("mask_w2", uniform_pm(k_h), (e, h, f))
        mask_params["mask_b2"] = self.param("mask_b2", uniform_pm(k_h), (e, f))

        mask = None if mask_folded else feature_mask(mask_params)     # [E, F]

        # (b) (stacked) bidirectional GRU over the window (reference:
        # qrnn.py:24,39-43; layer l>0 consumes layer l-1's output, matching
        # torch's stacked-GRU semantics with zero inter-layer dropout).
        k_g = 1.0 / h ** 0.5

        def gru_params(name, in_dim):
            return GRUParams(
                w_ih=self.param(f"{name}_w_ih", uniform_pm(k_g), (e, in_dim, 3 * h)),
                w_hh=self.param(f"{name}_w_hh", uniform_pm(k_g), (e, h, 3 * h)),
                b_ih=self.param(f"{name}_b_ih", uniform_pm(k_g), (e, 3 * h)),
                b_hh=self.param(f"{name}_b_hh", uniform_pm(k_g), (e, 3 * h)),
            )

        # Fold the mask into the input weights: (x ⊙ m) @ W == x @ (m ⊙ W).
        # Identity when the caller pre-folded (fold_feature_mask).
        def masked(p: GRUParams) -> GRUParams:
            if mask is None:
                return p
            return p._replace(w_ih=mask[:, :, None] * p.w_ih)

        def cast(p: GRUParams) -> GRUParams:
            return jax.tree.map(lambda a: a.astype(compute_dtype), p)

        out = x.astype(compute_dtype)                                  # [B,T,F]
        for layer in range(cfg.num_layers):
            sfx = "" if layer == 0 else f"_l{layer}"
            in_dim = f if layer == 0 else cfg.rnn_out_dim
            fwd = gru_params(f"gru_fwd{sfx}", in_dim)
            if layer == 0:
                fwd = masked(fwd)
            if cfg.bidirectional:
                bwd = gru_params(f"gru_bwd{sfx}", in_dim)
                if layer == 0:
                    bwd = masked(bwd)
                out = bidirectional_gru(cast(fwd), cast(bwd), out,
                                        backend=cfg.rnn_backend)
            else:
                out = gru(cast(fwd), out, backend=cfg.rnn_backend)
            # layer 0 broadcasts [B,T,F] across experts; the output (and all
            # deeper layers) carry the expert axis: [E,B,T,D].
        # The post-RNN path stays in the model's compute dtype (bf16 for
        # the flagship): rnn_out/mix are the largest activations outside
        # the recurrence (~78 MB each at flagship scale in f32), and
        # dropout + mixing + both head einsums each stream them through
        # HBM.  All reductions still ACCUMULATE in f32 (the cross-expert
        # sum explicitly, the head dots via preferred_element_type);
        # only storage between ops is narrow.  f32 models are unchanged.
        rnn_out = nn.Dropout(rate=cfg.dropout_rate)(
            out, deterministic=deterministic
        )

        # (c) cross-expert mixing + per-metric quantile heads
        # (reference: qrnn.py:46-55), via the O(E) sum-minus-own identity.
        if e > 1:
            total = jnp.sum(rnn_out.astype(jnp.float32), axis=0,
                            keepdims=True)                            # [1,B,T,D]
            mix = ((total - rnn_out.astype(jnp.float32)) / (e - 1)
                   ).astype(compute_dtype)                            # [E,B,T,D]
        else:
            mix = rnn_out

        # The head consumes concat(mix, own) along the feature axis
        # (reference: qrnn.py:50-53).  The weight KEEPS that [E, 2D, Q]
        # layout (checkpoint compatibility), but the einsum is split over
        # the two halves instead of materializing the [E,B,T,2D]
        # concatenation — at flagship scale that intermediate is ~157 MB
        # of pure HBM traffic for an op XLA cannot always fuse away.
        d = rnn_out.shape[-1]
        d_in = 2 * d
        k_d = 1.0 / d_in ** 0.5
        head_w = self.param("head_w", uniform_pm(k_d), (e, d_in, q))
        head_b = self.param("head_b", uniform_pm(k_d), (e, q))
        hw = head_w.astype(compute_dtype)
        preds = (jnp.einsum("ebtd,edq->ebtq", mix, hw[:, :d],
                            preferred_element_type=jnp.float32)
                 + jnp.einsum("ebtd,edq->ebtq", rnn_out, hw[:, d:],
                              preferred_element_type=jnp.float32))
        preds = preds + head_b[:, None, None, :]
        preds = jnp.transpose(preds, (1, 2, 0, 3))                    # [B,T,E,Q]
        if group_shape is not None:
            preds = preds.reshape(*group_shape, *preds.shape[1:])     # [G,B,T,E,Q]
        return preds

    # ------------------------------------------------------------------
    @property
    def quantiles(self) -> tuple[float, ...]:
        return self.config.quantiles

    def median_index(self) -> int:
        """Index of the .50 quantile in the output's last axis (the point
        estimate the reference plots/evaluates, estimate.py:103)."""
        diffs = [abs(qv - 0.5) for qv in self.config.quantiles]
        return diffs.index(min(diffs))
