"""Estimation models: the multi-task quantile GRU and the two baselines."""

from deeprest_tpu.models.qrnn import QuantileGRU
from deeprest_tpu.models.baselines import ResourceAwareBaseline, ComponentAwareBaseline

__all__ = ["QuantileGRU", "ResourceAwareBaseline", "ComponentAwareBaseline"]
