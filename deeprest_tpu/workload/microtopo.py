"""Synthetic microservice topologies at TrainTicket scale (40+ services).

The social-network app (:mod:`topology`) mirrors the reference's fixed
12-service DeathStarBench deployment.  BASELINE.json configs[2] names a
second application class — "TrainTicket (40+ services) 7-day trace" — whose
defining property is *topology scale*: an order of magnitude more services,
deeper call chains, and many more distinct call paths, with no hand-written
per-service logic to copy.  This module generates such applications
synthetically:

- A seeded, layered service DAG: gateways → service layers → stores.  The
  graph is deterministic in ``TopologyParams`` (same seed → identical
  topology → identical call-path feature space across runs/processes).
- Per-endpoint span-tree generation with per-trace randomness (optional
  downstream calls, cache hit/miss branches) so the trace synthesizer has
  real per-endpoint distributions to learn, exactly like the hand-written
  app.
- Store components carry the ``-mongodb``/``-redis``/``-memcached``
  suffixes the telemetry plane keys on (telemetry.is_stateful), so write
  IOps/throughput/usage series appear for the stateful tier.

The emitted traces flow through the same contract as every other corpus:
``simulate_corpus(..., app=SyntheticMicroserviceApp(params),
endpoints=app.endpoints)`` → featurize → train.  Nothing downstream knows
which application generated the data — that is the point: the estimator is
app-agnostic, as in the reference (its featurizer never hardcodes the app,
reference: resource-estimation/featurize.py:11-24).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.data.schema import Span


@dataclasses.dataclass(frozen=True)
class TopologyParams:
    """Shape of the generated service graph."""

    num_services: int = 40
    num_endpoints: int = 12
    num_gateways: int = 2
    depth: int = 4                  # service layers between gateway and stores
    max_fanout: int = 3             # downstream service calls per service
    store_fraction: float = 0.45    # services owning a backing store
    cache_fraction: float = 0.35    # stateful services fronted by a cache
    write_fraction: float = 0.35    # endpoints that mutate state
    p_optional_call: float = 0.35   # per-trace probability of optional edges
    p_cache_miss: float = 0.30
    seed: int = 0

    def __post_init__(self):
        if self.num_services < self.depth:
            raise ValueError("need at least one service per layer")
        if self.num_gateways < 1 or self.num_endpoints < 1:
            raise ValueError("need >= 1 gateway and endpoint")


@dataclasses.dataclass(frozen=True)
class _ServiceSpec:
    name: str
    layer: int
    children: tuple[int, ...]       # indices of downstream services
    optional: tuple[bool, ...]      # per-child: optional (per-trace coin)?
    store: str | None               # backing store component, if stateful
    cache: str | None               # look-aside cache component, if cached


class SyntheticMicroserviceApp:
    """Generates one span tree per API call over a seeded layered DAG.

    Drop-in peer of :class:`topology.SocialNetworkApp`: ``generate(endpoint,
    rng)`` returns the span trees of one API invocation; ``endpoints`` lists
    the API surface in a stable order.
    """

    def __init__(self, params: TopologyParams | None = None):
        self.params = p = params or TopologyParams()
        rng = np.random.default_rng(p.seed)

        # Layer assignment: round-robin keeps layers balanced regardless of
        # num_services; layer 0 is called by gateways, deeper layers by
        # shallower ones.
        layers: list[list[int]] = [[] for _ in range(p.depth)]
        for i in range(p.num_services):
            layers[i % p.depth].append(i)

        specs: list[_ServiceSpec] = []
        for i in range(p.num_services):
            layer = i % p.depth
            name = f"svc-{i:03d}"
            if layer + 1 < p.depth and layers[layer + 1]:
                pool = layers[layer + 1]
                k = int(rng.integers(1, p.max_fanout + 1))
                kids = tuple(
                    int(c) for c in rng.choice(pool, size=min(k, len(pool)),
                                               replace=False))
            else:
                kids = ()
            optional = tuple(bool(rng.random() < 0.5) for _ in kids)
            store = cache = None
            if rng.random() < p.store_fraction:
                store = f"{name}-{'mongodb' if rng.random() < 0.7 else 'redis'}"
                if rng.random() < p.cache_fraction:
                    cache = f"{name}-memcached"
            specs.append(_ServiceSpec(name=name, layer=layer, children=kids,
                                      optional=optional, store=store,
                                      cache=cache))
        self._services = specs

        # Endpoints: each rooted at a gateway, entering 1..max_fanout
        # layer-0 services; a write_fraction of endpoints mutate state.
        eps: list[tuple[str, str, tuple[int, ...], bool]] = []
        for j in range(p.num_endpoints):
            gateway = f"gateway-{j % p.num_gateways}"
            k = int(rng.integers(1, p.max_fanout + 1))
            entry = tuple(int(c) for c in rng.choice(
                layers[0], size=min(k, len(layers[0])), replace=False))
            is_write = rng.random() < p.write_fraction
            eps.append((f"/api/ep{j:02d}", gateway, entry, is_write))
        self._endpoints = eps

    # -- public surface -------------------------------------------------

    @property
    def endpoints(self) -> tuple[str, ...]:
        return tuple(name for name, *_ in self._endpoints)

    @property
    def components(self) -> tuple[str, ...]:
        """Every component the topology can emit (stable order)."""
        out: list[str] = sorted({gw for _, gw, _, _ in self._endpoints})
        for s in self._services:
            out.append(s.name)
            if s.cache:
                out.append(s.cache)
            if s.store:
                out.append(s.store)
        return tuple(out)

    def generate(self, endpoint: str, rng: np.random.Generator) -> list[Span]:
        for name, gateway, entry, is_write in self._endpoints:
            if name == endpoint:
                children = [self._expand(self._services[i], is_write, rng)
                            for i in entry]
                return [Span(component=gateway, operation=endpoint,
                             children=children)]
        raise KeyError(f"unknown endpoint {endpoint!r}")

    # -- internals ------------------------------------------------------

    def _expand(self, spec: _ServiceSpec, is_write: bool,
                rng: np.random.Generator) -> Span:
        p = self.params
        children: list[Span] = []
        if spec.store is not None:
            if is_write:
                children.append(Span(spec.store, "/insert"))
            elif spec.cache is not None:
                children.append(Span(spec.cache, "/mget"))
                if rng.random() < p.p_cache_miss:
                    children.append(Span(spec.store, "/find"))
                    children.append(Span(spec.cache, "/set"))
            else:
                children.append(Span(spec.store, "/find"))
        for idx, optional in zip(spec.children, spec.optional):
            if optional and rng.random() >= p.p_optional_call:
                continue
            children.append(self._expand(self._services[idx], is_write, rng))
        op = "/write" if is_write else "/read"
        return Span(component=spec.name, operation=op, children=children)
