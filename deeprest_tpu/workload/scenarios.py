"""Load scenarios: user curves + API compositions per time bucket.

Reproduces the five locust scenario envelopes (reference:
locust/locustfile-{normal,shape,scale,composition,crypto}.py — SURVEY.md
§2.3): a double-Gaussian two-peaks-per-"day" user curve with fresh random
peaks each cycle and ±20% noise (normal), a flat curve at peak level
(unseen *shape*), 3× peak heights (unseen *scale*), unseen API mixes up to
65% compose (unseen *composition*), and a randomly flat-or-wavy curve paired
with an injected CPU burner (crypto).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

from deeprest_tpu.workload.topology import API_ENDPOINTS

# (composePost, readHomeTimeline, readUserTimeline) weights; the remaining
# mass spreads over register/follow/login (reference: locustfile-normal.py
# keeps 13 seen compositions; composition scenario uses unseen mixes).
SEEN_COMPOSITIONS: tuple[tuple[float, float, float], ...] = (
    (0.10, 0.60, 0.25), (0.15, 0.55, 0.25), (0.20, 0.50, 0.25),
    (0.10, 0.50, 0.35), (0.25, 0.45, 0.25), (0.15, 0.45, 0.35),
    (0.30, 0.40, 0.25), (0.10, 0.40, 0.45), (0.20, 0.40, 0.35),
    (0.35, 0.35, 0.25), (0.25, 0.35, 0.35), (0.15, 0.35, 0.45),
    (0.30, 0.30, 0.35),
)
UNSEEN_COMPOSITIONS: tuple[tuple[float, float, float], ...] = (
    (0.45, 0.30, 0.20), (0.50, 0.25, 0.20), (0.55, 0.25, 0.15),
    (0.60, 0.20, 0.15), (0.65, 0.15, 0.15), (0.05, 0.75, 0.15),
    (0.05, 0.15, 0.75), (0.40, 0.10, 0.45), (0.65, 0.30, 0.05),
)


@dataclasses.dataclass
class LoadScenario:
    """A reproducible traffic program: bucket index → (#calls per endpoint)."""

    name: str
    base_users: float = 100.0
    peak_range: tuple[float, float] = (140.0, 200.0)
    cycle_len: int = 60                 # buckets per "day" (1h day, 1-min buckets)
    noise: float = 0.20
    flat: bool = False                  # shape scenario: hold the peak level
    random_mode: bool = False           # crypto scenario: flat-or-wavy per cycle
    compositions: Sequence[tuple[float, float, float]] = SEEN_COMPOSITIONS
    calls_per_user: float = 2.0         # API calls per user per bucket
    seed: int = 0
    # None → the 6 social-network endpoints with the reference's seen/unseen
    # composition tables.  Set to N for a generic N-endpoint app (synthetic
    # topologies): per-cycle compositions are then Dirichlet draws, which
    # preserves the "API mix shifts every cycle" property without a
    # hand-written table per app.
    generic_endpoints: int | None = None

    def users_curve(self, num_buckets: int) -> np.ndarray:
        """Double-Gaussian two-peaks-per-cycle curve, fresh peaks each cycle
        (reference: locustfile-normal.py:53-74)."""
        rng = np.random.default_rng(self.seed)
        users = np.empty(num_buckets)
        d = self.cycle_len
        for c0 in range(0, num_buckets, d):
            p1, p2 = rng.uniform(*self.peak_range, size=2)
            m1, m2 = sorted(rng.uniform(0.1 * d, 0.9 * d, size=2))
            sigma = d / 8.0
            flat_cycle = self.flat or (self.random_mode and rng.random() < 0.5)
            for i in range(c0, min(c0 + d, num_buckets)):
                t = i - c0
                if flat_cycle:
                    level = max(p1, p2)
                else:
                    level = self.base_users + (
                        (p1 - self.base_users) * np.exp(-((t - m1) ** 2) / (2 * sigma ** 2))
                        + (p2 - self.base_users) * np.exp(-((t - m2) ** 2) / (2 * sigma ** 2))
                    )
                users[i] = max(0.0, level * (1 + rng.uniform(-self.noise, self.noise)))
        return users

    def composition_curve(self, num_buckets: int) -> np.ndarray:
        """Per-cycle composition over the endpoints → [T, n_endpoints]."""
        rng = np.random.default_rng(self.seed + 1)
        d = self.cycle_len
        if self.generic_endpoints is not None:
            n = self.generic_endpoints
            weights = np.empty((num_buckets, n))
            for c0 in range(0, num_buckets, d):
                weights[c0:c0 + d] = rng.dirichlet(np.ones(n))
            return weights[:num_buckets]
        weights = np.empty((num_buckets, len(API_ENDPOINTS)))
        for c0 in range(0, num_buckets, d):
            compose, read_home, read_user = self.compositions[
                int(rng.integers(0, len(self.compositions)))
            ]
            rest = max(0.0, 1.0 - compose - read_home - read_user)
            w = np.asarray([compose, read_home, read_user,
                            rest * 0.2, rest * 0.3, rest * 0.5])
            weights[c0:c0 + d] = w / w.sum()
        return weights[:num_buckets]

    def traffic(self, num_buckets: int) -> np.ndarray:
        """[T, 6] integer call counts per endpoint per bucket."""
        rng = np.random.default_rng(self.seed + 2)
        users = self.users_curve(num_buckets)
        comp = self.composition_curve(num_buckets)
        rates = users[:, None] * self.calls_per_user * comp
        return rng.poisson(rates).astype(np.int64)


def normal_scenario(seed: int = 0) -> LoadScenario:
    return LoadScenario(name="normal", seed=seed)


def shape_scenario(seed: int = 0) -> LoadScenario:
    return LoadScenario(name="shape", flat=True, seed=seed)


def scale_scenario(seed: int = 0) -> LoadScenario:
    return LoadScenario(name="scale", peak_range=(420.0, 600.0), seed=seed)


def composition_scenario(seed: int = 0) -> LoadScenario:
    return LoadScenario(name="composition", compositions=UNSEEN_COMPOSITIONS,
                        seed=seed)


def crypto_scenario(seed: int = 0) -> LoadScenario:
    return LoadScenario(name="crypto", random_mode=True, seed=seed)


SCENARIOS: dict[str, Callable[[int], LoadScenario]] = {
    "normal": normal_scenario,
    "shape": shape_scenario,
    "scale": scale_scenario,
    "composition": composition_scenario,
    "crypto": crypto_scenario,
}
