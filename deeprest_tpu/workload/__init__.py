"""The capability harness: a scenario-driven workload + telemetry simulator.

The reference obtains training corpora by deploying a 12-service social
network on Kubernetes, driving it with locust, and scraping Jaeger +
Prometheus (reference: social-network/, locust/, minikube-openebs/ —
SURVEY.md L0-L3).  None of that infrastructure can exist inside a TPU
training job, but the *capability* it provides — realistic span trees and
traffic-correlated per-component resource series, under controllable load
scenarios including anomalies — is reproduced here as a deterministic,
seedable simulator emitting the exact raw-data contract the data plane
consumes.  Month-scale corpora stream bucket-by-bucket to JSONL
(:func:`simulator.simulate_corpus_iter`, constant memory) and are
featurized by the native C++ ETL (deeprest_tpu.data.native, ~25x the
Python span walk) — see benchmarks/month_scale.py for the full 30-day
pipeline.
"""

from deeprest_tpu.workload.topology import SocialNetworkApp, API_ENDPOINTS
from deeprest_tpu.workload.scenarios import (
    LoadScenario,
    normal_scenario,
    shape_scenario,
    scale_scenario,
    composition_scenario,
    crypto_scenario,
    SCENARIOS,
)
from deeprest_tpu.workload.telemetry import ResourceModel, Anomaly
from deeprest_tpu.workload.simulator import simulate_corpus
from deeprest_tpu.workload.microtopo import (
    SyntheticMicroserviceApp,
    TopologyParams,
)

__all__ = [
    "SocialNetworkApp",
    "API_ENDPOINTS",
    "SyntheticMicroserviceApp",
    "TopologyParams",
    "LoadScenario",
    "normal_scenario",
    "shape_scenario",
    "scale_scenario",
    "composition_scenario",
    "crypto_scenario",
    "SCENARIOS",
    "ResourceModel",
    "Anomaly",
    "simulate_corpus",
]
