"""The simulated application: DeathStarBench-style social network topology.

Span-tree generators reproduce the wire-level call structure of the
reference application (component/operation names and fan-out shape follow
the reference's hot paths: compose at
social-network-source/src/ComposePostService/ComposePostHandler.h:463-583
and the gateway script nginx-web-server/lua-scripts-k8s/wrk2-api/post/
compose.lua:86-143; reads at HomeTimelineHandler.h:73-102 and
UserTimelineHandler.h; media at media-frontend/lua-scripts-k8s/
upload-media.lua — see SURVEY.md §3.1-3.2).  Probabilistic branches model
what makes real traces vary: optional media/urls/mentions, cache misses
falling through to MongoDB, and mention fan-out.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.data.schema import Span


def _s(component: str, operation: str, *children: Span) -> Span:
    return Span(component=component, operation=operation, children=list(children))


@dataclasses.dataclass(frozen=True)
class AppParams:
    """Branch probabilities of the simulated app (locustfile-normal.py:14-23
    semantics: 20% media, 0-5 mentions; cache-miss rates are deployment
    realism knobs)."""

    p_media: float = 0.20
    p_urls: float = 0.30
    max_mentions: int = 5
    p_cache_miss: float = 0.25
    p_graph_cache_miss: float = 0.15
    mean_read_posts: float = 10.0


class SocialNetworkApp:
    """Generates one span tree per API call."""

    def __init__(self, params: AppParams | None = None):
        self.params = params or AppParams()

    # -- write path ----------------------------------------------------

    def compose_post(self, rng: np.random.Generator) -> list[Span]:
        p = self.params
        traces: list[Span] = []
        if rng.random() < p.p_media:
            traces.append(
                _s("media-frontend", "/upload-media",
                   _s("media-mongodb", "/insert"))
            )

        text_children = []
        if rng.random() < p.p_urls:
            text_children.append(
                _s("url-shorten-service", "/UploadUrls",
                   _s("url-shorten-mongodb", "/insert"),
                   _s("compose-post-service", "/UploadUrls",
                      _s("compose-post-redis", "/hset")))
            )
        n_mentions = int(rng.integers(0, p.max_mentions + 1))
        if n_mentions > 0:
            mention_children = [_s("user-memcached", "/mget")]
            if rng.random() < p.p_cache_miss:
                mention_children.append(_s("user-mongodb", "/find"))
            mention_children.append(
                _s("compose-post-service", "/UploadUserMentions",
                   _s("compose-post-redis", "/hset")))
            text_children.append(
                _s("user-mention-service", "/UploadUserMentions", *mention_children)
            )
        text_children.append(
            _s("compose-post-service", "/UploadText",
               _s("compose-post-redis", "/hset")))

        home_children = [
            _s("social-graph-service", "/GetFollowers",
               _s("social-graph-redis", "/zrange"),
               *([_s("social-graph-mongodb", "/find")]
                 if rng.random() < p.p_graph_cache_miss else [])),
            _s("home-timeline-redis", "/zadd"),
        ]

        traces.append(
            _s("nginx-thrift", "/wrk2-api/post/compose",
               _s("user-service", "/UploadCreatorWithUserId",
                  _s("compose-post-service", "/UploadCreator",
                     _s("compose-post-redis", "/hset"))),
               _s("media-service", "/UploadMedia",
                  _s("compose-post-service", "/UploadMedia",
                     _s("compose-post-redis", "/hset"))),
               _s("text-service", "/UploadText", *text_children),
               _s("unique-id-service", "/UploadUniqueId",
                  _s("compose-post-service", "/UploadUniqueId",
                     _s("compose-post-redis", "/hset"),
                     _s("post-storage-service", "/StorePost",
                        _s("post-storage-mongodb", "/insert")),
                     _s("user-timeline-service", "/WriteUserTimeline",
                        _s("user-timeline-mongodb", "/update"),
                        _s("user-timeline-redis", "/zadd")),
                     _s("write-home-timeline-service", "/Consume",
                        *home_children))))
        )
        return traces

    # -- read paths ----------------------------------------------------

    def _read_posts(self, rng: np.random.Generator) -> list[Span]:
        children = [_s("post-storage-memcached", "/mget")]
        if rng.random() < self.params.p_cache_miss:
            children.append(_s("post-storage-mongodb", "/find"))
        return [_s("post-storage-service", "/ReadPosts", *children)]

    def read_home_timeline(self, rng: np.random.Generator) -> list[Span]:
        return [
            _s("nginx-thrift", "/wrk2-api/home-timeline/read",
               _s("home-timeline-service", "/ReadHomeTimeline",
                  _s("home-timeline-redis", "/zrevrange"),
                  *self._read_posts(rng)))
        ]

    def read_user_timeline(self, rng: np.random.Generator) -> list[Span]:
        children = [_s("user-timeline-redis", "/zrevrange")]
        if rng.random() < self.params.p_cache_miss:
            children.append(_s("user-timeline-mongodb", "/find"))
        return [
            _s("nginx-thrift", "/wrk2-api/user-timeline/read",
               _s("user-timeline-service", "/ReadUserTimeline",
                  *children, *self._read_posts(rng)))
        ]

    # -- account paths -------------------------------------------------

    def register(self, rng: np.random.Generator) -> list[Span]:
        return [
            _s("nginx-thrift", "/wrk2-api/user/register",
               _s("user-service", "/RegisterUser",
                  _s("user-mongodb", "/insert"),
                  _s("social-graph-service", "/InsertUser",
                     _s("social-graph-mongodb", "/insert"))))
        ]

    def follow(self, rng: np.random.Generator) -> list[Span]:
        return [
            _s("nginx-thrift", "/wrk2-api/user/follow",
               _s("social-graph-service", "/Follow",
                  _s("social-graph-mongodb", "/update"),
                  _s("social-graph-redis", "/zadd")))
        ]

    def login(self, rng: np.random.Generator) -> list[Span]:
        children = [_s("user-memcached", "/get")]
        if rng.random() < self.params.p_cache_miss:
            children.append(_s("user-mongodb", "/find"))
        return [
            _s("nginx-thrift", "/wrk2-api/user/login",
               _s("user-service", "/Login", *children))
        ]

    def generate(self, api: str, rng: np.random.Generator) -> list[Span]:
        return getattr(self, api)(rng)


API_ENDPOINTS = (
    "compose_post",
    "read_home_timeline",
    "read_user_timeline",
    "register",
    "follow",
    "login",
)
