"""Telemetry plane: per-component resource series from simulated traffic.

Models the five resources the reference predicts (cpu millicores, memory
WSS MB, write-IOps, write throughput KB, disk usage MB — reference:
resource-estimation/utils.py:8-26) as functions of per-bucket invocation
activity: CPU tracks ops with saturation and noise, memory is a
working-set EMA over recent activity, write metrics track mutation ops on
stateful components, and disk usage accumulates.  Anomaly injectors
reproduce the sanity-check experiments: cryptojacking burns CPU decoupled
from traffic (reference: locust/pow.py), ransomware-style encryption shows
up as traffic-independent read+rewrite IO (claimed in reference
README.md:5; no injector ships there — SURVEY.md §5.3)."""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.data.schema import MetricSample, Span

WRITE_OPS = ("/insert", "/update", "/zadd", "/hset", "/save")
STATEFUL_SUFFIXES = ("-mongodb", "-redis", "-memcached")


def is_stateful(component: str) -> bool:
    return component.endswith(STATEFUL_SUFFIXES)


def count_ops(traces: list[Span]) -> tuple[dict[str, int], dict[str, int]]:
    """Per-component (all ops, write ops) counts in one bucket."""
    ops: dict[str, int] = {}
    writes: dict[str, int] = {}
    for trace in traces:
        for _, node in trace.walk():
            ops[node.component] = ops.get(node.component, 0) + 1
            if node.operation in WRITE_OPS:
                writes[node.component] = writes.get(node.component, 0) + 1
    return ops, writes


ANOMALY_KINDS = ("cryptojacking", "ransomware")


@dataclasses.dataclass
class Anomaly:
    """A traffic-decoupled resource consumer injected into one component."""

    kind: str                  # "cryptojacking" | "ransomware"
    component: str
    start: int                 # bucket index, inclusive
    end: int                   # exclusive
    magnitude: float = 1.0

    def __post_init__(self):
        if self.kind not in ANOMALY_KINDS:
            raise ValueError(
                f"unknown anomaly kind {self.kind!r}; choose from {ANOMALY_KINDS}"
            )

    def active(self, t: int) -> bool:
        return self.start <= t < self.end


@dataclasses.dataclass
class ComponentProfile:
    cpu_per_op: float
    base_cpu: float
    base_mem: float
    mem_per_activity: float
    kb_per_write: float
    # Nonlinear service physics.  A linear resource model makes the
    # component-aware linear baseline the generative process — optimal by
    # construction, so the dossier could only ever show the deep model
    # tying it.  Real clusters are not linear (the reference's >90%-incl.-
    # unseen-traffic claim is measured against a real one): service cost
    # per op grows convexly near capacity (queueing, context switches),
    # cache-backed stores burn extra CPU on the cold fraction of a traffic
    # ramp (memcached-lookaside misses fall through to the DB — the
    # reference's own PostStorage/UserTimeline read path), and group
    # commit makes write-IOps sublinear in logical writes.
    capacity_ops: float = 400.0     # ops/bucket where queueing bites
    queue_gain: float = 0.5         # convexity strength at saturation
    miss_cost: float = 0.0          # extra cpu per cold op (stateful only)
    write_batch: float = 400.0      # group-commit softening scale


class ResourceModel:
    """Stateful telemetry generator; one ``step`` per time bucket."""

    def __init__(self, seed: int = 0, anomalies: list[Anomaly] | None = None):
        self.rng = np.random.default_rng(seed)
        self.anomalies = anomalies or []
        self._profiles: dict[str, ComponentProfile] = {}
        self._ema: dict[str, float] = {}
        self._usage: dict[str, float] = {}
        self._t = 0

    def _profile(self, component: str) -> ComponentProfile:
        if component not in self._profiles:
            # Reproducible per-component character, from a stable hash so
            # profiles depend on neither discovery order nor PYTHONHASHSEED
            # (process-randomized hash() would break corpus reproducibility
            # across CLI invocations).
            import hashlib

            digest = hashlib.blake2b(component.encode(), digest_size=4).digest()
            r = np.random.default_rng(int.from_bytes(digest, "little"))
            heavy = 2.0 if component in ("nginx-thrift", "compose-post-service") else 1.0
            self._profiles[component] = ComponentProfile(
                cpu_per_op=heavy * r.uniform(0.15, 0.6),
                base_cpu=r.uniform(2.0, 12.0),
                base_mem=r.uniform(60.0, 400.0),
                mem_per_activity=r.uniform(0.02, 0.10),
                kb_per_write=r.uniform(1.0, 16.0),
                capacity_ops=heavy * r.uniform(150.0, 600.0),
                queue_gain=r.uniform(0.3, 0.9),
                miss_cost=(r.uniform(0.3, 1.0)
                           if is_stateful(component) else 0.0),
                write_batch=r.uniform(200.0, 600.0),
            )
        return self._profiles[component]

    def step(self, traces: list[Span],
             components: list[str] | None = None) -> list[MetricSample]:
        """One bucket of telemetry from raw traces (convenience wrapper)."""
        ops, writes = count_ops(traces)
        return self.step_counts(ops, writes, components)

    def step_counts(self, ops: dict[str, int], writes: dict[str, int],
                    components: list[str] | None = None) -> list[MetricSample]:
        """One bucket of telemetry from precomputed per-component counts.

        Pass ``components`` (the corpus-wide component set) so every bucket
        reports the same metric keys — components idle this bucket report
        baseline utilization, exactly like a real scrape would.
        """
        ops = dict(ops)
        for c in components or ():
            ops.setdefault(c, 0)
        # Anomalous components must report even in zero-traffic buckets.
        for a in self.anomalies:
            ops.setdefault(a.component, 0)
        samples: list[MetricSample] = []
        for component in sorted(ops):
            prof = self._profile(component)
            n_ops = ops[component]
            n_writes = writes.get(component, 0)

            prev_ema = self._ema.get(component, 0.0)
            ema = 0.9 * prev_ema + 0.1 * n_ops
            self._ema[component] = ema

            # Queueing convexity: cost per op rises toward capacity
            # (M/M/1-flavored rho^2/(1-rho), rho capped below 1).
            rho = min(n_ops / prof.capacity_ops, 0.9)
            cpu = prof.base_cpu + prof.cpu_per_op * n_ops * (
                1.0 + prof.queue_gain * rho * rho / (1.0 - rho))
            # Cache-warmth transient: ops EXCEEDING the warm set (the
            # activity EMA) miss and fall through — same op count costs
            # more on a ramp than in steady state, a history effect a
            # per-bucket linear scaler cannot represent.
            if prof.miss_cost and n_ops:
                cold = max(0.0, n_ops - prev_ema)
                cpu += prof.miss_cost * cold
            # Group commit: physical write-IOps sublinear in logical writes.
            wiops = n_writes / (1.0 + n_writes / prof.write_batch)
            wtp = n_writes * prof.kb_per_write

            for a in self.anomalies:
                if a.component == component and a.active(self._t):
                    if a.kind == "cryptojacking":
                        # pow.py-style CPU burner: large, traffic-independent
                        cpu += 400.0 * a.magnitude
                    elif a.kind == "ransomware":
                        cpu += 80.0 * a.magnitude
                        wiops += 200.0 * a.magnitude
                        wtp += 200.0 * a.magnitude * prof.kb_per_write

            cpu *= 1.0 + self.rng.normal(0.0, 0.03)
            mem = prof.base_mem + prof.mem_per_activity * ema * 10.0
            mem *= 1.0 + self.rng.normal(0.0, 0.01)

            samples.append(MetricSample(component, "cpu", round(max(cpu, 0.0), 4)))
            samples.append(MetricSample(component, "memory", round(max(mem, 0.0), 4)))
            if is_stateful(component):
                # Write metrics carry scrape noise like the CPU/mem series
                # do (a real exporter's delta windows never land exactly on
                # commit boundaries; exact noise-free series also let a
                # linear baseline fit them perfectly, which no real scrape
                # allows).  Drawn only for components that report, so
                # non-stateful components do not consume the noise stream.
                wiops *= 1.0 + self.rng.normal(0.0, 0.05)
                wtp *= 1.0 + self.rng.normal(0.0, 0.05)
                usage = self._usage.get(component, 50.0) + wtp / 1024.0
                self._usage[component] = usage
                samples.append(MetricSample(component, "write-iops", round(wiops, 4)))
                samples.append(MetricSample(component, "write-tp", round(wtp, 4)))
                samples.append(MetricSample(component, "usage", round(usage, 4)))
        self._t += 1
        return samples
