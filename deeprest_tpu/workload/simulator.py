"""Corpus simulation: scenario → raw-data buckets (and a CLI).

Two-phase so every bucket carries the identical metric-key set the
featurizer requires: (1) generate every bucket's span trees from the
scenario's traffic program, discovering the full component set; (2) run the
stateful resource model over the trace timeline.

CLI:
    python -m deeprest_tpu.workload.simulator \
        --scenario normal --buckets 480 --seed 0 --out corpus.jsonl \
        [--anomaly cryptojacking:media-mongodb:300:360]
"""

from __future__ import annotations

import argparse

import numpy as np

from deeprest_tpu.data.schema import Bucket, save_raw_data_jsonl, save_raw_data_pickle
from deeprest_tpu.workload.scenarios import SCENARIOS, LoadScenario
from deeprest_tpu.workload.telemetry import Anomaly, ResourceModel, count_ops
from deeprest_tpu.workload.topology import API_ENDPOINTS, AppParams, SocialNetworkApp


def simulate_corpus(
    scenario: LoadScenario,
    num_buckets: int,
    app_params: AppParams | None = None,
    anomalies: list[Anomaly] | None = None,
    resource_seed: int | None = None,
    app=None,
    endpoints: tuple[str, ...] | None = None,
) -> list[Bucket]:
    """Deterministic: same scenario/seeds → identical corpus.

    ``app``/``endpoints`` default to the social-network topology; pass any
    object with ``generate(endpoint, rng) -> list[Span]`` (e.g.
    :class:`microtopo.SyntheticMicroserviceApp`) plus its endpoint tuple to
    simulate a different application.  The scenario's traffic matrix must be
    as wide as ``endpoints`` (use ``LoadScenario.generic_endpoints``).
    """
    if endpoints is None:
        if app is None:
            endpoints = API_ENDPOINTS
        else:
            # A custom app must declare its surface — defaulting it to the
            # social-network endpoint list could pass the width check by
            # coincidence and fail deep in the bucket loop.
            try:
                endpoints = tuple(app.endpoints)
            except AttributeError:
                raise TypeError(
                    "custom app has no .endpoints attribute; pass "
                    "endpoints= explicitly") from None
    if app is None:
        app = SocialNetworkApp(app_params)
    trace_rng = np.random.default_rng(scenario.seed + 3)
    traffic = scenario.traffic(num_buckets)          # [T, num_endpoints]
    if traffic.shape[1] != len(endpoints):
        raise ValueError(
            f"scenario emits {traffic.shape[1]}-endpoint traffic but the app "
            f"has {len(endpoints)} endpoints — set scenario.generic_endpoints")

    # Phase 1: generate traces, counting ops in the same walk (count_ops is
    # the only tree traversal; trees are not re-walked in phase 2).
    per_bucket_traces: list[list] = []
    per_bucket_counts: list[tuple[dict, dict]] = []
    components: set[str] = set()
    for t in range(num_buckets):
        traces = []
        for api_idx, api in enumerate(endpoints):
            for _ in range(int(traffic[t, api_idx])):
                traces.extend(app.generate(api, trace_rng))
        ops, writes = count_ops(traces)
        per_bucket_traces.append(traces)
        per_bucket_counts.append((ops, writes))
        components.update(ops)

    # Phase 2: stateful telemetry over the full component set.
    model = ResourceModel(
        seed=scenario.seed if resource_seed is None else resource_seed,
        anomalies=anomalies,
    )
    ordered = sorted(components)
    return [
        Bucket(metrics=model.step_counts(ops, writes, components=ordered),
               traces=traces)
        for traces, (ops, writes) in zip(per_bucket_traces, per_bucket_counts)
    ]


def parse_anomaly(spec: str) -> Anomaly:
    """``kind:component:start:end[:magnitude]``"""
    parts = spec.split(":")
    if len(parts) not in (4, 5):
        raise argparse.ArgumentTypeError(
            f"anomaly spec {spec!r} != kind:component:start:end[:magnitude]"
        )
    return Anomaly(
        kind=parts[0], component=parts[1], start=int(parts[2]), end=int(parts[3]),
        magnitude=float(parts[4]) if len(parts) == 5 else 1.0,
    )


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", choices=sorted(SCENARIOS), default="normal")
    ap.add_argument("--buckets", type=int, default=480,
                    help="number of time buckets (a 'day' is 60)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", required=True,
                    help="output path (.jsonl or .pkl by extension)")
    ap.add_argument("--anomaly", type=parse_anomaly, action="append", default=[],
                    help="kind:component:start:end[:magnitude], repeatable")
    ap.add_argument("--calls-per-user", type=float, default=2.0)
    ap.add_argument("--app", choices=("social", "synthetic"), default="social",
                    help="application topology: the 12-service social network "
                         "or a seeded synthetic DAG (TrainTicket scale)")
    ap.add_argument("--services", type=int, default=40,
                    help="synthetic app: number of services")
    ap.add_argument("--endpoints", type=int, default=12,
                    help="synthetic app: number of API endpoints")
    args = ap.parse_args(argv)

    scenario = SCENARIOS[args.scenario](args.seed)
    scenario.calls_per_user = args.calls_per_user
    app = endpoints = None
    if args.app == "synthetic":
        from deeprest_tpu.workload.microtopo import (
            SyntheticMicroserviceApp, TopologyParams,
        )

        app = SyntheticMicroserviceApp(TopologyParams(
            num_services=args.services, num_endpoints=args.endpoints,
            seed=args.seed))
        endpoints = app.endpoints
        scenario.generic_endpoints = len(endpoints)
    buckets = simulate_corpus(scenario, args.buckets, anomalies=args.anomaly,
                              app=app, endpoints=endpoints)
    if args.out.endswith(".pkl"):
        save_raw_data_pickle(buckets, args.out)
    else:
        save_raw_data_jsonl(buckets, args.out)
    total_traces = sum(len(b.traces) for b in buckets)
    print(f"wrote {len(buckets)} buckets, {total_traces} traces, "
          f"{len(buckets[0].metrics)} metric keys -> {args.out}")


if __name__ == "__main__":
    main()
