"""Corpus simulation: scenario → raw-data buckets (and a CLI).

Two-phase so every bucket carries the identical metric-key set the
featurizer requires: (1) generate every bucket's span trees from the
scenario's traffic program, discovering the full component set; (2) run the
stateful resource model over the trace timeline.

CLI:
    python -m deeprest_tpu.workload.simulator \
        --scenario normal --buckets 480 --seed 0 --out corpus.jsonl \
        [--anomaly cryptojacking:media-mongodb:300:360]
"""

from __future__ import annotations

import argparse

import numpy as np

from deeprest_tpu.data.schema import Bucket, save_raw_data_jsonl, save_raw_data_pickle
from deeprest_tpu.workload.scenarios import SCENARIOS, LoadScenario
from deeprest_tpu.workload.telemetry import Anomaly, ResourceModel, count_ops
from deeprest_tpu.workload.topology import API_ENDPOINTS, AppParams, SocialNetworkApp


def simulate_corpus(
    scenario: LoadScenario,
    num_buckets: int,
    app_params: AppParams | None = None,
    anomalies: list[Anomaly] | None = None,
    resource_seed: int | None = None,
    app=None,
    endpoints: tuple[str, ...] | None = None,
) -> list[Bucket]:
    """Deterministic: same scenario/seeds → identical corpus.

    ``app``/``endpoints`` default to the social-network topology; pass any
    object with ``generate(endpoint, rng) -> list[Span]`` (e.g.
    :class:`microtopo.SyntheticMicroserviceApp`) plus its endpoint tuple to
    simulate a different application.  The scenario's traffic matrix must be
    as wide as ``endpoints`` (use ``LoadScenario.generic_endpoints``).
    """
    if endpoints is None:
        if app is None:
            endpoints = API_ENDPOINTS
        else:
            # A custom app must declare its surface — defaulting it to the
            # social-network endpoint list could pass the width check by
            # coincidence and fail deep in the bucket loop.
            try:
                endpoints = tuple(app.endpoints)
            except AttributeError:
                raise TypeError(
                    "custom app has no .endpoints attribute; pass "
                    "endpoints= explicitly") from None
    if app is None:
        app = SocialNetworkApp(app_params)
    trace_rng = np.random.default_rng(scenario.seed + 3)
    traffic = scenario.traffic(num_buckets)          # [T, num_endpoints]
    if traffic.shape[1] != len(endpoints):
        raise ValueError(
            f"scenario emits {traffic.shape[1]}-endpoint traffic but the app "
            f"has {len(endpoints)} endpoints — set scenario.generic_endpoints")

    # Phase 1: generate traces, counting ops in the same walk (count_ops is
    # the only tree traversal; trees are not re-walked in phase 2).
    per_bucket_traces: list[list] = []
    per_bucket_counts: list[tuple[dict, dict]] = []
    components: set[str] = set()
    for t in range(num_buckets):
        traces = []
        for api_idx, api in enumerate(endpoints):
            for _ in range(int(traffic[t, api_idx])):
                traces.extend(app.generate(api, trace_rng))
        ops, writes = count_ops(traces)
        per_bucket_traces.append(traces)
        per_bucket_counts.append((ops, writes))
        components.update(ops)
    # An app that declares its full graph (synthetic topologies) gets metric
    # keys for every deployed component, invoked or idle — like a real
    # scrape — and keeps the keyset identical to simulate_corpus_iter's.
    components.update(getattr(app, "components", ()))

    # Phase 2: stateful telemetry over the full component set.
    model = ResourceModel(
        seed=scenario.seed if resource_seed is None else resource_seed,
        anomalies=anomalies,
    )
    ordered = sorted(components)
    return [
        Bucket(metrics=model.step_counts(ops, writes, components=ordered),
               traces=traces)
        for traces, (ops, writes) in zip(per_bucket_traces, per_bucket_counts)
    ]


def simulate_corpus_iter(
    scenario: LoadScenario,
    num_buckets: int,
    app_params: AppParams | None = None,
    anomalies: list[Anomaly] | None = None,
    resource_seed: int | None = None,
    app=None,
    endpoints: tuple[str, ...] | None = None,
    components: tuple[str, ...] | None = None,
    discovery_buckets: int = 120,
):
    """Constant-memory variant of :func:`simulate_corpus`: yields buckets
    one at a time, so month-scale corpora stream straight to JSONL without
    ever holding tens of millions of span objects.

    The fixed metric keyset every bucket must carry comes from (in order):
    ``components``, the app's declared ``components`` attribute (synthetic
    topologies know their full graph), or a discovery pre-pass of
    ``discovery_buckets`` buckets re-generated deterministically — the
    series prefix plus a stride/peak-traffic sample across the whole run,
    so a branch that only fires under late peak load is still likely in
    the keyset.  Discovery is sampling, not proof: pass ``components``
    explicitly for apps with very rare branches (the generator fail-fasts
    on any component outside the keyset rather than poisoning the corpus).
    Two sampling caveats, accepted deliberately: tier-2 buckets are
    re-generated with per-bucket rngs whose draws differ from the real
    pass, so (rarely) a discovered component may never occur in the actual
    corpus — its metric key is then present but always idle — and the
    bit-identity with :func:`simulate_corpus` noted below is therefore
    guaranteed only when the component set comes from ``components=`` or
    the app, not from discovery on a series longer than the prefix.

    Identical RNG draw order to :func:`simulate_corpus`, so for an equal
    component set the streamed corpus is bit-identical to the in-memory
    one.
    """
    # Plain function (not a generator): every argument error surfaces HERE,
    # before any caller opens/truncates an output file on the iterator's
    # behalf.
    if endpoints is None:
        if app is None:
            endpoints = API_ENDPOINTS
        else:
            try:
                endpoints = tuple(app.endpoints)
            except AttributeError:
                raise TypeError(
                    "custom app has no .endpoints attribute; pass "
                    "endpoints= explicitly") from None
    if app is None:
        app = SocialNetworkApp(app_params)
    traffic = scenario.traffic(num_buckets)
    if traffic.shape[1] != len(endpoints):
        raise ValueError(
            f"scenario emits {traffic.shape[1]}-endpoint traffic but the app "
            f"has {len(endpoints)} endpoints — set scenario.generic_endpoints")

    if components is None:
        components = getattr(app, "components", None)
    if components is None:
        # Discovery pre-pass, two tiers sharing the budget:
        #   1. the series PREFIX, regenerated with the same sequential rng
        #      the real pass uses (same seed → bit-identical traces), so
        #      everything in those buckets is in the keyset by construction;
        #   2. buckets SAMPLED ACROSS the whole series — an even stride plus
        #      the highest-traffic buckets — each with a per-bucket rng.
        # Tier 2 exists because a rare branch can first fire deep into a
        # month-scale run (e.g. only under peak traffic); a prefix-only
        # pre-pass would then fail-fast in _corpus_gen hours in, after the
        # caller has already streamed a large partial JSONL.  Peak buckets
        # see the most traces, so they are the best places to observe rare
        # branches.
        # The full budget still goes to the prefix (so every run that was
        # safe before stays safe by construction); tier 2 ADDS up to
        # budget//2 sampled buckets on top.
        prefix_n = min(num_buckets, discovery_buckets)
        scratch_rng = np.random.default_rng(scenario.seed + 3)
        seen: set[str] = set()

        def observe(t: int, rng) -> None:
            traces = []
            for api_idx, api in enumerate(endpoints):
                for _ in range(int(traffic[t, api_idx])):
                    traces.extend(app.generate(api, rng))
            ops, _ = count_ops(traces)
            seen.update(ops)

        for t in range(prefix_n):
            observe(t, scratch_rng)
        rest = discovery_buckets // 2 if num_buckets > prefix_n else 0
        if rest > 0:
            stride = np.linspace(prefix_n, num_buckets - 1,
                                 num=rest // 2, dtype=np.int64)
            # Peak candidates come from BEYOND the prefix (an early-peaking
            # series must not consume the peak budget on buckets the prefix
            # already covered).
            tail_traffic = traffic[prefix_n:].sum(axis=1)
            peak = prefix_n + np.argsort(tail_traffic)[::-1][:rest - len(stride)]
            for t in sorted(set(stride.tolist()) | set(peak.tolist())):
                observe(int(t), np.random.default_rng((scenario.seed + 3, int(t))))
        components = tuple(seen)
    return _corpus_gen(scenario, num_buckets, anomalies, resource_seed, app,
                       endpoints, traffic, sorted(components))


def _corpus_gen(scenario, num_buckets, anomalies, resource_seed, app,
                endpoints, traffic, ordered):
    comp_set = set(ordered)
    trace_rng = np.random.default_rng(scenario.seed + 3)
    model = ResourceModel(
        seed=scenario.seed if resource_seed is None else resource_seed,
        anomalies=anomalies,
    )
    for t in range(num_buckets):
        traces = []
        for api_idx, api in enumerate(endpoints):
            for _ in range(int(traffic[t, api_idx])):
                traces.extend(app.generate(api, trace_rng))
        ops, writes = count_ops(traces)
        # Fail FAST on a component outside the fixed keyset (first seen
        # after the discovery window): emitting it would make this bucket's
        # metric keys diverge and poison the whole corpus for featurization.
        unknown = set(ops) - comp_set
        if unknown:
            raise ValueError(
                f"bucket {t}: components {sorted(unknown)} first appear "
                f"after the discovery window — pass components= explicitly "
                "or raise discovery_buckets")
        yield Bucket(metrics=model.step_counts(ops, writes, components=ordered),
                     traces=traces)


def build_synthetic_app(scenario: LoadScenario, num_services: int,
                        num_endpoints: int, seed: int):
    """Construct the synthetic topology for a CLI run and point the
    scenario's composition at its endpoint surface.  Shared by the two
    simulate CLIs (this module's main and deeprest_tpu.cli simulate)."""
    from deeprest_tpu.workload.microtopo import (
        SyntheticMicroserviceApp, TopologyParams,
    )

    app = SyntheticMicroserviceApp(TopologyParams(
        num_services=num_services, num_endpoints=num_endpoints, seed=seed))
    scenario.generic_endpoints = len(app.endpoints)
    return app, app.endpoints


def build_shifted_app(scenario: LoadScenario, num_services: int,
                      num_services_after: int, num_endpoints: int,
                      seed: int):
    """The mid-corpus topology-change pair (ROADMAP item 6's scenario
    library): the BEFORE and AFTER synthetic topologies of a rolling
    deployment that adds/removes services.

    Both apps share the seed and the endpoint surface (names are
    ``/api/epNN``, so the scenario's traffic matrix stays valid across
    the shift), but a different ``num_services`` re-draws the layered
    DAG — services appear, vanish, and rewire, which is exactly the
    call-path composition shift the drift monitors must flag (new hash
    columns gain mass, old ones go dark)."""
    from deeprest_tpu.workload.microtopo import (
        SyntheticMicroserviceApp, TopologyParams,
    )

    before = SyntheticMicroserviceApp(TopologyParams(
        num_services=num_services, num_endpoints=num_endpoints, seed=seed))
    after = SyntheticMicroserviceApp(TopologyParams(
        num_services=num_services_after, num_endpoints=num_endpoints,
        seed=seed))
    scenario.generic_endpoints = len(before.endpoints)
    return before, after, before.endpoints


def simulate_drift_corpus_iter(
    scenario: LoadScenario,
    num_buckets: int,
    shift_at: int,
    app_before,
    app_after,
    endpoints: tuple[str, ...],
    anomalies: list[Anomaly] | None = None,
    resource_seed: int | None = None,
):
    """Constant-memory corpus with a MID-CORPUS topology change: buckets
    before ``shift_at`` generate traces from ``app_before``, buckets at
    and after it from ``app_after`` (the rolling-deployment scenario the
    synthetic ``--services`` generator owed ROADMAP item 6).

    The fixed metric keyset is the UNION of both topologies' declared
    component sets, so every bucket carries identical keys — removed
    services go idle (their resource series fall to base load), added
    services come alive at the shift, exactly like a real scrape across
    a deployment.  Combine with an ``Anomaly`` whose window starts after
    ``shift_at`` for the ransomware-mid-drift scenario (the anomaly
    component must exist in ``app_after``)."""
    if not (0 < shift_at <= num_buckets):
        raise ValueError(
            f"shift_at {shift_at} must be in (0, num_buckets"
            f"={num_buckets}]")
    for app in (app_before, app_after):
        if not getattr(app, "components", ()):
            raise TypeError(
                "drift corpora need apps that declare .components "
                "(synthetic topologies do) — the union keyset cannot be "
                "discovered from a prefix that predates the shift")
    traffic = scenario.traffic(num_buckets)
    if traffic.shape[1] != len(endpoints):
        raise ValueError(
            f"scenario emits {traffic.shape[1]}-endpoint traffic but the "
            f"app has {len(endpoints)} endpoints — set "
            "scenario.generic_endpoints")
    ordered = sorted(set(app_before.components) | set(app_after.components))
    comp_set = set(ordered)
    trace_rng = np.random.default_rng(scenario.seed + 3)
    model = ResourceModel(
        seed=scenario.seed if resource_seed is None else resource_seed,
        anomalies=anomalies,
    )
    for t in range(num_buckets):
        app = app_before if t < shift_at else app_after
        traces = []
        for api_idx, api in enumerate(endpoints):
            for _ in range(int(traffic[t, api_idx])):
                traces.extend(app.generate(api, trace_rng))
        ops, writes = count_ops(traces)
        unknown = set(ops) - comp_set
        if unknown:
            raise ValueError(
                f"bucket {t}: components {sorted(unknown)} outside the "
                "declared union keyset — both apps must declare "
                ".components")
        yield Bucket(
            metrics=model.step_counts(ops, writes, components=ordered),
            traces=traces)


def write_corpus_jsonl(scenario: LoadScenario, num_buckets: int,
                       out_path: str, app=None, endpoints=None,
                       anomalies=None) -> dict:
    """Stream a corpus to JSONL at constant memory; returns counts."""
    stats = {"buckets": 0, "traces": 0, "metric_keys": 0}
    it = simulate_corpus_iter(scenario, num_buckets, anomalies=anomalies,
                              app=app, endpoints=endpoints)

    def counted():
        for b in it:
            stats["buckets"] += 1
            stats["traces"] += len(b.traces)
            stats["metric_keys"] = len(b.metrics)
            yield b

    save_raw_data_jsonl(counted(), out_path)
    return stats


def parse_anomaly(spec: str) -> Anomaly:
    """``kind:component:start:end[:magnitude]``"""
    parts = spec.split(":")
    if len(parts) not in (4, 5):
        raise argparse.ArgumentTypeError(
            f"anomaly spec {spec!r} != kind:component:start:end[:magnitude]"
        )
    return Anomaly(
        kind=parts[0], component=parts[1], start=int(parts[2]), end=int(parts[3]),
        magnitude=float(parts[4]) if len(parts) == 5 else 1.0,
    )


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", choices=sorted(SCENARIOS), default="normal")
    ap.add_argument("--buckets", type=int, default=480,
                    help="number of time buckets (a 'day' is 60)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", required=True,
                    help="output path (.jsonl or .pkl by extension)")
    ap.add_argument("--anomaly", type=parse_anomaly, action="append", default=[],
                    help="kind:component:start:end[:magnitude], repeatable")
    ap.add_argument("--calls-per-user", type=float, default=2.0)
    ap.add_argument("--app", choices=("social", "synthetic"), default="social",
                    help="application topology: the 12-service social network "
                         "or a seeded synthetic DAG (TrainTicket scale)")
    ap.add_argument("--services", type=int, default=40,
                    help="synthetic app: number of services")
    ap.add_argument("--endpoints", type=int, default=12,
                    help="synthetic app: number of API endpoints")
    ap.add_argument("--shift-at", type=int, default=0,
                    help="mid-corpus topology change: buckets at/after "
                         "this index generate from a re-drawn synthetic "
                         "topology with --services-after services "
                         "(0 = no shift; synthetic app only)")
    ap.add_argument("--services-after", type=int, default=None,
                    help="service count of the post-shift topology "
                         "(default: --services + 50%%)")
    args = ap.parse_args(argv)

    scenario = SCENARIOS[args.scenario](args.seed)
    scenario.calls_per_user = args.calls_per_user
    app = endpoints = None
    if args.shift_at:
        if args.app != "synthetic":
            ap.error("--shift-at needs --app synthetic (the social "
                     "topology is fixed)")
        after_n = (args.services_after if args.services_after is not None
                   else args.services + max(args.services // 2, 1))
        before, after, endpoints = build_shifted_app(
            scenario, args.services, after_n, args.endpoints, args.seed)
        it = simulate_drift_corpus_iter(
            scenario, args.buckets, args.shift_at, before, after,
            endpoints, anomalies=args.anomaly)
        if args.out.endswith(".pkl"):
            buckets = list(it)
            save_raw_data_pickle(buckets, args.out)
            stats = {"buckets": len(buckets),
                     "traces": sum(len(b.traces) for b in buckets),
                     "metric_keys": len(buckets[0].metrics)}
        else:
            from deeprest_tpu.data.schema import save_raw_data_jsonl

            stats = {"buckets": 0, "traces": 0, "metric_keys": 0}

            def counted():
                for b in it:
                    stats["buckets"] += 1
                    stats["traces"] += len(b.traces)
                    stats["metric_keys"] = len(b.metrics)
                    yield b

            save_raw_data_jsonl(counted(), args.out)
        print(f"wrote {stats['buckets']} buckets ({args.services}->"
              f"{after_n} services at bucket {args.shift_at}), "
              f"{stats['traces']} traces, {stats['metric_keys']} metric "
              f"keys -> {args.out}")
        return
    if args.app == "synthetic":
        app, endpoints = build_synthetic_app(scenario, args.services,
                                             args.endpoints, args.seed)
    if args.out.endswith(".pkl"):
        buckets = simulate_corpus(scenario, args.buckets,
                                  anomalies=args.anomaly,
                                  app=app, endpoints=endpoints)
        save_raw_data_pickle(buckets, args.out)
        stats = {"buckets": len(buckets),
                 "traces": sum(len(b.traces) for b in buckets),
                 "metric_keys": len(buckets[0].metrics)}
    else:
        # JSONL streams bucket-by-bucket: month-scale corpora never hold
        # more than one bucket of span objects in memory.
        stats = write_corpus_jsonl(scenario, args.buckets, args.out,
                                   app=app, endpoints=endpoints,
                                   anomalies=args.anomaly)
    print(f"wrote {stats['buckets']} buckets, {stats['traces']} traces, "
          f"{stats['metric_keys']} metric keys -> {args.out}")


if __name__ == "__main__":
    main()
