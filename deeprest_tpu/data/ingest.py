"""Ingestion adapters: real telemetry systems → the raw-data contract.

The reference's input contract is "Jaeger span trees + Prometheus metrics
from an instrumented cluster" (reference: resource-estimation/README.md:29-63
— "the tracing tool (e.g., Jaeger)" / "the monitoring tool (e.g.,
Prometheus)"; tracer wiring social-network-source/src/tracing.h:52-61;
Jaeger deployment social-network-deploy/k8s-yaml/tracing/run.yaml; scrape
configs minikube-openebs/monitor-openebs-pg.yaml:38-173), but its repo ships
no converter — the pickle appears fully formed.  This module is that
converter: it turns

- Jaeger query-API JSON (``GET /api/traces?...`` → ``{"data": [...]}``),
- OTLP/JSON trace exports (``{"resourceSpans": [...]}``), and
- Prometheus range-query JSON (``/api/v1/query_range`` → ``resultType:
  "matrix"``)

into :class:`~deeprest_tpu.data.schema.Bucket` lists that featurize
identically to the framework's own collector JSONL, so the estimator can be
pointed at ANY instrumented cluster with zero custom collection code.

Discretization follows the reference: the bucket width is the monitoring
scrape interval ("a window size ... defined as the scrape interval in the
resource monitoring tool", README.md:29; the reference cluster scrapes at
5 s, monitor-openebs-pg.yaml:38), traces land in the bucket of their root
span's start time, and counter-style metrics contribute per-bucket
increases while gauges contribute per-bucket means.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from deeprest_tpu.data.schema import Bucket, MetricSample, Span

# ---------------------------------------------------------------------------
# shared span-tree assembly
# ---------------------------------------------------------------------------


def _assemble_trees(
    records: Sequence[tuple[str, str | None, float, str, str]],
) -> list[tuple[float, Span]]:
    """Link ``(span_id, parent_id, start_seconds, component, operation)``
    records into rooted trees.

    Shared by the Jaeger and OTLP adapters (same algorithm, different wire
    fields): children are ordered by start time — the invocation ordering
    the span tree encodes (reference: resource-estimation/README.md:49-55)
    — and a span whose parent is absent from the dump becomes a root
    (partial captures must surface, not vanish).  Returns (root start
    seconds, tree) in root start order.
    """
    nodes: dict[str, Span] = {}
    start: dict[str, float] = {}
    parent: dict[str, str | None] = {}
    for sid, pid, ts, component, operation in records:
        nodes[sid] = Span(component=component, operation=operation)
        start[sid] = ts
        parent[sid] = pid
    pending: dict[str, list[tuple[float, Span]]] = {}
    for sid, node in nodes.items():
        pid = parent[sid]
        if pid is not None and pid in nodes:
            pending.setdefault(pid, []).append((start[sid], node))
    for pid, kids in pending.items():
        nodes[pid].children = [c for _, c in sorted(kids, key=lambda p: p[0])]
    roots = [sid for sid in nodes
             if parent[sid] is None or parent[sid] not in nodes]
    return [(start[sid], nodes[sid])
            for sid in sorted(roots, key=lambda s: start[s])]


# ---------------------------------------------------------------------------
# Jaeger query-API JSON → (root start-time, span tree)
# ---------------------------------------------------------------------------


def jaeger_traces(payload: Mapping[str, Any]) -> list[tuple[float, Span]]:
    """Convert a Jaeger query-API response into rooted span trees.

    Accepts the ``{"data": [trace, ...]}`` envelope or a bare trace list.
    Each Jaeger trace contributes one (start_time_seconds, tree) per root
    span (spans with no CHILD_OF reference, or whose parent is missing
    from the dump — Jaeger emits such orphans for partial captures).
    """
    traces = payload.get("data", payload) if isinstance(payload, Mapping) \
        else payload
    out: list[tuple[float, Span]] = []
    for trace in traces:
        procs = {
            pid: (proc.get("serviceName") or pid)
            for pid, proc in (trace.get("processes") or {}).items()
        }
        records = []
        for s in trace.get("spans") or []:
            pid = None
            for ref in s.get("references") or []:
                if ref.get("refType") == "CHILD_OF":
                    pid = ref.get("spanID")
                    break
            records.append((
                s["spanID"], pid, int(s.get("startTime", 0)) / 1e6,
                str(procs.get(s.get("processID"), s.get("processID", "?"))),
                str(s.get("operationName", "?")),
            ))
        out.extend(_assemble_trees(records))
    return out


# ---------------------------------------------------------------------------
# OTLP/JSON trace export → (root start-time, span tree)
# ---------------------------------------------------------------------------


def otlp_traces(payload: Mapping[str, Any]) -> list[tuple[float, Span]]:
    """Convert an OTLP/JSON trace export (``{"resourceSpans": [...]}``)
    into rooted span trees.  The component is the resource's
    ``service.name`` attribute — the same identity the reference's tracer
    registers per service (reference: social-network-source/src/
    tracing.h:52-61).  Spans are linked by (traceId, parentSpanId) across
    resource boundaries, so a cross-service trace assembles into one tree.
    """
    records: list[dict] = []
    for rs in payload.get("resourceSpans") or []:
        service = "?"
        for attr in ((rs.get("resource") or {}).get("attributes") or []):
            if attr.get("key") == "service.name":
                service = str((attr.get("value") or {}).get("stringValue",
                                                            service))
        for ss in rs.get("scopeSpans") or rs.get("instrumentationLibrarySpans") or []:
            for s in ss.get("spans") or []:
                records.append({
                    "trace": s.get("traceId"),
                    "id": s.get("spanId"),
                    "parent": s.get("parentSpanId") or None,
                    "service": service,
                    "op": str(s.get("name", "?")),
                    "start_ns": int(s.get("startTimeUnixNano", 0)),
                })
    by_trace: dict[str, list[dict]] = {}
    for r in records:
        by_trace.setdefault(r["trace"], []).append(r)
    out: list[tuple[float, Span]] = []
    for spans in by_trace.values():
        out.extend(_assemble_trees([
            (r["id"], r["parent"], r["start_ns"] / 1e9, r["service"], r["op"])
            for r in spans
        ]))
    return out


# ---------------------------------------------------------------------------
# Prometheus range-query JSON → (timestamp, component, resource, value)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MetricRule:
    """How one Prometheus metric becomes a raw-data resource series.

    ``mode='gauge'``: the bucket value is the mean of samples in the
    window (memory bytes, fs usage).  ``mode='counter'``: the bucket value
    is the within-bucket increase of a cumulative counter (cpu seconds,
    write counts), reset-tolerant (a decrease restarts from the new
    value).  These are the two shapes every metric in the reference's
    scrape set has (cadvisor + OpenEBS volume exporters,
    minikube-openebs/monitor-openebs-pg.yaml:60-173).
    """

    resource: str
    mode: str = "gauge"  # 'gauge' | 'counter'


# cadvisor-style defaults covering the reference's five resources
# (cpu / memory / write-iops / write-throughput / usage — SURVEY.md §L2).
DEFAULT_RESOURCE_MAP: dict[str, MetricRule] = {
    "container_cpu_usage_seconds_total": MetricRule("cpu", "counter"),
    "container_memory_working_set_bytes": MetricRule("memory", "gauge"),
    "container_fs_writes_total": MetricRule("wiops", "counter"),
    "container_fs_writes_bytes_total": MetricRule("wtp", "counter"),
    "container_fs_usage_bytes": MetricRule("usage", "gauge"),
}

# Component-identity labels, first match wins.  kubernetes_pod_name is the
# reference's own relabel target (monitor-openebs-pg.yaml:55-57,142-143).
COMPONENT_LABELS = ("kubernetes_pod_name", "pod", "container_label_io_kubernetes_pod_name",
                    "container", "instance", "job")


def prometheus_series(
    payload: Mapping[str, Any],
    resource_map: Mapping[str, MetricRule] | None = None,
    component_labels: Sequence[str] = COMPONENT_LABELS,
) -> list[tuple[float, str, str, float, str, str]]:
    """Flatten a ``query_range`` matrix response into
    ``(ts_seconds, component, resource, value, mode, series_id)`` samples.

    Series whose ``__name__`` has no entry in ``resource_map`` are skipped
    (a range query scoped to one metric has no such series; a federated
    dump may).  The component is the first present label from
    ``component_labels``.  ``series_id`` is the full label set: several
    Prometheus series can share one (component, resource) key — a
    multi-container pod has one cumulative cpu counter PER container —
    and counter increases are only meaningful within ONE series
    (interleaving two counters looks like resets and giant jumps), so
    bucketize aggregates per series first, then sums across series.
    """
    rmap = DEFAULT_RESOURCE_MAP if resource_map is None else resource_map
    data = payload.get("data", payload)
    out: list[tuple[float, str, str, float, str, str]] = []
    for series in data.get("result") or []:
        labels = series.get("metric") or {}
        rule = rmap.get(labels.get("__name__", ""))
        if rule is None:
            continue
        component = next((labels[l] for l in component_labels if l in labels),
                        None)
        if component is None:
            continue
        sid = json.dumps(sorted(labels.items()))
        for ts, val in series.get("values") or ([series["value"]]
                                                if "value" in series else []):
            try:
                v = float(val)
            except (TypeError, ValueError):
                continue
            if math.isnan(v):
                continue
            out.append((float(ts), str(component), rule.resource, v,
                        rule.mode, sid))
    return out


# ---------------------------------------------------------------------------
# discretization onto the common bucket timeline
# ---------------------------------------------------------------------------


def bucketize(
    traces: Iterable[tuple[float, Span]],
    samples: Iterable[tuple[float, str, str, float, str] |
                      tuple[float, str, str, float, str, str]],
    bucket_s: float,
    t0: float | None = None,
    t1: float | None = None,
) -> list[Bucket]:
    """Discretize traces + metric samples into the ordered bucket list the
    estimator consumes (reference: resource-estimation/README.md:29-34 —
    one item per scrape window).

    Every bucket carries the full (component, resource) keyset observed
    anywhere in the range, zero-filled when silent, so the metric-series
    matrix is rectangular — the property featurization requires.

    Aggregation is PER SERIES first (the optional 6th sample element; a
    multi-container pod has one cumulative counter per container, and
    interleaving two counters would read as resets and giant jumps), then
    summed across the key's series: counters sum their per-bucket
    increases, gauges sum their per-bucket means (a pod's memory is the
    sum of its containers').
    """
    traces = list(traces)
    samples = list(samples)
    if bucket_s <= 0:
        raise ValueError(f"bucket_s must be positive, got {bucket_s}")
    times = [t for t, _ in traces] + [s[0] for s in samples]
    if not times:
        return []
    lo = min(times) if t0 is None else t0
    hi = max(times) if t1 is None else t1
    lo = math.floor(lo / bucket_s) * bucket_s
    n = max(1, int(math.ceil((hi - lo) / bucket_s + 1e-9)) or 1)
    if hi >= lo + n * bucket_s:
        n += 1

    def idx(ts: float) -> int | None:
        i = int((ts - lo) // bucket_s)
        return i if 0 <= i < n else None

    trace_buckets: list[list[Span]] = [[] for _ in range(n)]
    for ts, root in traces:
        i = idx(ts)
        if i is not None:
            trace_buckets[i].append(root)

    # Vectorized grid placement: one numpy pass computes every sample's
    # bucket cell with the same floor semantics as the scalar
    # ``int((ts - lo) // bucket_s)`` (np.floor matches // for negatives).
    cells = np.empty((0,), np.int64)
    if samples:
        ts_all = np.fromiter((s[0] for s in samples), dtype=np.float64,
                             count=len(samples))
        cells = np.floor((ts_all - lo) / bucket_s).astype(np.int64)

    # (component, resource, series) → per-bucket accumulators.  Gauges
    # collect (cell, value) pairs and reduce with np.add.at/bincount below
    # — same f64 accumulation in the same sample order as the historical
    # scalar loop, so results are bit-identical; counters keep the
    # sequential reset-tolerant walk (inherently order-dependent).
    SKey = tuple  # (comp, res, series_id)
    gauge_pts: dict[SKey, list[tuple[int, float]]] = {}
    counter_vals: dict[SKey, list[list[tuple[float, float]]]] = {}
    modes: dict[SKey, str] = {}
    for k, sample in enumerate(samples):
        i = int(cells[k])
        if not 0 <= i < n:
            continue
        ts, comp, res, val, mode = sample[:5]
        sid = sample[5] if len(sample) > 5 else ""
        skey = (comp, res, sid)
        modes[skey] = mode
        if mode == "counter":
            counter_vals.setdefault(skey, [[] for _ in range(n)])[i].append(
                (ts, val))
        else:
            gauge_pts.setdefault(skey, []).append((i, val))

    values: dict[tuple[str, str], list[float]] = {}
    for skey, mode in modes.items():
        if mode == "counter":
            per = counter_vals[skey]
            vals = [0.0] * n
            prev_last: float | None = None
            for i in range(n):
                pts = sorted(per[i])
                inc = 0.0
                last = prev_last
                for _, v in pts:
                    if last is None:
                        last = v
                        continue
                    # reset-tolerant increase (counter restarted below its
                    # previous value): count growth from the new base.
                    inc += v - last if v >= last else v
                    last = v
                vals[i] = inc
                prev_last = last if last is not None else prev_last
        else:
            pts = gauge_pts[skey]
            cell_idx = np.fromiter((p[0] for p in pts), np.int64, len(pts))
            pt_vals = np.fromiter((p[1] for p in pts), np.float64, len(pts))
            sums = np.zeros((n,), np.float64)
            np.add.at(sums, cell_idx, pt_vals)
            cnts = np.bincount(cell_idx, minlength=n)
            vals = np.where(cnts > 0, sums / np.maximum(cnts, 1), 0.0).tolist()
        key = (skey[0], skey[1])
        acc = values.setdefault(key, [0.0] * n)
        for i in range(n):
            acc[i] += vals[i]

    keys = sorted(values)
    buckets = []
    for i in range(n):
        metrics = [MetricSample(component=c, resource=r,
                                value=values[(c, r)][i])
                   for c, r in keys]
        buckets.append(Bucket(metrics=metrics, traces=trace_buckets[i]))
    return buckets


# ---------------------------------------------------------------------------
# live-endpoint pull (Jaeger query API + Prometheus HTTP API)
# ---------------------------------------------------------------------------
#
# The reference deploys LIVE Jaeger and Prometheus services
# (social-network-deploy/k8s-yaml/tracing/run.yaml:1-18;
# minikube-openebs/monitor-openebs-pg.yaml:38-173) — file dumps were the
# hand-carried stopgap.  These pullers speak the same HTTP APIs those
# deployments expose, with time-range pagination, feeding the same
# ``bucketize``; stdlib urllib only (no client-library dependency).


def _http_get_json(url: str, timeout_s: float = 30.0):
    import urllib.request

    req = urllib.request.Request(url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def pull_jaeger(
    base_url: str,
    start_s: float,
    end_s: float,
    services: Sequence[str] | None = None,
    limit: int = 1000,
    timeout_s: float = 30.0,
    min_slice_s: float = 1.0,
    fetch=_http_get_json,
) -> list[tuple[float, Span]]:
    """Pull span trees from a live Jaeger query API over ``[start_s, end_s)``.

    Jaeger's HTTP API (``GET /api/traces?service=..&start=..&end=..``,
    microsecond timestamps) has no result cursor — only ``limit``.
    Pagination is therefore TIME-SLICING: when a slice returns ``limit``
    traces (the truncation signal), it is split in half and both halves
    re-queried, down to ``min_slice_s`` (below that the slice is accepted
    with a warning — better a bounded loss surfaced than an unbounded
    recursion).  ``services=None`` discovers the service list from
    ``/api/services``.  Traces are deduplicated by traceID across slices
    and services (a trace spanning services returns for each).
    """
    base = base_url.rstrip("/")
    if services is None:
        payload = fetch(f"{base}/api/services", timeout_s)
        services = [str(s) for s in (payload.get("data") or [])]
    from urllib.parse import urlencode

    seen: set[str] = set()
    out: list[tuple[float, Span]] = []
    for service in services:
        slices = [(start_s, end_s)]
        while slices:
            lo, hi = slices.pop()
            if hi <= lo:
                continue
            q = urlencode({"service": service, "start": int(lo * 1e6),
                           "end": int(hi * 1e6), "limit": limit})
            payload = fetch(f"{base}/api/traces?{q}", timeout_s)
            traces = payload.get("data") or []
            if len(traces) >= limit and (hi - lo) > min_slice_s:
                mid = (lo + hi) / 2.0
                slices += [(lo, mid), (mid, hi)]
                continue
            if len(traces) >= limit:
                import warnings

                warnings.warn(
                    f"jaeger slice [{lo}, {hi}) for {service!r} still hits "
                    f"limit={limit} at the {min_slice_s}s floor — some "
                    f"traces in this slice are not retrievable")
            fresh = [t for t in traces
                     if t.get("traceID") not in seen]
            for t in fresh:
                tid = t.get("traceID")
                if tid is not None:
                    seen.add(tid)
            out.extend(jaeger_traces(fresh))
    out.sort(key=lambda p: p[0])
    return out


def pull_prometheus(
    base_url: str,
    start_s: float,
    end_s: float,
    step_s: float,
    resource_map: Mapping[str, MetricRule] | None = None,
    timeout_s: float = 30.0,
    max_points: int = 10_000,
    fetch=_http_get_json,
) -> list[tuple[float, str, str, float, str, str]]:
    """Pull metric samples from a live Prometheus over ``[start_s, end_s]``.

    One ``/api/v1/query_range`` per metric in ``resource_map``, chunked so
    each request stays under ``max_points`` samples per series (Prometheus
    rejects ranges over ~11k points).  Chunk boundaries are inclusive on
    both ends, so boundary samples are deduplicated by (series, ts).
    """
    from urllib.parse import urlencode

    base = base_url.rstrip("/")
    rmap = DEFAULT_RESOURCE_MAP if resource_map is None else resource_map
    if step_s <= 0:
        raise ValueError(f"step_s must be positive, got {step_s}")
    span = max_points * step_s
    dedup: dict[tuple[str, float, str], tuple] = {}
    for metric in rmap:
        lo = start_s
        while True:
            hi = min(lo + span, end_s)
            q = urlencode({"query": metric, "start": lo, "end": hi,
                           "step": step_s})
            payload = fetch(f"{base}/api/v1/query_range?{q}", timeout_s)
            for s in prometheus_series(payload, resource_map=rmap):
                dedup[(s[5], s[0], s[2])] = s
            if hi >= end_s:
                break
            # Chunks OVERLAP at the boundary instant (the dedup key
            # absorbs the duplicate) — advancing past ``hi`` would skip
            # samples between evaluations.
            lo = hi
    return sorted(dedup.values(), key=lambda s: s[0])


def ingest_live(
    jaeger_url: str | None,
    prom_url: str | None,
    start_s: float,
    end_s: float,
    bucket_s: float,
    step_s: float | None = None,
    resource_map: Mapping[str, MetricRule] | None = None,
    services: Sequence[str] | None = None,
    timeout_s: float = 30.0,
    fetch=_http_get_json,
) -> list[Bucket]:
    """Pull ``[start_s, end_s)`` from live Jaeger/Prometheus endpoints and
    discretize into the ordered bucket list (``bucketize``).  ``step_s``
    defaults to the bucket width — the scrape-interval-as-bucket contract
    (reference: resource-estimation/README.md:29)."""
    traces = (pull_jaeger(jaeger_url, start_s, end_s, services=services,
                          timeout_s=timeout_s, fetch=fetch)
              if jaeger_url else [])
    samples = (pull_prometheus(prom_url, start_s, end_s,
                               step_s if step_s is not None else bucket_s,
                               resource_map=resource_map,
                               timeout_s=timeout_s, fetch=fetch)
               if prom_url else [])
    if not traces and not samples:
        return []
    # t1 a fraction of a bucket inside the end so an exactly-aligned range
    # keeps [start, end) semantics instead of growing a trailing empty
    # bucket.  The margin is bucket-relative (not absolute 1e-9: epoch
    # timestamps have float ulp ~2.4e-7, which would swallow it); sample
    # placement is unaffected — only the range arithmetic sees t1.
    return bucketize(traces, samples, bucket_s, t0=start_s,
                     t1=max(start_s, end_s - 1e-3 * bucket_s))


class LiveEndpointTailer:
    """A ``BucketTailer``-protocol source polling live Jaeger/Prometheus.

    Each ``poll()`` pulls the closed bucket range since the previous poll
    (aligned down to whole buckets, ``lag_s`` behind the clock so
    scrape/collection stragglers land before their bucket is read) and
    returns it as Buckets — plugging a live cluster straight into
    ``StreamingTrainer.run`` with no hand-carried dumps.

    A successful pull that yields NO data for its range emits zero-filled
    buckets for the skipped grid cells (a quiet cluster, or series gone
    stale) rather than silently advancing past them: downstream windowing
    treats consecutive list entries as time-adjacent, and a counter
    increase across a silent gap must not collapse into one bucket.

    Failures escalate instead of retrying forever: DETERMINISTIC errors
    (bad URL, HTTP 4xx like 404/auth) raise after
    ``max_deterministic_failures`` consecutive occurrences — a stream
    that can never succeed must not look healthy while ingesting nothing.
    Transient errors (timeouts, connection resets, 5xx) keep retrying the
    same range but set ``degraded`` after ``max_transient_failures`` in a
    row so operators can see the outage; any success clears both.
    """

    backlog = False     # the pull is always caught up to now - lag

    def __init__(self, jaeger_url: str | None = None,
                 prom_url: str | None = None, bucket_s: float = 5.0,
                 step_s: float | None = None,
                 resource_map: Mapping[str, MetricRule] | None = None,
                 services: Sequence[str] | None = None,
                 lag_s: float | None = None, timeout_s: float = 30.0,
                 max_deterministic_failures: int = 3,
                 max_transient_failures: int = 8,
                 now=None, fetch=_http_get_json):
        if not jaeger_url and not prom_url:
            raise ValueError("need at least one of jaeger_url/prom_url")
        import time as _time

        self.jaeger_url = jaeger_url
        self.prom_url = prom_url
        self.bucket_s = bucket_s
        self.step_s = step_s
        self.resource_map = resource_map
        self.services = services
        self.lag_s = 2 * bucket_s if lag_s is None else lag_s
        self.timeout_s = timeout_s
        self.max_deterministic_failures = max_deterministic_failures
        self.max_transient_failures = max_transient_failures
        self.consecutive_failures = 0
        self._deterministic_failures = 0
        self.degraded = False
        self._now = now if now is not None else _time.time
        self._fetch = fetch
        # Start at the previous whole bucket so the first poll returns at
        # most one bucket instead of an unbounded history backfill.
        self._cursor = (math.floor((self._now() - self.lag_s) / bucket_s)
                        * bucket_s)

    # -- ingest-watermark convention (round 24, shared with the wire
    # -- receiver in data/wire.py; train/stream.py persists it in the
    # -- round-17 checkpoint sidecar and hands it back on resume) ------

    def ingest_watermark(self) -> dict:
        """This source's resume cursor: the bucket-aligned instant up to
        which every poll result has been handed to the stream."""
        return {"kind": "time_cursor", "position": float(self._cursor)}

    def resume_from(self, wm: dict) -> None:
        """Adopt a persisted cursor so a restarted stream re-polls the
        gap since its last checkpoint exactly once — no bucket skipped,
        none double-counted.  Foreign/malformed dialects are ignored
        (the fresh now-lag cursor stands)."""
        if not isinstance(wm, dict) or wm.get("kind") != "time_cursor":
            return
        try:
            pos = float(wm["position"])
        except (KeyError, TypeError, ValueError):
            return
        if pos > 0:
            # re-align defensively: a cursor off the bucket grid would
            # shift every subsequent bucket boundary
            self._cursor = math.floor(pos / self.bucket_s) * self.bucket_s

    def _note_failure(self, exc: Exception) -> None:
        import urllib.error

        self.consecutive_failures += 1
        # HTTPError before ValueError has no overlap issue (HTTPError is an
        # OSError); 4xx minus 429 is deterministic — the same request will
        # fail the same way (wrong path, missing series endpoint, auth) —
        # while 5xx/429 and transport errors are worth retrying.
        deterministic = (
            isinstance(exc, urllib.error.HTTPError)
            and 400 <= exc.code < 500 and exc.code != 429
        ) or (isinstance(exc, (ValueError, TypeError))
              and not isinstance(exc, urllib.error.URLError))
        if deterministic:
            self._deterministic_failures += 1
            if self._deterministic_failures >= self.max_deterministic_failures:
                raise RuntimeError(
                    f"live ingest: {self._deterministic_failures} consecutive "
                    f"deterministic failures (last: {exc!r}) — the endpoint "
                    "configuration is wrong; retrying cannot succeed"
                ) from exc
        else:
            self._deterministic_failures = 0
        if (not self.degraded
                and self.consecutive_failures >= self.max_transient_failures):
            self.degraded = True
            print(f"live ingest: DEGRADED — {self.consecutive_failures} "
                  f"consecutive pull failures (last: {exc!r})")
        print(f"live ingest: pull failed ({exc}); will retry")

    def poll(self) -> list[Bucket]:
        edge = (math.floor((self._now() - self.lag_s) / self.bucket_s)
                * self.bucket_s)
        if edge <= self._cursor:
            return []
        try:
            # Pull ONE lead-in bucket before the cursor and drop it: a
            # counter's per-bucket increase needs a base sample BEFORE the
            # first reported bucket — without the lead-in, every poll's
            # first bucket (i.e. every bucket, at one-bucket-per-poll
            # cadence) would re-establish bases and stream counters as 0.
            buckets = ingest_live(
                self.jaeger_url, self.prom_url,
                self._cursor - self.bucket_s, edge,
                self.bucket_s, step_s=self.step_s,
                resource_map=self.resource_map, services=self.services,
                timeout_s=self.timeout_s, fetch=self._fetch)[1:]
        except Exception as exc:   # blip: retry the SAME range (bounded)
            self._note_failure(exc)
            return []
        self.consecutive_failures = 0
        self._deterministic_failures = 0
        self.degraded = False
        cells = int(round((edge - self._cursor) / self.bucket_s))
        if len(buckets) < cells:
            # bucketize zero-fills interior grid cells whenever ANY data
            # exists in the range, so a short return means the whole range
            # was silent: keep the bucket stream's continuous cadence with
            # explicitly empty buckets (and a log line for operators).
            missing = cells - len(buckets)
            print(f"live ingest: no data for {missing} of {cells} bucket(s) "
                  f"in [{self._cursor:.0f}, {edge:.0f}); zero-filling")
            buckets = buckets + [Bucket() for _ in range(missing)]
        self._cursor = edge
        return buckets

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# file-level convenience (the CLI's ingest surface)
# ---------------------------------------------------------------------------


def ingest_files(
    trace_paths: Sequence[str],
    prom_paths: Sequence[str],
    bucket_s: float,
    resource_map: Mapping[str, MetricRule] | None = None,
) -> list[Bucket]:
    """Load Jaeger/OTLP trace dumps + Prometheus range dumps and produce
    the ordered bucket list.  Format auto-detection: a payload with
    ``resourceSpans`` is OTLP, otherwise Jaeger query JSON; metric files
    must be query_range responses."""
    traces: list[tuple[float, Span]] = []
    for path in trace_paths:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        if isinstance(payload, Mapping) and "resourceSpans" in payload:
            traces.extend(otlp_traces(payload))
        else:
            traces.extend(jaeger_traces(payload))
    samples: list[tuple[float, str, str, float, str]] = []
    for path in prom_paths:
        with open(path, encoding="utf-8") as f:
            samples.extend(prometheus_series(json.load(f),
                                             resource_map=resource_map))
    return bucketize(traces, samples, bucket_s)
