"""What-if trace synthesis: hypothetical traffic → feature vectors.

Capability parity with the reference's TraceSynthesizer (reference:
resource-estimation/synthesizer.py:10-52): learn, per API endpoint (root
span), the empirical distribution over observed *single-trace* feature
vectors; then synthesize a traffic feature vector for any requested
``{endpoint: count}`` mix — including shapes/scales/compositions never
observed — by sampling that many per-trace vectors per endpoint and summing.

Differences from the reference: vectors are keyed by compact byte signatures
instead of ``str``/``eval`` round-trips, sampling draws counts from a
multinomial instead of looping per call (O(#distinct) not O(#calls)), and
the synthesizer shares the corpus-wide :class:`CallPathSpace` so synthetic
vectors are column-compatible with training features by construction.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.data.featurize import CallPathSpace
from deeprest_tpu.data.schema import Bucket, Span


@dataclasses.dataclass
class _EndpointDist:
    vectors: np.ndarray    # [num_distinct, capacity] observed per-trace vectors
    counts: np.ndarray     # [num_distinct] observation counts

    @property
    def probs(self) -> np.ndarray:
        return self.counts / self.counts.sum()


class TraceSynthesizer:
    """Per-endpoint empirical distribution over single-trace feature vectors."""

    def __init__(self, space: CallPathSpace):
        self.space = space
        self._dists: dict[str, _EndpointDist] = {}

    # ------------------------------------------------------------------

    def fit(self, buckets: list[Bucket]) -> "TraceSynthesizer":
        self.space.observe(buckets)
        acc: dict[str, dict[bytes, tuple[np.ndarray, int]]] = {}
        for bucket in buckets:
            for trace in bucket.traces:
                endpoint = trace.label
                vec = self.space.extract([trace])
                key = vec.tobytes()
                per_ep = acc.setdefault(endpoint, {})
                if key in per_ep:
                    per_ep[key] = (per_ep[key][0], per_ep[key][1] + 1)
                else:
                    per_ep[key] = (vec, 1)
        self._dists = {
            ep: _EndpointDist(
                vectors=np.stack([v for v, _ in entries.values()]),
                counts=np.asarray([c for _, c in entries.values()], np.float64),
            )
            for ep, entries in acc.items()
        }
        return self

    @property
    def endpoints(self) -> list[str]:
        return sorted(self._dists)

    # ------------------------------------------------------------------

    def synthesize(self, expected_api_calls: dict[str, int],
                   rng: np.random.Generator | None = None) -> np.ndarray:
        """One time step: ``{endpoint: count}`` → [capacity] feature vector."""
        rng = rng or np.random.default_rng()
        x = np.zeros((self.space.capacity,), dtype=np.float32)
        for endpoint, count in expected_api_calls.items():
            if endpoint not in self._dists:
                raise KeyError(
                    f"unknown API endpoint {endpoint!r}; observed: {self.endpoints}"
                )
            if count <= 0:
                continue
            dist = self._dists[endpoint]
            draws = rng.multinomial(count, dist.probs)     # [num_distinct]
            x += draws.astype(np.float32) @ dist.vectors
        return x

    def synthesize_series(self, traffic: list[dict[str, int]],
                          seed: int = 0) -> np.ndarray:
        """A whole hypothetical timeline: list of per-step mixes → [T, capacity]."""
        rng = np.random.default_rng(seed)
        return np.stack([self.synthesize(step, rng) for step in traffic])


def synthesize_span(trace_dict: dict) -> Span:
    """Convenience: dict literal → Span (for handwritten what-if traces)."""
    return Span.from_dict(trace_dict)
