"""Host-side data plane: telemetry contract, featurization, windowing."""

from deeprest_tpu.data.schema import Span, MetricSample, Bucket, load_raw_data
from deeprest_tpu.data.featurize import (
    CallPathSpace,
    featurize_buckets,
    count_invocations,
)
from deeprest_tpu.data.windows import (
    sliding_windows,
    MinMaxStats,
    minmax_fit,
    minmax_apply,
    minmax_invert,
)
from deeprest_tpu.data.synthesize import TraceSynthesizer

__all__ = [
    "Span",
    "MetricSample",
    "Bucket",
    "load_raw_data",
    "CallPathSpace",
    "featurize_buckets",
    "count_invocations",
    "sliding_windows",
    "MinMaxStats",
    "minmax_fit",
    "minmax_apply",
    "minmax_invert",
    "TraceSynthesizer",
]
