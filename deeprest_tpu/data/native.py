"""ctypes binding for the native C++ featurization ETL.

Loads ``native/libdeeprest_etl.so`` (built via ``make -C native``) and
exposes :func:`featurize_jsonl`, which matches
:func:`deeprest_tpu.data.featurize.featurize_buckets` output exactly but
streams the corpus twice through the C++ parser instead of materializing
Python span trees — the fast path for month-scale corpora.  Falls back to
the pure-Python pipeline when the library isn't built (``require_native``
turns that into an error).
"""

from __future__ import annotations

import ctypes
import json
import os
import tempfile

import numpy as np

from deeprest_tpu.config import FeaturizeConfig
from deeprest_tpu.data.featurize import CallPathSpace, FeaturizedData, featurize_buckets

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# Env override first so sanitizer/instrumented builds can be forced even
# when the default library exists.
_LIB_CANDIDATES = (
    os.environ.get("DEEPREST_ETL_LIB", ""),
    os.path.join(_REPO_ROOT, "native", "libdeeprest_etl.so"),
)

_lib: ctypes.CDLL | None = None
_lib_checked = False


def load_library() -> ctypes.CDLL | None:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    for path in _LIB_CANDIDATES:
        if path and os.path.exists(path):
            lib = ctypes.CDLL(path)
            lib.drft_featurize_file.restype = ctypes.c_int
            lib.drft_featurize_file.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
                ctypes.c_longlong, ctypes.c_longlong, ctypes.c_ulonglong,
                ctypes.c_char_p, ctypes.c_longlong,
            ]
            lib.drft_stable_hash.restype = ctypes.c_ulonglong
            lib.drft_stable_hash.argtypes = [ctypes.c_char_p, ctypes.c_ulonglong]
            _lib = lib
            break
    return _lib


def native_available() -> bool:
    return load_library() is not None


def stable_hash_native(joined: str, seed: int) -> int:
    lib = load_library()
    if lib is None:
        raise RuntimeError("native ETL library not built (make -C native)")
    return int(lib.drft_stable_hash(joined.encode("utf-8"), seed))


def featurize_jsonl(
    path: str,
    config: FeaturizeConfig | None = None,
    require_native: bool = False,
) -> FeaturizedData:
    """Featurize a JSONL corpus via the native ETL (or Python fallback)."""
    config = config or FeaturizeConfig()
    lib = load_library()
    if lib is None:
        if require_native:
            raise RuntimeError("native ETL library not built (make -C native)")
        from deeprest_tpu.data.schema import load_raw_data

        return featurize_buckets(load_raw_data(path), config)

    with tempfile.TemporaryDirectory(prefix="drft_etl_") as out_dir:
        err = ctypes.create_string_buffer(1024)
        rc = lib.drft_featurize_file(
            path.encode("utf-8"), out_dir.encode("utf-8"),
            1 if config.hash_features else 0,
            config.capacity, config.round_to, config.hash_seed,
            err, len(err),
        )
        if rc != 0:
            raise ValueError(f"native featurize failed: {err.value.decode()}")

        with open(os.path.join(out_dir, "header.json"), encoding="utf-8") as f:
            header = json.load(f)
        t, cap = header["num_buckets"], header["capacity"]
        metric_keys = header["metric_keys"]
        components = header["components"]

        def load(name, cols):
            arr = np.fromfile(os.path.join(out_dir, name), dtype="<f4")
            return arr.reshape(t, cols)

        traffic = load("traffic.bin", cap)
        resources_mat = load("resources.bin", len(metric_keys))
        invocations_mat = load("invocations.bin", len(components))

    space = CallPathSpace(config=config)
    space.frozen_capacity = cap
    if not config.hash_features:
        space.index = {
            tuple(key.split("\x1f")): i for i, key in enumerate(header["vocab"])
        }
    return FeaturizedData(
        traffic=traffic,
        resources={k: resources_mat[:, i].copy() for i, k in enumerate(metric_keys)},
        invocations={c: invocations_mat[:, i].copy() for i, c in enumerate(components)},
        space=space,
    )
