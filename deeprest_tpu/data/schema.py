"""The raw-telemetry data contract.

The reference defines this contract only informally, as the pickle layout its
ETL must produce (reference: resource-estimation/README.md:29-63 and the
3-bucket example raw_data.pkl): an ordered list of time buckets, one per
monitoring scrape window, each holding

    {"metrics": [{"component": str, "resource": str, "value": float}, ...],
     "traces":  [span-tree, ...]}

where a span tree is ``{"component": str, "operation": str, "children": [...]}``.

Here the contract is typed and has two on-disk encodings:

1. the reference-compatible pickle of plain dicts (so reference corpora load
   unchanged), and
2. a streaming-friendly JSON-lines encoding (one bucket per line) that the
   native C++ featurizer and the workload simulator both speak — the explicit
   ETL artifact the reference leaves implicit (SURVEY.md §L2).
"""

from __future__ import annotations

import dataclasses
import io
import json
import pickle
from typing import Any, Iterable, Iterator, Mapping, Sequence


@dataclasses.dataclass
class Span:
    """One node of a distributed-trace span tree."""

    component: str
    operation: str
    children: list["Span"] = dataclasses.field(default_factory=list)

    @property
    def label(self) -> str:
        # The per-node feature-space token; the reference joins with "_"
        # (reference: resource-estimation/featurize.py:13) which is ambiguous
        # when component names contain underscores — kept for parity, the
        # call-path key itself is a tuple so no ambiguity leaks upward.
        return f"{self.component}_{self.operation}"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Span":
        return cls(
            component=str(d["component"]),
            operation=str(d["operation"]),
            children=[cls.from_dict(c) for c in d.get("children", ())],
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "component": self.component,
            "operation": self.operation,
            "children": [c.to_dict() for c in self.children],
        }

    def walk(self, prefix: tuple[str, ...] = ()) -> Iterator[tuple[tuple[str, ...], "Span"]]:
        """Yield (root-to-node call path, node) for every node in the tree."""
        path = prefix + (self.label,)
        yield path, self
        for child in self.children:
            yield from child.walk(path)


@dataclasses.dataclass
class MetricSample:
    """One resource measurement for one component in one time bucket."""

    component: str
    resource: str
    value: float

    @property
    def key(self) -> str:
        return f"{self.component}_{self.resource}"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "MetricSample":
        return cls(str(d["component"]), str(d["resource"]), float(d["value"]))

    def to_dict(self) -> dict[str, Any]:
        return {
            "component": self.component,
            "resource": self.resource,
            "value": self.value,
        }


@dataclasses.dataclass
class Bucket:
    """One monitoring time window: resource measurements + the traces in it."""

    metrics: list[MetricSample] = dataclasses.field(default_factory=list)
    traces: list[Span] = dataclasses.field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Bucket":
        return cls(
            metrics=[MetricSample.from_dict(m) for m in d.get("metrics", ())],
            traces=[Span.from_dict(t) for t in d.get("traces", ())],
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "metrics": [m.to_dict() for m in self.metrics],
            "traces": [t.to_dict() for t in self.traces],
        }


# --------------------------------------------------------------------------
# Loading / saving


def load_raw_data(path: str) -> list[Bucket]:
    """Load a corpus from either encoding, sniffed by content.

    Accepts the reference pickle layout unchanged and the JSONL encoding.
    """
    with open(path, "rb") as f:
        head = f.read(2)
        f.seek(0)
        if head[:1] in (b"{", b"["):  # JSONL (or a single JSON array)
            text = io.TextIOWrapper(f, encoding="utf-8")
            first = text.read(1)
            text.seek(0)
            if first == "[":
                return [Bucket.from_dict(b) for b in json.load(text)]
            return [Bucket.from_dict(json.loads(line)) for line in text if line.strip()]
        raw = pickle.load(f)
    return [Bucket.from_dict(b) for b in raw]


def save_raw_data_pickle(buckets: Sequence[Bucket], path: str) -> None:
    """Write the reference-compatible pickle-of-dicts encoding."""
    with open(path, "wb") as f:
        pickle.dump([b.to_dict() for b in buckets], f)


def save_raw_data_jsonl(buckets: Iterable[Bucket], path: str) -> None:
    """Write the streaming JSONL encoding (one bucket per line)."""
    with open(path, "w", encoding="utf-8") as f:
        for b in buckets:
            json.dump(b.to_dict(), f, separators=(",", ":"))
            f.write("\n")


def iter_raw_data_jsonl(path: str) -> Iterator[Bucket]:
    """Stream buckets from a JSONL corpus without loading it whole."""
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if line.strip():
                yield Bucket.from_dict(json.loads(line))
