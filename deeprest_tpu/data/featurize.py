"""Call-path featurization: span trees → fixed-width count vectors.

Semantics follow the reference's feature construction (reference:
resource-estimation/featurize.py:11-57): every root-to-node *call path*
observed in any trace becomes one feature dimension, and a bucket's feature
vector counts how many times each path occurs across the bucket's traces.
Per-component invocation counts (plus a synthetic ``general`` stream counting
whole traces) feed the component-aware baseline.

TPU-first departures from the reference:

- **Static width.**  The raw space is unbounded; XLA wants static shapes.
  Vectors are materialized at a fixed ``capacity`` (rounded up to an MXU-lane
  multiple) so a growing vocabulary never changes array shapes mid-run.
- **Hash-bucketing mode.**  For streaming/10k-endpoint corpora the dictionary
  is replaced by a seeded FNV-1a hash of the call path into ``capacity``
  buckets: no global vocabulary pass, no recompile, multi-host and
  cross-language consistent (native/featurizer.cpp implements the same
  function).
- **Streaming API.**  ``observe``/``extract`` work bucket-at-a-time so the
  continuous-retrain mode can featurize a live firehose.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from deeprest_tpu.config import FeaturizeConfig
from deeprest_tpu.data.schema import Bucket, Span

CallPath = tuple[str, ...]

# float32 can represent every integer count below 2**24 exactly, which is
# what makes the vectorized bincount path bit-identical to the historical
# `x[col] += 1.0` accumulation loop (see CallPathSpace.extract).
_EXACT_F32_COUNT = 1 << 24


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_SEED_MIX = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _stable_hash(path: CallPath, seed: int) -> int:
    """Seeded FNV-1a over the \\x1f-joined call path.

    Deliberately simple: the native C++ featurizer (native/featurizer.cpp)
    implements the identical function so hash-mode columns are consistent
    across languages and hosts.
    """
    h = _FNV_OFFSET ^ ((seed * _SEED_MIX) & _MASK64)
    for b in "\x1f".join(path).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


@dataclasses.dataclass
class CallPathSpace:
    """The feature space M: call path → column index.

    In dictionary mode indices are assigned in first-observed order, matching
    the reference's growth rule (reference: resource-estimation/
    featurize.py:14-15) so vocabularies are reproducible for a fixed corpus
    order.  In hash mode indices are ``stable_hash(path) % capacity`` and the
    space never needs fitting.
    """

    config: FeaturizeConfig = dataclasses.field(default_factory=FeaturizeConfig)
    index: dict[CallPath, int] = dataclasses.field(default_factory=dict)
    # Set on first extract (or explicit freeze()); afterwards the vector
    # width never changes even if the vocabulary keeps growing.
    frozen_capacity: int | None = None
    # Hash-mode memo: call path → column.  Paths repeat massively across
    # traces and the byte-wise FNV is the dominant per-span cost; one hash
    # per distinct path amortizes it away.  Only populated after freeze()
    # (the column depends on the frozen capacity); never serialized — it is
    # pure cache, rebuilt on demand.  Dictionary mode needs no memo: the
    # index IS the path→column map.
    _hash_memo: dict[CallPath, int] = dataclasses.field(
        default_factory=dict, repr=False, compare=False)

    # -- construction ------------------------------------------------------

    def observe(self, buckets_or_traces: Iterable[Bucket] | Iterable[Span]) -> "CallPathSpace":
        """Grow the vocabulary from buckets (or bare traces). No-op in hash mode."""
        if self.config.hash_features:
            return self
        for item in buckets_or_traces:
            traces = item.traces if isinstance(item, Bucket) else [item]
            for trace in traces:
                for path, _ in trace.walk():
                    if path not in self.index:
                        self.index[path] = len(self.index)
        return self

    @classmethod
    def fit(cls, buckets: Iterable[Bucket], config: FeaturizeConfig | None = None) -> "CallPathSpace":
        return cls(config=config or FeaturizeConfig()).observe(buckets)

    # -- geometry ----------------------------------------------------------

    @property
    def num_observed(self) -> int:
        return len(self.index)

    @property
    def capacity(self) -> int:
        """Static feature-vector width (the model's input dimension).

        Frozen at the first extraction so a vocabulary that keeps growing
        can never change array shapes mid-run (it overflows instead).
        """
        if self.frozen_capacity is not None:
            return self.frozen_capacity
        cfg = self.config
        if cfg.capacity > 0:
            return cfg.capacity
        return _round_up(max(self.num_observed, 1), cfg.round_to)

    def freeze(self) -> "CallPathSpace":
        """Pin the current capacity as the permanent vector width."""
        if self.frozen_capacity is None:
            self.frozen_capacity = self.capacity
        return self

    def column_of(self, path: CallPath) -> int | None:
        if self.config.hash_features:
            return _stable_hash(path, self.config.hash_seed) % self.capacity
        idx = self.index.get(path)
        if idx is None or idx >= self.capacity:
            return None
        return idx

    # -- extraction --------------------------------------------------------

    def _trace_columns(self, traces: Sequence[Span]) -> np.ndarray:
        """int32 column ids, one per counted span, across ``traces``.

        The vectorized core: an explicit-stack preorder walk (no generator
        frames, no per-visit ``label`` property) that resolves each path to
        its column via the hash memo (hash mode) or the index (dictionary
        mode, overflow columns dropped).  Count order is irrelevant — the
        caller bincounts — so only the multiset of columns must match the
        reference loop's.  Requires a frozen capacity (extract freezes).
        """
        cols: list[int] = []
        append = cols.append
        if self.config.hash_features:
            memo = self._hash_memo
            memo_get = memo.get
            cap = self.capacity
            seed = self.config.hash_seed
            for trace in traces:
                stack = [((), trace)]
                pop, push = stack.pop, stack.append
                while stack:
                    prefix, node = pop()
                    path = prefix + (node.component + "_" + node.operation,)
                    c = memo_get(path)
                    if c is None:
                        c = _stable_hash(path, seed) % cap
                        memo[path] = c
                    append(c)
                    for child in node.children:
                        push((path, child))
        else:
            # The index is already the memo; unknown paths are NOT cached
            # as dropped — observe() may legally assign them a column later
            # (the reference loop honors that, so the memo must too).
            index_get = self.index.get
            cap = self.capacity
            for trace in traces:
                stack = [((), trace)]
                pop, push = stack.pop, stack.append
                while stack:
                    prefix, node = pop()
                    path = prefix + (node.component + "_" + node.operation,)
                    idx = index_get(path)
                    if idx is not None and idx < cap:
                        append(idx)
                    for child in node.children:
                        push((path, child))
        return np.asarray(cols, dtype=np.int32)

    def extract(self, traces: Sequence[Span], out: np.ndarray | None = None) -> np.ndarray:
        """Count each call path across ``traces`` into a [capacity] vector.

        Freezes the capacity on first call.  A caller-supplied ``out`` buffer
        is fully overwritten (counts are per-call, never cumulative).  Paths
        beyond a fixed ``capacity`` in dictionary mode are dropped (counted
        into nothing) — the documented overflow policy; size the capacity or
        switch to hashing to avoid it.

        Vectorized: column ids are gathered once per span (memoized per
        path) and accumulated with ``np.bincount``.  Bit-identical to the
        reference loop (``extract_reference``) for any count below 2**24 —
        counts are integers and float32 represents those exactly.
        """
        self.freeze()
        counts = np.bincount(self._trace_columns(traces),
                             minlength=self.capacity)
        if out is not None:
            out[:] = counts
            return out
        return counts.astype(np.float32)

    def extract_sparse(self, traces: Sequence[Span]
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Sparse twin of :meth:`extract`: ``(cols, counts)`` for the
        nonzero columns only, off the same memoized ``_trace_columns``
        walk.

        At the 10k-endpoint width any one bucket touches a handful of
        call paths — the dense vector is >99% zeros — so the sparse-first
        pipeline (train/data.SparseSeriesRing → ops/densify.densify_coo)
        carries ``(cols, counts)`` and defers densification to one
        on-device scatter.  Columns are unique and ascending
        (``np.unique``); counts are float32 integers, so scattering them
        into a zero vector is BIT-IDENTICAL to :meth:`extract` for any
        count below 2**24 (pinned by tests/test_sparse.py).  Freezes the
        capacity on first call, exactly like ``extract``.
        """
        self.freeze()
        cols, counts = np.unique(self._trace_columns(traces),
                                 return_counts=True)
        return cols.astype(np.int32), counts.astype(np.float32)

    def trace_columns_from_dict(self, trace) -> np.ndarray:
        """Preorder int32 column ids for ONE raw span-tree dict.

        The wire receiver's Span-free twin of :meth:`_trace_columns`
        (data/wire.py decodes frame payloads straight off the socket):
        walking the parsed JSON dict directly skips the per-span
        ``Span.from_dict`` object construction the file-tailer path
        pays, while producing the identical column multiset —
        ``np.unique`` downstream makes the two paths bit-identical
        (tests/test_wire.py pins this against
        ``_trace_columns([Span.from_dict(d)])``).  Shares the hash memo
        with every other extraction path.  Freezes the capacity like
        ``extract``.
        """
        self.freeze()
        cols: list[int] = []
        append = cols.append
        if self.config.hash_features:
            memo = self._hash_memo
            memo_get = memo.get
            cap = self.capacity
            seed = self.config.hash_seed
            stack = [((), trace)]
            pop, push = stack.pop, stack.append
            while stack:
                prefix, node = pop()
                path = prefix + (str(node["component"]) + "_"
                                 + str(node["operation"]),)
                c = memo_get(path)
                if c is None:
                    c = _stable_hash(path, seed) % cap
                    memo[path] = c
                append(c)
                for child in node.get("children", ()):
                    push((path, child))
        else:
            index_get = self.index.get
            cap = self.capacity
            stack = [((), trace)]
            pop, push = stack.pop, stack.append
            while stack:
                prefix, node = pop()
                path = prefix + (str(node["component"]) + "_"
                                 + str(node["operation"]),)
                idx = index_get(path)
                if idx is not None and idx < cap:
                    append(idx)
                for child in node.get("children", ()):
                    push((path, child))
        return np.asarray(cols, dtype=np.int32)

    def sparse_from_columns(self, col_parts: Sequence[np.ndarray]
                            ) -> tuple[np.ndarray, np.ndarray]:
        """``(cols, counts)`` from precomputed per-trace column arrays —
        the commit half of the wire hot path.

        ``extract_sparse(traces)`` is exactly
        ``sparse_from_columns([per-trace columns])`` because
        ``_trace_columns`` is the per-trace concatenation and
        ``np.unique`` consumes an order-free multiset; this is what lets
        data/wire.py memoize whole trace blobs (bytes → column array)
        and still train bit-identically to the tailer path
        (tests/test_wire.py pins the equality)."""
        self.freeze()
        if col_parts:
            allcols = np.concatenate(col_parts)
        else:
            allcols = np.empty(0, dtype=np.int32)
        cols, counts = np.unique(allcols, return_counts=True)
        return cols.astype(np.int32), counts.astype(np.float32)

    def extract_reference(self, traces: Sequence[Span],
                          out: np.ndarray | None = None) -> np.ndarray:
        """The historical per-span accumulation loop, kept verbatim as the
        semantic specification of ``extract``: parity tests pin the
        vectorized path against it bit-for-bit, and benchmarks/etl_bench.py
        uses it as the old-throughput baseline."""
        self.freeze()
        if out is not None:
            out[:] = 0.0
            x = out
        else:
            x = np.zeros((self.capacity,), dtype=np.float32)  # graftlint: disable=DN001 -- the pinned per-span accumulation REFERENCE is dense by definition; extract_sparse is the sparse-first path
        for trace in traces:
            for path, _ in trace.walk():
                col = self.column_of(path)
                if col is not None:
                    x[col] += 1.0
        return x

    def extract_buckets(self, buckets: Sequence[Bucket]) -> np.ndarray:
        """[num_buckets, capacity] traffic matrix."""
        self.freeze()
        out = np.zeros((len(buckets), self.capacity), dtype=np.float32)  # graftlint: disable=DN001 -- the offline [T, F] corpus matrix is this function's documented product (FeaturizedData.traffic); the streaming hot path uses extract_sparse + SparseSeriesRing instead
        for t, bucket in enumerate(buckets):
            self.extract(bucket.traces, out=out[t])
        return out

    # -- introspection -----------------------------------------------------

    def vocabulary(self) -> list[CallPath]:
        """Observed call paths in column order (dictionary mode only)."""
        return sorted(self.index, key=self.index.__getitem__)

    def endpoints(self) -> list[str]:
        """Root-level API endpoints (length-1 call paths) observed so far."""
        return [p[0] for p in self.vocabulary() if len(p) == 1]

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-safe state: config, column-ordered vocabulary, frozen width."""
        return {
            "config": dataclasses.asdict(self.config),
            "vocabulary": [list(p) for p in self.vocabulary()],
            "frozen_capacity": self.frozen_capacity,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CallPathSpace":
        space = cls(config=FeaturizeConfig(**d["config"]))
        space.index = {tuple(p): i for i, p in enumerate(d["vocabulary"])}
        space.frozen_capacity = d["frozen_capacity"]
        return space


# --------------------------------------------------------------------------
# Invocation counts (component-aware baseline input)


def count_invocations(traces: Sequence[Span]) -> dict[str, int]:
    """Per-component span counts in a bucket, plus ``general`` = #traces.

    (reference: resource-estimation/featurize.py:43-57)
    """
    counts: dict[str, int] = {"general": 0}
    for trace in traces:
        counts["general"] += 1
        for _, node in trace.walk():
            counts[node.component] = counts.get(node.component, 0) + 1
    return counts


@dataclasses.dataclass
class FeaturizedData:
    """The model-ready triple the reference pickles as ``input.pkl``
    (reference: resource-estimation/featurize.py:104-106)."""

    traffic: np.ndarray                    # [T, capacity] float32 path counts
    resources: dict[str, np.ndarray]       # metric key → [T] float32
    invocations: dict[str, np.ndarray]     # component → [T] float32
    space: CallPathSpace

    @property
    def metric_names(self) -> list[str]:
        return list(self.resources)

    def targets(self) -> np.ndarray:
        """[T, num_metrics] resource matrix in metric_names order."""
        return np.stack([self.resources[k] for k in self.metric_names], axis=-1)

    def save(self, path: str) -> str:
        """One-file ``.npz`` artifact — the typed replacement for the
        reference's ``input.pkl`` (reference: featurize.py:104-106), with
        the feature space included so downstream synthesis/serving stays
        column-compatible by construction.  Returns the actual path written
        (np.savez appends ``.npz`` when missing)."""
        import json

        if not path.endswith(".npz"):
            path += ".npz"
        np.savez_compressed(
            path,
            traffic=self.traffic,
            resource_names=np.array(self.metric_names),
            resource_values=self.targets(),
            invocation_names=np.array(list(self.invocations)),
            invocation_values=np.stack(
                [self.invocations[k] for k in self.invocations], axis=-1
            ) if self.invocations else np.zeros((len(self.traffic), 0)),
            space_json=np.frombuffer(
                json.dumps(self.space.to_dict()).encode(), dtype=np.uint8
            ),
        )
        return path

    @classmethod
    def load(cls, path: str) -> "FeaturizedData":
        import json
        import os

        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path += ".npz"
        with np.load(path, allow_pickle=False) as z:
            space = CallPathSpace.from_dict(
                json.loads(bytes(z["space_json"]).decode())
            )
            resources = {
                str(name): z["resource_values"][:, i].astype(np.float32)
                for i, name in enumerate(z["resource_names"])
            }
            invocations = {
                str(name): z["invocation_values"][:, i].astype(np.float32)
                for i, name in enumerate(z["invocation_names"])
            }
            return cls(traffic=z["traffic"].astype(np.float32),
                       resources=resources, invocations=invocations,
                       space=space)


# --------------------------------------------------------------------------
# Process-parallel featurization (corpus-scale ingest)
#
# Two-phase observe→merge→extract over contiguous bucket shards.  Phase 1
# (dictionary mode only): each worker walks its shard and returns the
# shard-local first-observed path order; merging shards IN ORDER reproduces
# the serial first-observed column order exactly — the reference's growth
# rule (featurize.py:14-15) — because a path's first global occurrence lies
# in the earliest shard containing it, and within that shard the worker
# preserved local first-observed order.  Phase 2: workers extract their
# shard's traffic rows and invocation counts against the merged (frozen)
# space.  Counts are integers, so the merged result is bit-identical to a
# serial run.
#
# Workers are forked AFTER the corpus (and, for phase 2, the merged space)
# are bound to module globals: fork inherits them copy-on-write, so the
# corpus is never pickled to the pool — only the small per-shard results
# travel back.

_POOL_BUCKETS: Sequence[Bucket] | None = None
_POOL_SPACE: CallPathSpace | None = None


def _observe_shard(span: tuple[int, int]) -> list[CallPath]:
    lo, hi = span
    seen: set[CallPath] = set()
    order: list[CallPath] = []
    for bucket in _POOL_BUCKETS[lo:hi]:
        for trace in bucket.traces:
            for path, _ in trace.walk():
                if path not in seen:
                    seen.add(path)
                    order.append(path)
    return order


def _extract_shard(span: tuple[int, int]) -> tuple[np.ndarray, list[dict[str, int]]]:
    lo, hi = span
    chunk = _POOL_BUCKETS[lo:hi]
    traffic = _POOL_SPACE.extract_buckets(chunk)
    return traffic, [count_invocations(b.traces) for b in chunk]


def _sparse_lines_shard(lines: Sequence[bytes]) -> list[tuple]:
    """One pool worker's slice of a bulk wire frame: raw bucket-JSONL
    lines → ``((cols, vals), metrics_row)`` per bucket, all through the
    Span-free dict walk.  The space rides the fork (``_POOL_SPACE``,
    copy-on-write) so only the lines travel in and the small sparse rows
    travel back; memo growth inside a worker is a private cache and
    never affects results (hash columns are pure functions)."""
    import json as _json

    space = _POOL_SPACE
    out = []
    for line in lines:
        d = _json.loads(line)
        parts = [space.trace_columns_from_dict(t)
                 for t in d.get("traces", ())]
        row = space.sparse_from_columns(parts)
        metrics = {f"{m['component']}_{m['resource']}": float(m["value"])
                   for m in d.get("metrics", ())}
        out.append((row, metrics))
    return out


def parallel_extract_sparse_lines(
    lines: Sequence[bytes], space: CallPathSpace, workers: int = 0,
    pool=None,
) -> list[tuple]:
    """Bulk sparse featurization of raw bucket-JSONL lines — the wire
    receiver's cold-start path sharded across the round-8 forked pool.

    ``pool`` may be a live ``multiprocessing`` fork pool whose workers
    were forked AFTER ``bind_pool_space(space)`` (the receiver keeps one
    for the whole plane lifetime — forking per frame would cost more
    than it shards).  Without one, falls back to the serial shard in
    this process.  Hash-mode spaces only for the pooled path: a
    dictionary-mode vocabulary may legally grow during extraction and
    workers cannot share that growth."""
    global _POOL_SPACE
    if pool is not None and space.config.hash_features and len(lines) > 1:
        w = max(1, workers)
        chunks = [lines[lo:hi] for lo, hi in _shard_spans(len(lines), w)]
        shard_results = pool.map(_sparse_lines_shard, chunks)
        return [r for shard in shard_results for r in shard]
    prev = _POOL_SPACE
    _POOL_SPACE = space
    try:
        return _sparse_lines_shard(lines)
    finally:
        _POOL_SPACE = prev


def bind_pool_space(space: CallPathSpace) -> None:
    """Bind the shared space for a long-lived fork pool (call BEFORE
    creating the pool so workers inherit it copy-on-write)."""
    global _POOL_SPACE
    space.freeze()
    _POOL_SPACE = space


def _shard_spans(n: int, workers: int) -> list[tuple[int, int]]:
    per = (n + workers - 1) // workers
    return [(lo, min(lo + per, n)) for lo in range(0, n, per)]


def resolve_workers(workers: int) -> int:
    """ETL worker-count knob semantics: 0 = one per CPU, 1 = serial."""
    if workers == 0:
        import os

        return os.cpu_count() or 1
    return max(1, workers)


def _parallel_featurize(
    buckets: Sequence[Bucket], space: CallPathSpace, workers: int,
) -> tuple[np.ndarray, list[dict[str, int]]] | None:
    """Sharded observe→merge→extract; None when parallelism is unavailable
    (no fork on this platform) so the caller falls back to serial."""
    import multiprocessing

    global _POOL_BUCKETS, _POOL_SPACE
    try:
        ctx = multiprocessing.get_context("fork")
    except ValueError:
        return None
    spans = _shard_spans(len(buckets), workers)
    _POOL_BUCKETS = buckets
    try:
        if not space.config.hash_features and space.frozen_capacity is None:
            with ctx.Pool(min(workers, len(spans))) as pool:
                shard_orders = pool.map(_observe_shard, spans)
            for order in shard_orders:          # in shard order: serial-exact
                for path in order:
                    if path not in space.index:
                        space.index[path] = len(space.index)
        space.freeze()
        _POOL_SPACE = space
        with ctx.Pool(min(workers, len(spans))) as pool:
            shard_results = pool.map(_extract_shard, spans)
    finally:
        _POOL_BUCKETS = None
        _POOL_SPACE = None
    traffic = np.vstack([r[0] for r in shard_results])
    invocations = [c for r in shard_results for c in r[1]]
    return traffic, invocations


def featurize_buckets(
    buckets: Sequence[Bucket],
    config: FeaturizeConfig | None = None,
    space: CallPathSpace | None = None,
    workers: int = 1,
) -> FeaturizedData:
    """Full-corpus featurization: traffic, resources, invocation counts.

    ``workers`` shards the trace-walking work (observe + extract +
    invocation counts) across a forked process pool: 1 = serial, 0 = one
    worker per CPU.  Results are bit-identical to serial in both modes
    (see _parallel_featurize).  Metric-series assembly stays in the parent
    — it walks no traces and its validation is order-dependent.
    """
    config = config or FeaturizeConfig()
    if space is None:
        space = CallPathSpace(config=config)

    workers = resolve_workers(workers)
    per_bucket_counts: list[dict[str, int]] | None = None
    traffic: np.ndarray | None = None
    # Parallelism only pays once walking dominates the fork+merge overhead.
    if workers > 1 and len(buckets) >= 4 * workers:
        parallel = _parallel_featurize(buckets, space, workers)
        if parallel is not None:
            traffic, per_bucket_counts = parallel

    if traffic is None:
        # Observe before extracting (no-op in hash mode): a caller-provided
        # fresh space would otherwise freeze at minimum capacity and silently
        # drop every path.  Already-frozen spaces are left untouched — novel
        # eval-corpus paths could never be addressed anyway, and growing the
        # index across serve-time calls would leak memory.
        if space.frozen_capacity is None:
            space.observe(buckets)
        traffic = space.extract_buckets(buckets)

    # Resource series must stay time-aligned with traffic: every bucket has to
    # carry exactly the metric keys of the union, or series would silently
    # shift against the traffic rows.
    resources: dict[str, list[float]] = {}
    expected_keys: set[str] | None = None
    for t, bucket in enumerate(buckets):
        seen: set[str] = set()
        for m in bucket.metrics:
            if m.key in seen:
                raise ValueError(f"bucket {t}: duplicate metric {m.key!r}")
            seen.add(m.key)
            resources.setdefault(m.key, []).append(m.value)
        if expected_keys is None:
            expected_keys = seen
        elif seen != expected_keys:
            missing, extra = expected_keys - seen, seen - expected_keys
            raise ValueError(
                f"bucket {t}: metric keys diverge from bucket 0 "
                f"(missing={sorted(missing)}, new={sorted(extra)}); every "
                "bucket must carry the same metrics or series misalign"
            )

    if per_bucket_counts is None:
        per_bucket_counts = [count_invocations(b.traces) for b in buckets]
    components = {c for counts in per_bucket_counts for c in counts}
    invocations: dict[str, list[float]] = {c: [] for c in components | {"general"}}
    for c in per_bucket_counts:
        for comp in invocations:
            invocations[comp].append(float(c.get(comp, 0)))

    return FeaturizedData(
        traffic=traffic,
        resources={k: np.asarray(v, dtype=np.float32) for k, v in resources.items()},
        invocations={k: np.asarray(v, dtype=np.float32) for k, v in invocations.items()},
        space=space,
    )
