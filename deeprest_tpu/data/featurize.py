"""Call-path featurization: span trees → fixed-width count vectors.

Semantics follow the reference's feature construction (reference:
resource-estimation/featurize.py:11-57): every root-to-node *call path*
observed in any trace becomes one feature dimension, and a bucket's feature
vector counts how many times each path occurs across the bucket's traces.
Per-component invocation counts (plus a synthetic ``general`` stream counting
whole traces) feed the component-aware baseline.

TPU-first departures from the reference:

- **Static width.**  The raw space is unbounded; XLA wants static shapes.
  Vectors are materialized at a fixed ``capacity`` (rounded up to an MXU-lane
  multiple) so a growing vocabulary never changes array shapes mid-run.
- **Hash-bucketing mode.**  For streaming/10k-endpoint corpora the dictionary
  is replaced by a seeded FNV-1a hash of the call path into ``capacity``
  buckets: no global vocabulary pass, no recompile, multi-host and
  cross-language consistent (native/featurizer.cpp implements the same
  function).
- **Streaming API.**  ``observe``/``extract`` work bucket-at-a-time so the
  continuous-retrain mode can featurize a live firehose.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from deeprest_tpu.config import FeaturizeConfig
from deeprest_tpu.data.schema import Bucket, Span

CallPath = tuple[str, ...]


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_SEED_MIX = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _stable_hash(path: CallPath, seed: int) -> int:
    """Seeded FNV-1a over the \\x1f-joined call path.

    Deliberately simple: the native C++ featurizer (native/featurizer.cpp)
    implements the identical function so hash-mode columns are consistent
    across languages and hosts.
    """
    h = _FNV_OFFSET ^ ((seed * _SEED_MIX) & _MASK64)
    for b in "\x1f".join(path).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


@dataclasses.dataclass
class CallPathSpace:
    """The feature space M: call path → column index.

    In dictionary mode indices are assigned in first-observed order, matching
    the reference's growth rule (reference: resource-estimation/
    featurize.py:14-15) so vocabularies are reproducible for a fixed corpus
    order.  In hash mode indices are ``stable_hash(path) % capacity`` and the
    space never needs fitting.
    """

    config: FeaturizeConfig = dataclasses.field(default_factory=FeaturizeConfig)
    index: dict[CallPath, int] = dataclasses.field(default_factory=dict)
    # Set on first extract (or explicit freeze()); afterwards the vector
    # width never changes even if the vocabulary keeps growing.
    frozen_capacity: int | None = None

    # -- construction ------------------------------------------------------

    def observe(self, buckets_or_traces: Iterable[Bucket] | Iterable[Span]) -> "CallPathSpace":
        """Grow the vocabulary from buckets (or bare traces). No-op in hash mode."""
        if self.config.hash_features:
            return self
        for item in buckets_or_traces:
            traces = item.traces if isinstance(item, Bucket) else [item]
            for trace in traces:
                for path, _ in trace.walk():
                    if path not in self.index:
                        self.index[path] = len(self.index)
        return self

    @classmethod
    def fit(cls, buckets: Iterable[Bucket], config: FeaturizeConfig | None = None) -> "CallPathSpace":
        return cls(config=config or FeaturizeConfig()).observe(buckets)

    # -- geometry ----------------------------------------------------------

    @property
    def num_observed(self) -> int:
        return len(self.index)

    @property
    def capacity(self) -> int:
        """Static feature-vector width (the model's input dimension).

        Frozen at the first extraction so a vocabulary that keeps growing
        can never change array shapes mid-run (it overflows instead).
        """
        if self.frozen_capacity is not None:
            return self.frozen_capacity
        cfg = self.config
        if cfg.capacity > 0:
            return cfg.capacity
        return _round_up(max(self.num_observed, 1), cfg.round_to)

    def freeze(self) -> "CallPathSpace":
        """Pin the current capacity as the permanent vector width."""
        if self.frozen_capacity is None:
            self.frozen_capacity = self.capacity
        return self

    def column_of(self, path: CallPath) -> int | None:
        if self.config.hash_features:
            return _stable_hash(path, self.config.hash_seed) % self.capacity
        idx = self.index.get(path)
        if idx is None or idx >= self.capacity:
            return None
        return idx

    # -- extraction --------------------------------------------------------

    def extract(self, traces: Sequence[Span], out: np.ndarray | None = None) -> np.ndarray:
        """Count each call path across ``traces`` into a [capacity] vector.

        Freezes the capacity on first call.  A caller-supplied ``out`` buffer
        is zeroed first (counts are per-call, never cumulative).  Paths beyond
        a fixed ``capacity`` in dictionary mode are dropped (counted into
        nothing) — the documented overflow policy; size the capacity or switch
        to hashing to avoid it.
        """
        self.freeze()
        if out is not None:
            out[:] = 0.0
            x = out
        else:
            x = np.zeros((self.capacity,), dtype=np.float32)
        for trace in traces:
            for path, _ in trace.walk():
                col = self.column_of(path)
                if col is not None:
                    x[col] += 1.0
        return x

    def extract_buckets(self, buckets: Sequence[Bucket]) -> np.ndarray:
        """[num_buckets, capacity] traffic matrix."""
        self.freeze()
        out = np.zeros((len(buckets), self.capacity), dtype=np.float32)
        for t, bucket in enumerate(buckets):
            self.extract(bucket.traces, out=out[t])
        return out

    # -- introspection -----------------------------------------------------

    def vocabulary(self) -> list[CallPath]:
        """Observed call paths in column order (dictionary mode only)."""
        return sorted(self.index, key=self.index.__getitem__)

    def endpoints(self) -> list[str]:
        """Root-level API endpoints (length-1 call paths) observed so far."""
        return [p[0] for p in self.vocabulary() if len(p) == 1]

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-safe state: config, column-ordered vocabulary, frozen width."""
        return {
            "config": dataclasses.asdict(self.config),
            "vocabulary": [list(p) for p in self.vocabulary()],
            "frozen_capacity": self.frozen_capacity,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CallPathSpace":
        space = cls(config=FeaturizeConfig(**d["config"]))
        space.index = {tuple(p): i for i, p in enumerate(d["vocabulary"])}
        space.frozen_capacity = d["frozen_capacity"]
        return space


# --------------------------------------------------------------------------
# Invocation counts (component-aware baseline input)


def count_invocations(traces: Sequence[Span]) -> dict[str, int]:
    """Per-component span counts in a bucket, plus ``general`` = #traces.

    (reference: resource-estimation/featurize.py:43-57)
    """
    counts: dict[str, int] = {"general": 0}
    for trace in traces:
        counts["general"] += 1
        for _, node in trace.walk():
            counts[node.component] = counts.get(node.component, 0) + 1
    return counts


@dataclasses.dataclass
class FeaturizedData:
    """The model-ready triple the reference pickles as ``input.pkl``
    (reference: resource-estimation/featurize.py:104-106)."""

    traffic: np.ndarray                    # [T, capacity] float32 path counts
    resources: dict[str, np.ndarray]       # metric key → [T] float32
    invocations: dict[str, np.ndarray]     # component → [T] float32
    space: CallPathSpace

    @property
    def metric_names(self) -> list[str]:
        return list(self.resources)

    def targets(self) -> np.ndarray:
        """[T, num_metrics] resource matrix in metric_names order."""
        return np.stack([self.resources[k] for k in self.metric_names], axis=-1)

    def save(self, path: str) -> str:
        """One-file ``.npz`` artifact — the typed replacement for the
        reference's ``input.pkl`` (reference: featurize.py:104-106), with
        the feature space included so downstream synthesis/serving stays
        column-compatible by construction.  Returns the actual path written
        (np.savez appends ``.npz`` when missing)."""
        import json

        if not path.endswith(".npz"):
            path += ".npz"
        np.savez_compressed(
            path,
            traffic=self.traffic,
            resource_names=np.array(self.metric_names),
            resource_values=self.targets(),
            invocation_names=np.array(list(self.invocations)),
            invocation_values=np.stack(
                [self.invocations[k] for k in self.invocations], axis=-1
            ) if self.invocations else np.zeros((len(self.traffic), 0)),
            space_json=np.frombuffer(
                json.dumps(self.space.to_dict()).encode(), dtype=np.uint8
            ),
        )
        return path

    @classmethod
    def load(cls, path: str) -> "FeaturizedData":
        import json
        import os

        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path += ".npz"
        with np.load(path, allow_pickle=False) as z:
            space = CallPathSpace.from_dict(
                json.loads(bytes(z["space_json"]).decode())
            )
            resources = {
                str(name): z["resource_values"][:, i].astype(np.float32)
                for i, name in enumerate(z["resource_names"])
            }
            invocations = {
                str(name): z["invocation_values"][:, i].astype(np.float32)
                for i, name in enumerate(z["invocation_names"])
            }
            return cls(traffic=z["traffic"].astype(np.float32),
                       resources=resources, invocations=invocations,
                       space=space)


def featurize_buckets(
    buckets: Sequence[Bucket],
    config: FeaturizeConfig | None = None,
    space: CallPathSpace | None = None,
) -> FeaturizedData:
    """Full-corpus featurization: traffic, resources, invocation counts."""
    config = config or FeaturizeConfig()
    if space is None:
        space = CallPathSpace(config=config)
    # Observe before extracting (no-op in hash mode): a caller-provided
    # fresh space would otherwise freeze at minimum capacity and silently
    # drop every path.  Already-frozen spaces are left untouched — novel
    # eval-corpus paths could never be addressed anyway, and growing the
    # index across serve-time calls would leak memory.
    if space.frozen_capacity is None:
        space.observe(buckets)

    traffic = space.extract_buckets(buckets)

    # Resource series must stay time-aligned with traffic: every bucket has to
    # carry exactly the metric keys of the union, or series would silently
    # shift against the traffic rows.
    resources: dict[str, list[float]] = {}
    expected_keys: set[str] | None = None
    for t, bucket in enumerate(buckets):
        seen: set[str] = set()
        for m in bucket.metrics:
            if m.key in seen:
                raise ValueError(f"bucket {t}: duplicate metric {m.key!r}")
            seen.add(m.key)
            resources.setdefault(m.key, []).append(m.value)
        if expected_keys is None:
            expected_keys = seen
        elif seen != expected_keys:
            missing, extra = expected_keys - seen, seen - expected_keys
            raise ValueError(
                f"bucket {t}: metric keys diverge from bucket 0 "
                f"(missing={sorted(missing)}, new={sorted(extra)}); every "
                "bucket must carry the same metrics or series misalign"
            )

    per_bucket_counts = [count_invocations(b.traces) for b in buckets]
    components = {c for counts in per_bucket_counts for c in counts}
    invocations: dict[str, list[float]] = {c: [] for c in components | {"general"}}
    for c in per_bucket_counts:
        for comp in invocations:
            invocations[comp].append(float(c.get(comp, 0)))

    return FeaturizedData(
        traffic=traffic,
        resources={k: np.asarray(v, dtype=np.float32) for k, v in resources.items()},
        invocations={k: np.asarray(v, dtype=np.float32) for k, v in invocations.items()},
        space=space,
    )
