"""Span firehose: push-based wire ingestion (round 24).

Every corpus so far entered the plane by PULL — file tailers and the
5s-scrape live pollers (the reference's batch design; PAPER.md L0/L1).
This module is the PUSH half of ROADMAP item 4: a threaded socket
receiver that takes length-prefixed span batches from many producers and
lands them in the sparse streaming corpus at wire speed, with Clipper-
style bounded-queue admission at the edge (drop + count, never buffer
unboundedly).

Protocol
--------
Every frame is a fixed 16-byte header (``struct '!BBHIQ'``: magic 0xD7,
frame type, flags, payload length, sequence number) followed by the
payload.  The header is unpacked once per frame with a precompiled
Struct — the hot loop never re-scans bytes to find frame boundaries.

Frame types::

    HELLO     c->s  JSON {"client": id}; opens the dedup window
    WELCOME   s->c  JSON {"watermark": seq}; highest seq COMMITTED for
                    this client id — the client prunes/replays against it
    BATCH     c->s  one bucket (sub-framed payload, below); seq is the
                    client's monotone batch sequence
    ACK       s->c  seq = highest committed sequence (advances when the
                    drained rows LAND IN THE RING — commit — not at
                    receipt, and not at drain: see "Commit" below)
    SLOWDOWN  s->c  JSON {"inflight": n, "limit": n} — explicit
                    backpressure; compliant clients pause
    DROPPED   s->c  JSON {"seqs": [..], "count": n} — exactly the
                    sequence numbers fast-dropped under overload (or
                    malformed); the client prunes those and ONLY those
                    (load shed with accounting, never a silent stall —
                    accepted-but-unACKed frames stay replayable)
    BYE       either direction, clean close

BATCH payload (Jaeger-shape JSON inside binary sub-framing)::

    u32 metrics_len | metrics JSON | u32 n | (u32 len | trace JSON) * n

Each trace blob is one span tree in the raw-corpus JSON shape
(``{"component", "operation", "children"}`` — no timestamps), so a call
tree that repeats serializes to byte-identical blobs.  The receiver
exploits that: a bounded ``bytes -> column array`` memo means a repeated
tree costs one dict lookup instead of ``json.loads`` + a span walk +
per-path hashing.  Cache misses decode through
``CallPathSpace.trace_columns_from_dict`` (the Span-free dict walk) and
``sparse_from_columns`` — the same memoized hash path as the tailer, so
wire-fed training is bit-identical to tailer-fed training
(tests/test_wire.py pins it).  No dense ``[., F]`` vector exists
anywhere on this path (DN001/DN002 stay silent).

A BATCH frame with ``FLAG_JSONL`` instead carries raw bucket-JSONL lines
(one bucket per line) — the cold-start bulk shape that lets a producer
replay an existing corpus file without re-encoding; those shards across
the round-8 forked featurize pool
(``featurize.parallel_extract_sparse_lines``).

Backpressure ladder (per connection, Clipper's bounded-queue discipline)
-----------------------------------------------------------------------
``inflight`` = frames featurized but not yet drained by the train
thread.  Below ``queue_depth``: accept.  At ``queue_depth``: accept but
send SLOWDOWN.  At ``hard_limit`` (or a full global buffer): fast-drop
the frame — count it, notify the producer with DROPPED, never decode it.
A producer that stays in the drop band for ``evict_after`` consecutive
frames is a slow consumer of our control frames and is evicted
(connection closed, counted) so it cannot monopolize the buffer other
connections share.

Commit: ACK means "in the ring", on every consumer shape
--------------------------------------------------------
The per-client watermark (what WELCOME reports, what ACK advances,
what the sidecar persists) must never run ahead of the ring, or a
kill+resume loses the gap: the client pruned on ACK, and the resumed
watermark says the frames are already ingested.  ``poll()`` drains AND
commits in one call — correct whenever the caller ingests the items on
the same thread before anything can observe the watermark (the serial
train loop, the VerdictIngestor).  A consumer that hands drained items
to ANOTHER thread (the overlapped ETL loop, where rows wait in a
bounded queue before ``_ingest_featurized``) must instead use
``poll_deferred()`` → ``(items, token)`` and call ``commit(token)``
only after the rows land — the stream's overlapped loop threads the
token through its ETL buffer and commits post-ingest, so a checkpoint
cut between drain and ingest can never persist a watermark covering
frames that are not in the ring.

Watermark convention (shared with data/ingest.LiveEndpointTailer)
-----------------------------------------------------------------
``ingest_watermark()`` returns a JSON-safe dict tagged by ``kind``;
``resume_from(wm)`` adopts one.  The stream persists the active source's
watermark inside the round-17 checkpoint/snapshot sidecar
(``stream_ring_watermark["source"]``) and hands it back on resume, so a
restarted stream deduplicates replayed frames (wire: per-client
committed seq) or re-anchors its poll cursor (live tailer: time cursor)
instead of double-counting spans.

Hot-loop discipline: graftlint WR001 (analysis/rules_wire.py) keeps
per-frame receive loops in wire modules free of file/console I/O,
whole-connection-buffer ``json.loads``, and unbounded appends.
"""

from __future__ import annotations

import json
import select
import socket
import struct
import threading
import time
from collections import deque
from typing import Sequence

import numpy as np

from deeprest_tpu.data.schema import Bucket
from deeprest_tpu.obs import metrics as obs_metrics

MAGIC = 0xD7
_HEADER = struct.Struct("!BBHIQ")   # magic, type, flags, payload len, seq
HEADER_SIZE = _HEADER.size          # 16 bytes
_U32 = struct.Struct("!I")

F_HELLO = 1
F_WELCOME = 2
F_BATCH = 3
F_ACK = 4
F_SLOWDOWN = 5
F_DROPPED = 6
F_BYE = 7

FLAG_JSONL = 0x1    # BATCH payload is raw bucket-JSONL lines (bulk)

# Same ceiling as train/stream.BucketTailer.MAX_POLL_BYTES: one frame can
# never force an unbounded allocation.
MAX_FRAME_BYTES = 64 << 20


def parse_hostport(spec: str) -> tuple[str, int]:
    """``HOST:PORT`` → tuple (the --wire-listen argument shape)."""
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bad wire address {spec!r}: want HOST:PORT")
    return (host or "127.0.0.1", int(port))


def pack_frame(ftype: int, payload: bytes = b"", seq: int = 0,
               flags: int = 0) -> bytes:
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame payload {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES {MAX_FRAME_BYTES}")
    return _HEADER.pack(MAGIC, ftype, flags, len(payload), seq) + payload


def encode_bucket_payload(bucket) -> bytes:
    """One bucket → the sub-framed BATCH payload.

    Accepts a :class:`Bucket` or its raw dict.  Trace blobs are
    serialized individually (compact separators) so identical call trees
    produce identical bytes — the receiver's blob memo keys on exactly
    these bytes.
    """
    d = bucket.to_dict() if isinstance(bucket, Bucket) else bucket
    head = json.dumps(d.get("metrics", []),
                      separators=(",", ":")).encode("utf-8")
    blobs = [json.dumps(t, separators=(",", ":")).encode("utf-8")
             for t in d.get("traces", ())]
    parts = [_U32.pack(len(head)), head, _U32.pack(len(blobs))]
    for b in blobs:
        parts.append(_U32.pack(len(b)))
        parts.append(b)
    return b"".join(parts)


_FILLED, _EOF, _IDLE = 1, 0, -1


def _recv_exact(sock: socket.socket, view: memoryview, *,
                idle_ok: bool = False) -> int:
    """Fill ``view`` exactly from ``sock`` via ``recv_into`` (no
    intermediate bytes objects).  Returns ``_FILLED``, ``_EOF`` (clean
    close before any byte), or ``_IDLE`` (timeout before any byte, only
    with ``idle_ok``); raises ConnectionError on EOF mid-buffer.  A
    timeout mid-buffer keeps waiting — a closed socket breaks it."""
    got = 0
    n = len(view)
    while got < n:
        try:
            k = sock.recv_into(view[got:])
        except socket.timeout:
            if got == 0:
                if idle_ok:
                    return _IDLE
                raise
            continue
        if k == 0:
            if got == 0:
                return _EOF
            raise ConnectionError("wire: EOF mid-frame")
        got += k
    return _FILLED


# ---------------------------------------------------------------------------
# Receiver


class _Conn:
    """Per-connection accounting.  ``enqueued`` is written only by the
    handler thread and ``drained`` only by the committing (train) thread
    — two single-writer monotone counters, so ``inflight`` needs no lock
    and a stale read only ever delays backpressure by one frame.
    ``inflight`` covers enqueued-but-uncommitted frames: in overlapped
    mode that includes rows still waiting in the ETL buffer, so the
    admission window is end-to-end, not just receiver-internal."""

    __slots__ = ("sock", "addr", "client_id", "enqueued", "drained",
                 "acked_sent", "drop_streak", "dropped_pending", "alive")

    def __init__(self, sock: socket.socket, addr) -> None:
        self.sock = sock
        self.addr = addr
        self.client_id = f"{addr[0]}:{addr[1]}"
        self.enqueued = 0
        self.drained = 0
        self.acked_sent = -1
        self.drop_streak = 0
        # seqs shed (overload or malformed) but not yet announced via a
        # DROPPED frame; bounded by the notice cadence in _on_batch and
        # flushed by _flush_acks on the next idle tick
        self.dropped_pending: list[int] = []
        self.alive = True

    @property
    def inflight(self) -> int:
        return self.enqueued - self.drained


class SpanFirehoseReceiver:
    """Threaded push receiver implementing the stream-source (tailer)
    protocol: ``poll()``/``backlog``/``dropped``/``close()`` plus the
    round-24 watermark convention, so ``StreamingTrainer.run`` and the
    serve plane's VerdictIngestor consume it unchanged — and the
    deferred-commit extension (``poll_deferred()``/``commit()``) the
    overlapped ETL loop uses so the watermark only ever covers rows
    that are actually in the ring.

    With ``space`` bound the receiver featurizes on its connection
    threads (``featurized = True``: ``poll()`` yields the same
    ``(row, metrics_row)`` tuples ``StreamingTrainer._featurize``
    produces, rows sparse ``(cols, vals)`` pairs).  Without a space it
    yields :class:`Bucket` objects (``featurized = False``) — the
    verdict-ingestor mode.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 space=None, sparse: bool = True,
                 queue_depth: int = 256,
                 hard_limit: int | None = None,
                 evict_after: int | None = None,
                 max_buffered: int = 4096,
                 trace_cache_entries: int = 65536,
                 fork_workers: int = 1,
                 idle_timeout_s: float = 0.2) -> None:
        if space is not None and not sparse:
            raise ValueError(
                "wire ingestion is sparse-first by design: a dense "
                "[., F] row per frame is exactly the allocation "
                "DN001/DN002 exist to keep off this path — run the "
                "stream with the sparse feed (the default) or use the "
                "file tailer")
        self._host, self._port = host, port
        self._space = space
        self._sparse = sparse
        self.queue_depth = max(1, queue_depth)
        self.hard_limit = hard_limit or 2 * self.queue_depth
        self.evict_after = evict_after or 4 * self.queue_depth
        self.max_buffered = max_buffered
        self._idle_s = idle_timeout_s
        # items: (conn, seq, t_featurized, payload)
        self._out: deque = deque()
        self._conns: list[_Conn] = []
        self._conns_lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._listener: threading.Thread | None = None
        self._lsock: socket.socket | None = None
        self._stop = threading.Event()
        # committed seq per client id — the dedup floor WELCOME reports
        # and resume_from() restores.  Written by the committing thread
        # (poll()'s caller, or commit()'s in deferred mode), read by
        # handler threads (GIL-atomic dict ops; a stale read only delays
        # dedup of an already-counted frame by one poll).
        self._committed: dict[str, int] = {}
        # drained-but-uncommitted batches: (token, [(conn, seq, t_enq)]).
        # poll_deferred() appends, commit() pops — the window a kill may
        # strike without losing anything, because nothing in here has
        # been ACKed or counted into the watermark yet.
        self._commit_lock = threading.Lock()
        self._commit_token = 0
        self._uncommitted: deque = deque()
        # highest ENQUEUED seq per client id: dedups a reconnect replay
        # of frames that are already in the buffer but not yet drained
        # (committed alone would admit them twice)
        self._seen: dict[str, int] = {}
        # bounded trace-blob memo: bytes -> int32 column array.  Hash
        # mode only — a dictionary-mode vocabulary may still grow, which
        # would invalidate cached (dropped-path) entries.
        self._blob_memo: dict[bytes, np.ndarray] | None = None
        if space is not None and space.config.hash_features:
            self._blob_memo = {}
        self._blob_cap = max(1024, trace_cache_entries)
        # round-8 forked featurize pool for FLAG_JSONL bulk frames;
        # created lazily at start() when workers > 1 (serial fallback
        # otherwise — on a 1-core host the fork buys nothing).
        self._fork_workers = fork_workers
        self._pool = None
        # shared totals: multiple handler threads += these, so they live
        # behind _stats_lock — one uncontended acquire per FRAME (never
        # per span/trace: decode accumulates locally and flushes once).
        # Registry export stays delta-flushed from poll().
        self._stats_lock = threading.Lock()
        self.spans_total = 0
        self.batches_total = 0
        self.dropped_total = 0
        self.backpressure_total = 0
        self.duplicates_total = 0
        self.evictions_total = 0
        self.malformed_total = 0
        self.memo_hits = 0
        self.memo_misses = 0
        self._obs_flushed = {"spans": 0, "batches": 0, "dropped": 0,
                             "backpressure": 0}
        self._lat = deque(maxlen=8192)   # drain-time ingest→ring latency
        self._hist = obs_metrics.REGISTRY.histogram(
            "deeprest_wire_ingest_seconds",
            "wire frame featurized → drained into the ring",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "SpanFirehoseReceiver":
        ls = socket.create_server((self._host, self._port))
        ls.settimeout(self._idle_s)
        self._lsock = ls
        self._host, self._port = ls.getsockname()[:2]
        if self._space is not None:
            self._space.freeze()
        workers = max(1, self._fork_workers)
        if (workers > 1 and self._space is not None
                and self._space.config.hash_features):
            import multiprocessing

            from deeprest_tpu.data.featurize import bind_pool_space

            try:
                ctx = multiprocessing.get_context("fork")
                bind_pool_space(self._space)
                with self._stats_lock:
                    self._pool = ctx.Pool(workers)
            except ValueError:
                with self._stats_lock:
                    self._pool = None   # no fork on this platform: serial
        self._listener = threading.Thread(
            target=self._accept_loop, args=(ls,),
            name="deeprest-wire-accept", daemon=True)
        self._listener.start()
        return self

    @property
    def address(self) -> tuple[str, int]:
        return (self._host, self._port)

    @property
    def featurized(self) -> bool:
        return self._space is not None

    def close(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.join(timeout=5.0)
        with self._conns_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5.0)
        with self._stats_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.close()
            pool.join()
        self._flush_obs()

    # -- accept / per-connection handler -------------------------------

    def _accept_loop(self, lsock: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return                       # listener closed
            sock.settimeout(self._idle_s)
            conn = _Conn(sock, addr)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name=f"deeprest-wire-{addr[1]}",
                                 daemon=True)
            with self._conns_lock:
                self._conns.append(conn)
                # prune finished handlers so a long-lived plane's thread
                # ledger stays O(open connections), not O(ever connected)
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
            t.start()
            obs_metrics.REGISTRY.gauge(
                "deeprest_wire_connections",
                "open wire ingest connections").set(self.connections)

    @property
    def connections(self) -> int:
        with self._conns_lock:
            return sum(1 for c in self._conns if c.alive)

    def _serve_conn(self, conn: _Conn) -> None:
        sock = conn.sock
        hdr = bytearray(HEADER_SIZE)
        hdr_view = memoryview(hdr)
        buf = bytearray(1 << 16)
        try:
            while not self._stop.is_set() and conn.alive:
                st = _recv_exact(sock, hdr_view, idle_ok=True)
                if st == _IDLE:
                    self._flush_acks(conn)
                    continue
                if st == _EOF:
                    return
                magic, ftype, flags, length, seq = _HEADER.unpack(hdr)
                if magic != MAGIC or length > MAX_FRAME_BYTES:
                    with self._stats_lock:
                        self.malformed_total += 1
                    return                   # desynced stream: drop conn
                if length > len(buf):
                    buf = bytearray(length)
                payload = memoryview(buf)[:length]
                if length and _recv_exact(sock, payload) != _FILLED:
                    return
                if ftype == F_BATCH:
                    self._on_batch(conn, flags, seq, payload)
                    self._flush_acks(conn)
                elif ftype == F_HELLO:
                    self._on_hello(conn, payload)
                elif ftype == F_BYE:
                    self._flush_acks(conn)
                    return
                # unknown frame types are skipped (forward compatibility)
        except (ConnectionError, OSError):
            pass                             # producer vanished: clean up
        finally:
            self._retire(conn)

    def _on_hello(self, conn: _Conn, payload: memoryview) -> None:
        try:
            meta = json.loads(bytes(payload)) if len(payload) else {}
            cid = str(meta.get("client") or conn.client_id)
        except (ValueError, TypeError):
            with self._stats_lock:
                self.malformed_total += 1
            cid = conn.client_id
        conn.client_id = cid
        wm = self._committed.get(cid, 0)
        self._send(conn, pack_frame(
            F_WELCOME, json.dumps({"watermark": wm}).encode("utf-8")))

    def _on_batch(self, conn: _Conn, flags: int, seq: int,
                  payload: memoryview) -> None:
        cid = conn.client_id
        if seq <= max(self._committed.get(cid, 0), self._seen.get(cid, 0)):
            # replay of a frame that is already committed OR already in
            # the buffer (client reconnected before our ACK landed):
            # dedup, never double-count
            with self._stats_lock:
                self.duplicates_total += 1
            return
        inflight = conn.inflight
        if inflight >= self.hard_limit or len(self._out) >= self.max_buffered:
            # Clipper admission: shed with accounting, notify producer.
            # The DROPPED notice names the EXACT seqs shed — a range
            # would also cover accepted-but-unACKed frames below it,
            # and a client pruning those loses them on a receiver kill.
            with self._stats_lock:
                self.dropped_total += 1
            conn.drop_streak += 1
            conn.dropped_pending.append(seq)
            if conn.drop_streak == 1 or conn.drop_streak % 64 == 0:
                self._flush_dropped(conn)
            if conn.drop_streak >= self.evict_after:
                self._evict(conn)
            return
        if inflight >= self.queue_depth and (
                inflight == self.queue_depth or conn.enqueued % 64 == 0):
            with self._stats_lock:
                self.backpressure_total += 1
            self._send(conn, pack_frame(F_SLOWDOWN, json.dumps(
                {"inflight": inflight,
                 "limit": self.queue_depth}).encode("utf-8")))
        try:
            item, nspans = (self._decode_jsonl(payload)
                            if flags & FLAG_JSONL
                            else self._decode_bucket(payload))
        except (ValueError, KeyError, TypeError, struct.error):
            # counted ONCE: the dropped/stats aggregates already add
            # malformed_total, so bumping dropped_total too would count
            # this frame twice in the accounting identity.  The seq is
            # still announced as shed so the client can prune it.
            with self._stats_lock:
                self.malformed_total += 1
            conn.dropped_pending.append(seq)
            self._flush_dropped(conn)
            return
        with self._stats_lock:
            self.batches_total += 1
            self.spans_total += nspans
        conn.drop_streak = 0
        if seq > self._seen.get(cid, 0):
            self._seen[cid] = seq
        # a bulk (FLAG_JSONL) frame's buckets ride as ONE list item under
        # ONE sequence number — drained atomically, so a kill can never
        # half-apply it
        self._out.append((conn, seq, time.monotonic(), item))
        conn.enqueued += 1

    def _decode_bucket(self, payload: memoryview):
        """Sub-framed BATCH payload → one poll item.  The per-trace blob
        memo is the wire fast path: a repeated call tree costs a bytes
        hash + dict hit instead of json parse + walk + per-path FNV."""
        (mlen,) = _U32.unpack_from(payload, 0)
        off = 4 + mlen
        metrics = json.loads(bytes(payload[4:off]))
        (ntr,) = _U32.unpack_from(payload, off)
        off += 4
        space = self._space
        memo = self._blob_memo
        nspans = 0
        if space is None:
            # bucket mode (VerdictIngestor): decode to schema objects
            traces = []
            for _ in range(ntr):
                (blen,) = _U32.unpack_from(payload, off)
                off += 4
                d = json.loads(bytes(payload[off:off + blen]))
                off += blen
                traces.append(d)
            bucket = Bucket.from_dict({"metrics": metrics,
                                       "traces": traces})
            nspans = sum(1 for t in bucket.traces for _ in t.walk())
            return bucket, nspans
        parts = []
        hits = misses = 0      # flushed once per frame, never per trace
        for _ in range(ntr):
            (blen,) = _U32.unpack_from(payload, off)
            off += 4
            blob = bytes(payload[off:off + blen])
            off += blen
            cols = memo.get(blob) if memo is not None else None
            if cols is None:
                misses += 1
                cols = space.trace_columns_from_dict(json.loads(blob))
                if memo is not None:
                    if len(memo) >= self._blob_cap:
                        memo.clear()     # bounded: full reset beats LRU
                    memo[blob] = cols
            else:
                hits += 1
            nspans += len(cols)
            parts.append(cols)
        with self._stats_lock:
            self.memo_hits += hits
            self.memo_misses += misses
        row = space.sparse_from_columns(parts)
        metrics_row = {f"{m['component']}_{m['resource']}": float(m["value"])
                       for m in metrics}
        return (row, metrics_row), nspans

    def _decode_jsonl(self, payload: memoryview):
        """FLAG_JSONL bulk frame: bucket-JSONL lines sharded across the
        round-8 forked featurize pool (serial fallback in-process)."""
        lines = [ln for ln in bytes(payload).split(b"\n") if ln]
        if self._space is None:
            buckets = [Bucket.from_dict(json.loads(ln)) for ln in lines]
            nspans = sum(1 for b in buckets
                         for t in b.traces for _ in t.walk())
            return buckets, nspans
        from deeprest_tpu.data.featurize import parallel_extract_sparse_lines

        with self._stats_lock:
            pool = self._pool
        feats = parallel_extract_sparse_lines(
            lines, self._space, workers=max(1, self._fork_workers),
            pool=pool)
        nspans = int(sum(f[0][1].sum() for f in feats))
        return feats, nspans

    def _flush_acks(self, conn: _Conn) -> None:
        """Push the committed watermark (and any unannounced shed seqs)
        back to the producer.  Commit advances when the drained rows
        LAND IN THE RING (poll() for same-thread consumers, commit() in
        deferred mode) — an ACK is a promise the spans reached the
        ring, not just a socket or an ETL queue."""
        wm = self._committed.get(conn.client_id, 0)
        if wm > conn.acked_sent:
            conn.acked_sent = wm
            self._send(conn, pack_frame(F_ACK, seq=wm))
        if conn.dropped_pending:
            self._flush_dropped(conn)

    def _flush_dropped(self, conn: _Conn) -> None:
        """Announce the exact shed seqs accumulated since the last
        notice (bounded by the notice cadence, ≤ 64 between sends)."""
        seqs, conn.dropped_pending = conn.dropped_pending, []
        self._send(conn, pack_frame(F_DROPPED, json.dumps(
            {"seqs": seqs,
             "count": conn.drop_streak}).encode("utf-8")))

    def _send(self, conn: _Conn, frame: bytes) -> None:
        try:
            conn.sock.sendall(frame)
        except (OSError, ValueError):
            conn.alive = False

    def _evict(self, conn: _Conn) -> None:
        with self._stats_lock:
            self.evictions_total += 1
        conn.alive = False
        try:
            conn.sock.close()
        except OSError:
            pass

    def _retire(self, conn: _Conn) -> None:
        conn.alive = False
        try:
            conn.sock.close()
        except OSError:
            pass
        obs_metrics.REGISTRY.gauge(
            "deeprest_wire_connections",
            "open wire ingest connections").set(self.connections)

    # -- stream-source (tailer) protocol --------------------------------

    @property
    def backlog(self) -> bool:
        return len(self._out) > 0

    @property
    def dropped(self) -> int:
        """Tailer-protocol drop counter (RefreshResult's etl.dropped):
        overload drops + malformed frames."""
        with self._stats_lock:
            return self.dropped_total + self.malformed_total

    def poll(self, max_items: int | None = None) -> list:
        """Drain featurized items (or Buckets) AND commit them.

        Committing means: the per-client watermark advances, so an
        ACKed frame is by definition in the ring and a frame lost in a
        crash is by definition unACKed and will be replayed on
        reconnect — no span is ever silently half-applied.  That
        equivalence only holds if the caller ingests the returned items
        on this same thread before the watermark can be observed (a
        checkpoint cut, a WELCOME): the serial train loop and the
        VerdictIngestor do.  A consumer that queues the items for
        ANOTHER thread to ingest must use :meth:`poll_deferred` +
        :meth:`commit` instead, or a kill between drain and ingest
        loses the queued frames (ACKed and watermarked, never rung).
        """
        out, token = self.poll_deferred(max_items)
        self.commit(token)
        return out

    def poll_deferred(self, max_items: int | None = None
                      ) -> tuple[list, int]:
        """Drain WITHOUT committing: returns ``(items, token)``.  The
        drained frames stay un-ACKed and outside the watermark until
        ``commit(token)`` — call it only once the items are in the
        ring.  Uncommitted frames survive a kill by replay: the client
        still holds them pending, and a resumed watermark excludes
        them, so the reconnect WELCOME solicits exactly the gap."""
        out = []
        drained = []
        pop = self._out.popleft
        while self._out and (max_items is None or len(out) < max_items):
            try:
                conn, seq, t_enq, item = pop()
            except IndexError:       # pragma: no cover - racing close()
                break
            drained.append((conn, seq, t_enq))
            if isinstance(item, list):      # bulk frame: atomic unit
                out.extend(item)
            else:
                out.append(item)
        with self._commit_lock:
            self._commit_token += 1
            token = self._commit_token
            if drained:
                self._uncommitted.append((token, drained))
        return out, token

    def commit(self, token: int) -> None:
        """Advance per-client watermarks/ACK state for every batch
        drained at or before ``token`` — the drained rows are now in
        the ring.  Ingest→ring latency is observed here, so the
        histogram covers the full path including any queue wait."""
        batches = []
        with self._commit_lock:
            while self._uncommitted and self._uncommitted[0][0] <= token:
                batches.append(self._uncommitted.popleft()[1])
        if batches:
            now = time.monotonic()
            lats = []
            for drained in batches:
                for conn, seq, t_enq in drained:
                    conn.drained += 1
                    if seq > self._committed.get(conn.client_id, 0):
                        self._committed[conn.client_id] = seq
                    lats.append(now - t_enq)
            with self._stats_lock:
                self._lat.extend(lats)
            for lat in lats:
                self._hist.observe(lat)
        self._flush_obs()

    def _flush_obs(self) -> None:
        """Delta-flush local counters into the obs registry — called at
        poll cadence so the per-frame hot loop never takes the registry
        lock."""
        reg = obs_metrics.REGISTRY
        with self._stats_lock:
            cur = {"spans": self.spans_total,
                   "batches": self.batches_total,
                   "dropped": self.dropped_total + self.malformed_total,
                   "backpressure": self.backpressure_total}
        flushed = self._obs_flushed
        help_ = {"spans": "spans accepted over the wire",
                 "batches": "bucket batches accepted over the wire",
                 "dropped": "wire frames dropped (overload + malformed)",
                 "backpressure": "SLOWDOWN frames sent to producers"}
        for key, val in cur.items():
            delta = val - flushed[key]
            if delta:
                reg.counter(f"deeprest_wire_{key}_total",
                            help_[key]).inc(delta)
                flushed[key] = val
        reg.gauge("deeprest_wire_connections",
                  "open wire ingest connections").set(self.connections)

    # -- watermark convention (shared with LiveEndpointTailer) ----------

    def ingest_watermark(self) -> dict:
        return {"kind": "wire_seq", "clients": dict(self._committed)}

    def resume_from(self, wm: dict) -> None:
        if not isinstance(wm, dict) or wm.get("kind") != "wire_seq":
            return
        for cid, seq in (wm.get("clients") or {}).items():
            try:
                seq = int(seq)
            except (TypeError, ValueError):
                continue
            if seq > self._committed.get(str(cid), 0):
                self._committed[str(cid)] = seq

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        """The /healthz + RefreshResult-printout view: same shapes as the
        ``deeprest_wire_*`` registry series."""
        # deliberately OUTSIDE _stats_lock: _out is the lock-free
        # hot-path deque (single ingest writer, GIL-atomic len) and
        # connections acquires _conns_lock — neither belongs inside this
        # critical section (graftrace RC001 reads the incidental
        # placement as guard intent, and nesting _conns_lock under
        # _stats_lock is a lock-order hazard for free)
        pending = len(self._out)
        conns = self.connections
        with self._stats_lock:
            # snapshot under the lock commit() appends under — sorted()
            # iterating a deque another thread extends raises
            # RuntimeError, which would take /healthz down with it
            lat = sorted(self._lat)
            p99 = (lat[min(len(lat) - 1, int(0.99 * len(lat)))]
                   if lat else None)
            return {
                "spans": self.spans_total,
                "batches": self.batches_total,
                "dropped": self.dropped_total + self.malformed_total,
                "backpressure": self.backpressure_total,
                "duplicates": self.duplicates_total,
                "evictions": self.evictions_total,
                "connections": conns,
                "pending": pending,
                "memo_hit_rate": (self.memo_hits
                                  / max(1, self.memo_hits
                                        + self.memo_misses)),
                "p99_ingest_s": p99,
            }


# ---------------------------------------------------------------------------
# Client


class WireClient:
    """Blocking push client with reconnect + replay.

    Unacked frames stay in a bounded pending window; on reconnect the
    receiver's WELCOME watermark prunes the committed prefix and the
    rest is replayed, so a receiver kill mid-stream loses nothing and a
    stream resume double-counts nothing.  SLOWDOWN frames pause the
    sender (``slowdown_pause_s``); DROPPED frames prune exactly the
    seqs the receiver shed (backpressure accounting, not silent loss —
    accepted frames stay pending until an ACK covers them).  If the
    receiver stops ACKing entirely, the window is still bounded: an
    ACK wait that times out sheds the oldest pending frames, counted
    in ``timeout_shed``.
    """

    def __init__(self, address, client_id: str = "wire-client", *,
                 timeout_s: float = 10.0, pending_limit: int = 1024,
                 slowdown_pause_s: float = 0.02,
                 reconnect: bool = True, max_retries: int = 30,
                 retry_backoff_s: float = 0.1) -> None:
        if isinstance(address, str):
            address = parse_hostport(address)
        self.address = tuple(address)
        self.client_id = client_id
        self.timeout_s = timeout_s
        self.pending_limit = pending_limit
        self.slowdown_pause_s = slowdown_pause_s
        self.reconnect = reconnect
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self._sock: socket.socket | None = None
        self._seq = 0
        self._pending: dict[int, tuple[int, bytes]] = {}   # seq -> frame
        self.acked = 0
        self.slowdowns = 0
        self.server_dropped = 0
        self.timeout_shed = 0
        self.reconnects = 0
        self.sent_batches = 0
        self._hdr = bytearray(HEADER_SIZE)

    # -- connection -----------------------------------------------------

    def connect(self) -> "WireClient":
        sock = socket.create_connection(self.address,
                                        timeout=self.timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            sock.sendall(pack_frame(F_HELLO, json.dumps(
                {"client": self.client_id}).encode("utf-8")))
            ftype, _, _, payload = self._read_frame()
            if ftype != F_WELCOME:
                raise ConnectionError(
                    f"wire: expected WELCOME, got {ftype}")
            wm = int(json.loads(payload or b"{}").get("watermark", 0))
            self.acked = max(self.acked, wm)
            self._seq = max(self._seq, wm)
            self._prune(wm)
            # replay everything the receiver has not committed
            for seq in sorted(self._pending):
                flags, pl = self._pending[seq]
                self._sock.sendall(pack_frame(F_BATCH, pl, seq=seq,
                                              flags=flags))
        except BaseException:
            self._sock = None
            try:
                sock.close()
            except OSError:
                pass
            raise
        return self

    def _reconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        last: Exception | None = None
        for attempt in range(self.max_retries):
            try:
                self.connect()
                self.reconnects += 1
                return
            except (OSError, ConnectionError) as exc:
                last = exc
                time.sleep(self.retry_backoff_s * min(8, 1 + attempt))
        raise ConnectionError(
            f"wire: could not reconnect to {self.address}") from last

    # -- send path ------------------------------------------------------

    def send_bucket(self, bucket) -> int:
        """Push one bucket; returns its sequence number."""
        return self._send_batch(encode_bucket_payload(bucket), flags=0)

    def send_jsonl(self, lines: Sequence[bytes]) -> int:
        """Push raw bucket-JSONL lines as ONE bulk frame (cold-start
        replay of an existing corpus file; no client-side re-encode)."""
        return self._send_batch(b"\n".join(lines), flags=FLAG_JSONL)

    def _send_batch(self, payload: bytes, flags: int) -> int:
        if self._sock is None:
            self.connect()
        self._seq += 1
        seq = self._seq
        self._pending[seq] = (flags, payload)
        frame = pack_frame(F_BATCH, payload, seq=seq, flags=flags)
        try:
            self._sock.sendall(frame)
        except (OSError, ConnectionError):
            if not self.reconnect:
                raise
            self._reconnect()                # replays pending, incl. seq
        self.sent_batches += 1
        try:
            self._drain_server(block=False)
        except (OSError, ConnectionError):
            # the server died between our send and its ACK: the frame is
            # safe in the pending window — reconnect replays it
            if not self.reconnect:
                raise
            self._reconnect()
        if len(self._pending) > self.pending_limit:
            # respect the receiver's pace: wait for ACKs before queueing
            # more (the client-side half of the backpressure contract)
            if not self._await_acks(deadline_s=self.timeout_s):
                # stalled-but-connected receiver: no ACKs are coming, so
                # waiting again next send just adds a timeout per frame
                # while the window grows without bound.  Bound it
                # ourselves — shed the OLDEST unacked frames down to the
                # same target _await_acks aims for, with accounting
                # (the client-side mirror of the server's DROPPED
                # semantics: counted shed, never silent growth).
                target = self.pending_limit // 2
                for s in sorted(self._pending)[:len(self._pending)
                                               - target]:
                    del self._pending[s]
                    self.timeout_shed += 1
        return seq

    def flush(self, timeout_s: float | None = None) -> bool:
        """Block until every sent frame is acked or shed."""
        return self._await_acks(
            deadline_s=self.timeout_s if timeout_s is None else timeout_s,
            until_empty=True)

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self.flush()
            self._sock.sendall(pack_frame(F_BYE))
        except (OSError, ConnectionError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None

    # -- server->client frames ------------------------------------------

    def _read_frame(self):
        view = memoryview(self._hdr)
        if _recv_exact(self._sock, view) != _FILLED:
            raise ConnectionError("wire: server closed")
        magic, ftype, flags, length, seq = _HEADER.unpack(self._hdr)
        if magic != MAGIC or length > MAX_FRAME_BYTES:
            raise ConnectionError("wire: bad server frame")
        payload = b""
        if length:
            pbuf = bytearray(length)
            if _recv_exact(self._sock, memoryview(pbuf)) != _FILLED:
                raise ConnectionError("wire: EOF mid-frame")
            payload = bytes(pbuf)
        return ftype, flags, seq, payload

    def _handle(self, ftype: int, seq: int, payload: bytes) -> None:
        if ftype == F_ACK:
            self.acked = max(self.acked, seq)
            self._prune(self.acked)
        elif ftype == F_SLOWDOWN:
            self.slowdowns += 1
            time.sleep(self.slowdown_pause_s)
        elif ftype == F_DROPPED:
            # prune EXACTLY the seqs the server shed: anything else in
            # the window may be accepted-but-uncommitted, and pruning it
            # here would strand it unreplayable if the receiver dies
            # before committing
            try:
                seqs = [int(s) for s in
                        json.loads(payload or b"{}").get("seqs", ())]
            except (ValueError, TypeError):
                seqs = []
            self.server_dropped += len(seqs)
            for s in seqs:                   # shed, acknowledged as shed
                self._pending.pop(s, None)
        elif ftype == F_BYE:
            raise ConnectionError("wire: server said BYE")

    def _prune(self, through: int) -> None:
        for seq in [s for s in self._pending if s <= through]:
            del self._pending[seq]

    def _drain_server(self, block: bool) -> None:
        while self._sock is not None:
            r, _, _ = select.select([self._sock], [], [],
                                    0.05 if block else 0.0)
            if not r:
                return
            ftype, _, seq, payload = self._read_frame()
            self._handle(ftype, seq, payload)
            if not block:
                return

    def _await_acks(self, deadline_s: float,
                    until_empty: bool = False) -> bool:
        deadline = time.monotonic() + deadline_s
        target = self.pending_limit // 2
        while self._pending and (until_empty
                                 or len(self._pending) > target):
            if time.monotonic() > deadline:
                return False
            try:
                self._drain_server(block=True)
            except (OSError, ConnectionError):
                if not self.reconnect:
                    raise
                self._reconnect()
        return True


def push_corpus(address, buckets, *, client_id: str = "wire-push",
                client: WireClient | None = None,
                close: bool = True) -> int:
    """Push an iterable of buckets to a firehose receiver; returns the
    number pushed.  The obs exporter's self-ingestion path and the
    verdict pipeline both ride this."""
    c = client or WireClient(address, client_id=client_id)
    n = 0
    try:
        for b in buckets:
            c.send_bucket(b)
            n += 1
        c.flush()
    finally:
        if close and client is None:
            c.close()
    return n


__all__ = [
    "MAGIC", "HEADER_SIZE", "MAX_FRAME_BYTES", "FLAG_JSONL",
    "F_HELLO", "F_WELCOME", "F_BATCH", "F_ACK", "F_SLOWDOWN",
    "F_DROPPED", "F_BYE",
    "parse_hostport", "pack_frame", "encode_bucket_payload",
    "SpanFirehoseReceiver", "WireClient", "push_corpus",
]
