"""Sliding windows and train-split normalization statistics.

Replicates the reference's windowing (reference: resource-estimation/
utils.py:4-5 — note the last ``len(ts) - window`` start offset is exclusive)
and its min-max normalization computed on the *training split only*
(reference: resource-estimation/qrnn.py:69-75), but keeps the statistics as
explicit, serializable state so train/eval/serving all share one source of
truth instead of re-deriving scales inline (SURVEY.md §7.3 calls this out as
an easy silent-wrongness spot).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


def sliding_windows(ts: np.ndarray, window_size: int) -> np.ndarray:
    """[T, ...] → [T - window_size, window_size, ...] overlapping windows.

    A zero-copy strided view (the reference builds a Python list of slices);
    callers treat it as read-only or copy.
    """
    n = len(ts) - window_size
    if n <= 0:
        raise ValueError(
            f"series of length {len(ts)} too short for window_size={window_size}"
        )
    view = np.lib.stride_tricks.sliding_window_view(ts, window_size, axis=0)
    # sliding_window_view puts the window axis last; move it after the time
    # axis and drop the final start offset to match reference semantics.
    view = np.moveaxis(view, -1, 1)
    return view[:n]


@dataclasses.dataclass
class MinMaxStats:
    """Min-max scale state: ``x_norm = (x - min) / (max - min)``.

    Degenerate ranges (max == min) pass values through unchanged, matching
    the reference's guard (reference: resource-estimation/qrnn.py:72-74).
    Stored per-metric as arrays so one object scales the whole [.., E] target
    tensor at once.
    """

    min: np.ndarray   # broadcastable to the scaled tensor
    max: np.ndarray

    @property
    def range(self) -> np.ndarray:
        return self.max - self.min

    @property
    def _safe_range(self) -> np.ndarray:
        r = self.range
        return np.where(r == 0.0, 1.0, r)

    def apply(self, x: np.ndarray) -> np.ndarray:
        return np.where(self.range == 0.0, x, (x - self.min) / self._safe_range)

    def invert(self, x: np.ndarray) -> np.ndarray:
        return np.where(self.range == 0.0, x, x * self.range + self.min)

    def to_dict(self) -> dict:
        return {"min": np.asarray(self.min).tolist(), "max": np.asarray(self.max).tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "MinMaxStats":
        return cls(
            min=np.asarray(d["min"], dtype=np.float32),
            max=np.asarray(d["max"], dtype=np.float32),
        )


def minmax_fit(x: np.ndarray, split: int, axis: Sequence[int] | None = None) -> MinMaxStats:
    """Fit stats on ``x[:split]``.

    ``axis=None`` reduces over everything (the reference's treatment of the
    traffic tensor); pass the reduction axes to keep per-metric scales for
    the target tensor (the reference loops metrics one at a time —
    reference: resource-estimation/estimate.py:42-47).
    """
    train = x[:split]
    if axis is None:
        mn = np.asarray(np.min(train), dtype=np.float32)
        mx = np.asarray(np.max(train), dtype=np.float32)
    else:
        axis = tuple(axis)
        if 0 not in axis:
            raise ValueError(
                f"axis={axis} must include the leading (time/window) axis 0; "
                "stats are fit over the train split"
            )
        mn = np.min(train, axis=axis, keepdims=True).astype(np.float32)
        mx = np.max(train, axis=axis, keepdims=True).astype(np.float32)
        # drop the leading (time) keepdim so stats broadcast over any batch rank
        mn, mx = mn[0], mx[0]
    return MinMaxStats(min=mn, max=mx)


def minmax_apply(x: np.ndarray, stats: MinMaxStats) -> np.ndarray:
    return stats.apply(x)


def minmax_invert(x: np.ndarray, stats: MinMaxStats) -> np.ndarray:
    return stats.invert(x)
