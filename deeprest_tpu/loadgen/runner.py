"""Open-loop scenario runner: simulated users driving the live gateways.

The reference's locust layer (locustfile-*.py): a LoadShape ticks once per
time unit setting the target concurrent-user count from a double-Gaussian
two-peak curve, users re-weight their task mix per cycle, each task is an
HTTP call followed by 1-3 s of think time, media rides on 20% of composes,
mentions tag 0-5 graph friends (reference: locustfile-normal.py:14-155).

The same ``LoadScenario`` objects that parameterize the offline simulator
drive this runner, so a corpus captured from the live app and a simulated
corpus share their traffic envelope by construction.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from deeprest_tpu.loadgen.client import GatewayClient
from deeprest_tpu.loadgen.graph import SocialGraph
from deeprest_tpu.workload.scenarios import LoadScenario
from deeprest_tpu.workload.topology import API_ENDPOINTS


@dataclasses.dataclass
class RunnerConfig:
    tick_seconds: float = 1.0            # wall-clock per scenario bucket
    think_time: tuple[float, float] = (1.0, 3.0)   # reference: 1-3 s
    user_scale: float = 1.0              # scales the scenario's user curve
    max_spawn_per_tick: int = 70         # reference spawn-rate cap
    p_media: float = 0.20                # reference: 20% of composes
    p_urls: float = 0.30
    max_mentions: int = 5
    media_bytes: int = 4096
    seed: int = 0


_WORDS = ("systems", "latency", "timeline", "deploy", "trace", "bucket",
          "rollout", "cache", "quantile", "estimate", "shard", "mesh")


class _UserWorker:
    """One simulated user bound to a graph identity."""

    def __init__(self, runner: "LoadRunner", user_id: int, seed: int):
        self.runner = runner
        self.user_id = user_id
        self.rng = np.random.default_rng(seed)
        self.stop_event = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self.thread.start()

    def _loop(self) -> None:
        r = self.runner
        gateway = GatewayClient(*r.gateway_addr)
        media = GatewayClient(*r.media_addr) if r.media_addr else None
        graph = r.graph
        username = graph.username(self.user_id)
        friends = graph.friends(self.user_id) or [self.user_id]
        lo, hi = r.config.think_time
        while not self.stop_event.is_set():
            weights = r.current_weights
            action = API_ENDPOINTS[
                int(self.rng.choice(len(API_ENDPOINTS), p=weights))
            ]
            try:
                if action == "compose_post":
                    self._compose(gateway, media, username, friends)
                elif action == "read_home_timeline":
                    gateway.read_home_timeline(self.user_id)
                elif action == "read_user_timeline":
                    friend = int(friends[self.rng.integers(0, len(friends))])
                    gateway.read_user_timeline(friend)
                elif action == "register":
                    new_id = r.next_user_id()
                    gateway.register(new_id, f"user{new_id}", f"pw{new_id}")
                elif action == "follow":
                    friend = int(friends[self.rng.integers(0, len(friends))])
                    gateway.follow(self.user_id, friend)
                else:  # login
                    gateway.login(username, graph.password(self.user_id))
                r.count(action)
            except Exception:
                r.count("error")
            self.stop_event.wait(float(self.rng.uniform(lo, hi)))
        gateway.close()
        if media is not None:
            media.close()

    def _compose(self, gateway: GatewayClient, media: GatewayClient | None,
                 username: str, friends: list[int]) -> None:
        cfg = self.runner.config
        words = [str(w) for w in self.rng.choice(_WORDS, size=6)]
        n_mentions = int(self.rng.integers(0, cfg.max_mentions + 1))
        for f in self.rng.choice(friends, size=min(n_mentions, len(friends)),
                                 replace=False):
            words.append(f"@user{int(f)}")
        if self.rng.random() < cfg.p_urls:
            words.append(f"https://ex.ample/p{int(self.rng.integers(1e6))}")
        media_id = None
        if media is not None and self.rng.random() < cfg.p_media:
            payload = self.rng.bytes(cfg.media_bytes)
            media_id = media.upload_media(payload)["media_id"]
        gateway.compose(self.user_id, username, " ".join(words),
                        media_id=media_id)


class LoadRunner:
    def __init__(self, gateway_addr: tuple[str, int], graph: SocialGraph,
                 scenario: LoadScenario, config: RunnerConfig | None = None,
                 media_addr: tuple[str, int] | None = None):
        self.gateway_addr = gateway_addr
        self.media_addr = media_addr
        self.graph = graph
        self.scenario = scenario
        self.config = config or RunnerConfig()
        self.current_weights = np.full(len(API_ENDPOINTS),
                                       1.0 / len(API_ENDPOINTS))
        self._counts: dict[str, int] = {}
        self._count_lock = threading.Lock()
        self._next_user = graph.num_users + 1
        self._workers: list[_UserWorker] = []
        self._stopped: list[_UserWorker] = []
        self._checkout: list[int] = []

    # -- shared state used by workers ----------------------------------

    def count(self, key: str) -> None:
        with self._count_lock:
            self._counts[key] = self._counts.get(key, 0) + 1

    def next_user_id(self) -> int:
        with self._count_lock:
            uid = self._next_user
            self._next_user += 1
            return uid

    # -- control loop ---------------------------------------------------

    def run(self, num_ticks: int) -> dict:
        """Drive ``num_ticks`` scenario buckets; blocks for
        ``num_ticks * tick_seconds`` wall-clock, then winds all users down."""
        cfg = self.config
        users_curve = self.scenario.users_curve(num_ticks) * cfg.user_scale
        comp_curve = self.scenario.composition_curve(num_ticks)
        rng = np.random.default_rng(cfg.seed)
        # user-id checkout from the graph population (reference:
        # locustfile-normal.py:29-44,148-155)
        self._checkout = list(rng.permutation(np.arange(1, self.graph.num_users + 1)))
        peak = 0
        try:
            for tick in range(num_ticks):
                self.current_weights = comp_curve[tick]
                target = max(1, int(round(users_curve[tick])))
                self._resize(target, rng)
                peak = max(peak, len(self._workers))
                time.sleep(cfg.tick_seconds)
        finally:
            self._resize(0, rng)
        with self._count_lock:
            stats = dict(self._counts)
        stats["peak_users"] = peak
        return stats

    def _resize(self, target: int, rng: np.random.Generator) -> None:
        cfg = self.config
        while len(self._workers) > target:
            worker = self._workers.pop()
            worker.stop_event.set()
            self._checkout.append(worker.user_id)
            self._stopped.append(worker)
        spawned = 0
        while len(self._workers) < target and spawned < cfg.max_spawn_per_tick:
            if not self._checkout:
                break  # population exhausted; run with what we have
            uid = int(self._checkout.pop(0))
            worker = _UserWorker(self, uid, seed=int(rng.integers(1 << 31)))
            worker.start()
            self._workers.append(worker)
            spawned += 1
        # Reap finished threads as we go; at wind-down (target 0), join every
        # worker ever stopped so no request lands after run() returns.
        deadline = time.monotonic() + (15.0 if target == 0 else 0.0)
        remaining = []
        for worker in self._stopped:
            worker.thread.join(timeout=max(0.0, deadline - time.monotonic()))
            if worker.thread.is_alive():
                remaining.append(worker)
        self._stopped = remaining
