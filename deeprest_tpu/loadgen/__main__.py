"""End-to-end corpus capture CLI.

Boots the native cluster, warms up the social graph, drives a scenario, and
leaves a raw-data JSONL corpus ready for featurization — the whole L0-L3
loop the reference spreads across minikube + k8s + locust (SURVEY.md §3.5),
in one command:

    python -m deeprest_tpu.loadgen --scenario=normal --ticks=30 \\
        --tick-seconds=2 --out=raw_data.jsonl

With ``--target`` the supervisor is skipped and an already-running plane
(e.g. the k8s deployment from deploy/) is driven through its gateway — the
locust-against-a-cluster role (reference: locust/README.md:23-33); the
deployed trace collector writes the corpus on its side:

    python -m deeprest_tpu.loadgen --scenario=normal --ticks=480 \\
        --target=nginx-thrift.deeprest-sns.svc.cluster.local:9090 \\
        --media=media-frontend.deeprest-sns.svc.cluster.local:9090 \\
        --collector=trace-collector.deeprest-sns.svc.cluster.local:9090
"""

from __future__ import annotations

import argparse
import json
import sys
import threading

from deeprest_tpu.loadgen.burner import Burner
from deeprest_tpu.loadgen.cluster import SnsCluster
from deeprest_tpu.loadgen.graph import synthetic_social_graph
from deeprest_tpu.loadgen.runner import LoadRunner, RunnerConfig
from deeprest_tpu.loadgen.warmup import warmup
from deeprest_tpu.workload.scenarios import SCENARIOS


def _addr(spec: str) -> tuple[str, int]:
    host, _, port = spec.rpartition(":")
    if not host:
        raise argparse.ArgumentTypeError(f"{spec!r} is not host:port")
    return host, int(port)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="deeprest_tpu.loadgen")
    ap.add_argument("--scenario", choices=sorted(SCENARIOS), default="normal")
    ap.add_argument("--ticks", type=int, default=30, help="scenario buckets to run")
    ap.add_argument("--tick-seconds", type=float, default=2.0)
    ap.add_argument("--interval-ms", type=int, default=None,
                    help="collector bucket length (default: tick length)")
    ap.add_argument("--out", default="raw_data.jsonl")
    ap.add_argument("--target", type=_addr, default=None, metavar="HOST:PORT",
                    help="drive an existing gateway instead of booting a cluster")
    ap.add_argument("--media", type=_addr, default=None, metavar="HOST:PORT",
                    help="media-frontend of the existing plane (with --target)")
    ap.add_argument("--collector", type=_addr, default=None, metavar="HOST:PORT",
                    help="trace collector of the existing plane (crypto burner "
                         "registration; with --target)")
    ap.add_argument("--users", type=int, default=96, help="graph population")
    ap.add_argument("--user-scale", type=float, default=0.1,
                    help="scales the scenario user curve to local capacity")
    ap.add_argument("--think-min", type=float, default=1.0)
    ap.add_argument("--think-max", type=float, default=3.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--burn-component", default="compose-post-service",
                    help="crypto scenario: component the burner impersonates")
    ap.add_argument("--burn-local", action="store_true",
                    help="with --target: assert this process shares a "
                         "host/PID namespace with the collector, enabling "
                         "the crypto burner (dial-address loopback-ness "
                         "proves nothing — e.g. kubectl port-forward)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.burn_local and args.collector is None:
        ap.error("--burn-local requires --collector: without a collector "
                 "registration the burner's CPU is attributed to nothing "
                 "and the crypto anomaly never reaches the corpus")

    scenario = SCENARIOS[args.scenario](args.seed)
    graph = synthetic_social_graph(args.users, seed=args.seed)
    interval = args.interval_ms or int(args.tick_seconds * 1000)

    burner_failure: list[str] = []

    def drive(gateway_addr, media_addr, collector_addr, with_burner=True):
        stats = warmup(*gateway_addr, graph)
        print(f"warmup: {stats}", file=sys.stderr)
        runner = LoadRunner(
            gateway_addr, graph, scenario,
            RunnerConfig(tick_seconds=args.tick_seconds,
                         think_time=(args.think_min, args.think_max),
                         user_scale=args.user_scale, seed=args.seed),
            media_addr=media_addr,
        )
        burner = None
        timer = None
        if args.scenario == "crypto" and with_burner:
            # burn through the middle half of the run — clean baseline
            # buckets on both sides, like the reference's mid-experiment
            # injection
            burner = Burner(args.ticks * args.tick_seconds / 2,
                            collector_addr=collector_addr,
                            component=args.burn_component)

            def start_burner():
                # Timer threads swallow exceptions; a failed registration
                # must be LOUD — the whole point of the crypto scenario is
                # the injected anomaly, and a silent skip produces a clean
                # corpus labeled anomalous.  The failure is recorded so the
                # run itself reports it (stats + nonzero exit), not just a
                # stderr line nobody reads.
                try:
                    burner.start()
                except OSError as e:
                    burner_failure.append(str(e))
                    print(
                        "ERROR: crypto burner registration failed "
                        f"({e}); the run will contain NO cryptojack "
                        "anomaly — discard this corpus for anomaly work.",
                        file=sys.stderr)

            timer = threading.Timer(args.ticks * args.tick_seconds / 4,
                                    start_burner)
            timer.start()
        try:
            return runner.run(args.ticks)
        finally:
            if timer is not None:
                timer.cancel()
            if burner is not None:
                burner.stop()

    if args.target is not None:
        # drive an already-running plane; its collector owns the corpus
        with_burner = args.burn_local
        if args.scenario == "crypto" and not with_burner:
            # The burner burns CPU in THIS process; a collector on another
            # host samples /proc there, so registering our local pid would
            # attribute some unrelated same-pid process's usage to the
            # victim — corrupting the corpus (round-2 verdict weak #7).
            # A loopback dial address proves nothing (kubectl port-forward
            # tunnels remote collectors to 127.0.0.1), so the burner is
            # OFF in --target mode unless the operator asserts host
            # locality with --burn-local.
            print(
                "WARNING: --scenario=crypto with --target: the "
                "proof-of-work burner is SKIPPED — this process cannot "
                "prove it shares a host with the collector, and cross-host "
                "pid registration would attribute an unrelated process's "
                "CPU to the victim. Pass --burn-local if they do share a "
                "host, or run the burner inside the victim's pod.",
                file=sys.stderr)
        print(f"driving existing gateway {args.target}", file=sys.stderr)
        run_stats = drive(args.target, args.media, args.collector,
                          with_burner=with_burner)
        if burner_failure:
            run_stats["burner_failed"] = burner_failure[0]
        print(json.dumps({"scenario": args.scenario, "target": list(args.target),
                          **run_stats}))
        return 1 if burner_failure else 0

    with SnsCluster(out_path=args.out, interval_ms=interval,
                    verbose=args.verbose) as cluster:
        print(f"cluster up; gateway {cluster.gateway_addr}", file=sys.stderr)
        run_stats = drive(cluster.gateway_addr, cluster.media_addr,
                          cluster.collector_addr)
        cluster.stop(drain_s=1.5)
    if burner_failure:
        run_stats["burner_failed"] = burner_failure[0]
    print(json.dumps({"scenario": args.scenario, "out": args.out, **run_stats}))
    return 1 if burner_failure else 0


if __name__ == "__main__":
    sys.exit(main())
