"""Warmup: bulk-register the social graph and establish follow edges.

The reference does this with 200-way asyncio/aiohttp concurrency over
``/user/register`` then bidirectional ``/user/follow`` per graph edge
(reference: locust/warmup.py:53-84). Here: a thread pool over keep-alive
connections (aiohttp is not in the environment; threads saturate a local
gateway just as well).
"""

from __future__ import annotations

import concurrent.futures
import threading

from deeprest_tpu.loadgen.client import GatewayClient
from deeprest_tpu.loadgen.graph import SocialGraph


def warmup(host: str, port: int, graph: SocialGraph,
           concurrency: int = 16) -> dict[str, int]:
    """Returns counts of successful registrations / follows."""
    local = threading.local()
    all_clients: list[GatewayClient] = []
    clients_lock = threading.Lock()

    def get_client() -> GatewayClient:
        client = getattr(local, "client", None)
        if client is None:
            client = local.client = GatewayClient(host, port)
            with clients_lock:
                all_clients.append(client)
        return client

    def worker_batch(fn, items):
        ok = 0
        with concurrent.futures.ThreadPoolExecutor(concurrency) as pool:
            def one(item):
                try:
                    fn(get_client(), item)
                    return True
                except Exception:
                    get_client().close()  # reconnects on next use
                    return False
            for success in pool.map(one, items):
                ok += success
        return ok

    registered = worker_batch(
        lambda c, uid: c.register(uid, graph.username(uid), graph.password(uid)),
        range(1, graph.num_users + 1),
    )
    # graph.edges already lists both directions per undirected edge, matching
    # the reference's bidirectional follow loop.
    followed = worker_batch(
        lambda c, e: c.follow(e[0], e[1]),
        graph.edges,
    )
    for c in all_clients:
        c.close()
    return {"registered": registered, "followed": followed,
            "users": graph.num_users, "edges": len(graph.edges)}
