"""Boot and supervise a native snsd cluster (process-per-role).

The reference's equivalent is the Kubernetes deployment: 31 Service +
Deployment YAMLs, one pod per microservice/datastore (reference:
social-network/social-network-deploy/k8s-yaml/ — SURVEY.md §2.2). Here the
same component set runs as local processes of the one ``snsd`` binary, with
the trace collector in the Jaeger+Prometheus role writing the raw-data JSONL
contract.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import time

STORES = (
    "compose-post-redis", "user-timeline-redis", "home-timeline-redis",
    "social-graph-redis", "user-mongodb", "post-storage-mongodb",
    "user-timeline-mongodb", "social-graph-mongodb", "url-shorten-mongodb",
    "media-mongodb", "user-memcached", "post-storage-memcached", "rabbitmq",
)
SERVICES = (
    "compose-post-service", "unique-id-service", "text-service",
    "url-shorten-service", "user-mention-service", "media-service",
    "user-service", "social-graph-service", "post-storage-service",
    "user-timeline-service", "home-timeline-service",
)
GATEWAYS = ("nginx-thrift", "media-frontend")
CONSUMER = "write-home-timeline-service"
COLLECTOR = "trace-collector"


def _is_durable_store(component: str) -> bool:
    """kv (redis-role) and doc (mongodb-role) stores persist; caches and the
    queue are RAM-only by fidelity to their reference counterparts."""
    return component.endswith("-redis") or component.endswith("-mongodb")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def snsd_path() -> str:
    return os.environ.get(
        "DEEPREST_SNSD", os.path.join(_REPO_ROOT, "native", "sns", "snsd")
    )


def snsd_available() -> bool:
    return os.access(snsd_path(), os.X_OK)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            socks.append(s)        # owned by the finally from birth
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


class SnsCluster:
    """Context manager owning one process per component.

    >>> with SnsCluster(out_path="raw.jsonl", interval_ms=1000) as cluster:
    ...     GatewayClient(*cluster.gateway_addr) ...
    """

    def __init__(self, out_path: str, interval_ms: int = 5000,
                 grace_ms: int = 1000, verbose: bool = False,
                 data_dir: str | None = None, chaos: bool = False):
        # chaos=True arms the ChaosBurn fault-injection RPC in every
        # service (DEEPREST_CHAOS=1): a service can be told to fork an
        # unregistered cpu-burner child — the non-cooperative cryptojack
        # scenario (SURVEY.md §5.3).
        self.chaos = chaos
        # Collector /metrics + dashboard port, allocated at start()
        # (the reference's Prometheus scrape surface,
        # monitor-openebs-pg.yaml:38-173).
        self.metrics_addr: tuple[str, int] | None = None
        self.out_path = os.path.abspath(out_path)
        self.interval_ms = interval_ms
        self.grace_ms = grace_ms
        self.verbose = verbose
        # When set, kv/doc stores run durably (WAL + snapshots) under this
        # directory — the process-cluster stand-in for the reference's
        # per-store PVC mounts (user-timeline-mongodb.yaml:50-56).
        self.data_dir = os.path.abspath(data_dir) if data_dir else None
        self.components: dict[str, tuple[str, int]] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._extras: dict[str, list[str]] = {}   # per-component spawn args
        self._config_path: str | None = None

    # -- addresses ------------------------------------------------------

    @property
    def gateway_addr(self) -> tuple[str, int]:
        return self.components["nginx-thrift"]

    @property
    def media_addr(self) -> tuple[str, int]:
        return self.components["media-frontend"]

    @property
    def collector_addr(self) -> tuple[str, int]:
        return self.components[COLLECTOR]

    # -- lifecycle ------------------------------------------------------

    def start(self, timeout: float = 20.0) -> "SnsCluster":
        if not snsd_available():
            raise RuntimeError(f"snsd not built at {snsd_path()} (make -C native/sns)")
        # Sweep EMPTY leftover component cgroups from crashed/killed
        # clusters (rmdir refuses non-empty dirs, so live clusters are
        # untouched).  Without this, SIGKILLed runs would leak dirs
        # forever — there is no owner left to clean them.  Only dirs older
        # than a minute are swept: a concurrent cluster's service sits
        # briefly between mkdir and its cgroup.procs write, and sweeping
        # that window would silently strip its death-surviving CPU tier.
        base = "/sys/fs/cgroup/cpuacct/deeprest"
        try:
            now = time.time()
            for name in os.listdir(base):
                full = os.path.join(base, name)
                try:
                    if now - os.stat(full).st_mtime > 60:
                        os.rmdir(full)
                except OSError:
                    pass
        except OSError:
            pass  # no cgroupfs tier on this host
        named = list(STORES) + list(SERVICES) + list(GATEWAYS) + [COLLECTOR]
        ports = _free_ports(len(named) + 1)
        self.metrics_addr = ("127.0.0.1", ports.pop())
        self.components = {c: ("127.0.0.1", p) for c, p in zip(named, ports)}

        self._config_path = self.out_path + ".cluster.json"
        with open(self._config_path, "w", encoding="utf-8") as f:
            json.dump({"components": {
                c: {"host": h, "port": p} for c, (h, p) in self.components.items()
            }}, f, indent=2)

        try:
            # Collector first (registration target), then state, then logic.
            self._spawn(COLLECTOR, extra=[
                f"--out={self.out_path}",
                f"--interval-ms={self.interval_ms}",
                f"--grace-ms={self.grace_ms}",
                f"--metrics-port={self.metrics_addr[1]}",
            ])
            for c in STORES:
                self._spawn(c)
            for c in SERVICES:
                self._spawn(c)
            self._spawn(CONSUMER)
            for c in GATEWAYS:
                self._spawn(c)
            self._wait_ready(timeout)
        except Exception:
            self.stop()
            raise
        return self

    def _spawn(self, component: str, extra: list[str] | None = None) -> None:
        if extra is not None:
            self._extras[component] = list(extra)
        cmd = [snsd_path(), f"--service={component}", f"--config={self._config_path}"]
        cmd += self._extras.get(component, [])
        if self.data_dir and _is_durable_store(component):
            os.makedirs(self.data_dir, exist_ok=True)
            cmd.append(f"--data-dir={self.data_dir}")
        if self.verbose:
            cmd.append("--verbose")
        out = None if self.verbose else subprocess.DEVNULL
        env = None
        if self.chaos:
            env = dict(os.environ)
            env["DEEPREST_CHAOS"] = "1"
        self._procs[component] = subprocess.Popen(cmd, stdout=out, stderr=out,
                                                  env=env)

    def restart(self, component: str, timeout: float = 10.0,
                graceful: bool = False) -> None:
        """Kill one component's process and respawn it on the same port.

        ``graceful=False`` (SIGKILL) models a crash: a durable store must
        come back with its pre-crash state from WAL replay.
        """
        proc = self._procs.get(component)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM if graceful else signal.SIGKILL)
            proc.wait()
        self._spawn(component)
        host, port = self.components[component]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with socket.create_connection((host, port), timeout=0.25):
                    return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError(f"{component} did not come back after restart")

    def _wait_ready(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        pending = set(self.components)
        while pending and time.monotonic() < deadline:
            for c in sorted(pending):
                proc = self._procs.get(c)
                if proc is not None and proc.poll() is not None:
                    raise RuntimeError(f"{c} exited with {proc.returncode} during boot")
                host, port = self.components[c]
                try:
                    with socket.create_connection((host, port), timeout=0.25):
                        pending.discard(c)
                except OSError:
                    pass
            if pending:
                time.sleep(0.05)
        if pending:
            raise TimeoutError(f"components never came up: {sorted(pending)}")

    def stop(self, drain_s: float = 0.0) -> None:
        """SIGTERM the app first so span sinks flush into the collector,
        then the collector so its final buckets land in the output file."""
        if drain_s:
            time.sleep(drain_s)
        app = [c for c in self._procs if c != COLLECTOR]
        for c in app:
            self._terminate(c)
        for c in app:
            self._reap(c)
        if COLLECTOR in self._procs:
            self._terminate(COLLECTOR)
            self._reap(COLLECTOR)
        self._procs.clear()
        self._remove_cgroups()

    def cgroup_dir(self, component: str) -> str:
        """This cluster's cpuacct cgroup directory for ``component`` —
        the same FNV-1a64(config_path) naming native/sns/common.cpp
        ComponentCgroupDir uses (the single Python mirror of that
        scheme; _remove_cgroups and tests both go through here)."""
        assert self._config_path, "cluster not started"
        h = 0xCBF29CE484222325
        for b in self._config_path.encode():
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return f"/sys/fs/cgroup/cpuacct/deeprest/{h:016x}_{component}"

    def _remove_cgroups(self) -> None:
        """Best-effort rmdir of this cluster's per-component cpuacct
        cgroups (services self-placed into them at startup; a cgroup dir
        is only removable once empty, i.e. after every member exited)."""
        if not self._config_path:
            return
        base, prefix = os.path.split(self.cgroup_dir(""))
        try:
            names = os.listdir(base)
        except OSError:
            return  # no cgroupfs tier on this host
        for name in names:
            if name.startswith(prefix):
                try:
                    os.rmdir(os.path.join(base, name))
                except OSError:
                    pass  # member still exiting; next cluster run retries

    def _terminate(self, component: str) -> None:
        proc = self._procs.get(component)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def _reap(self, component: str, timeout: float = 8.0) -> None:
        proc = self._procs.get(component)
        if proc is None:
            return
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    def __enter__(self) -> "SnsCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
