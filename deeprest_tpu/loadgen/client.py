"""HTTP client for the snsd gateways + collector registration.

Speaks the same REST surface the reference's locust tasks hit (reference:
locust/locustfile-normal.py:88-144 → nginx-web-server/conf/nginx.conf
routes), over persistent keep-alive connections.
"""

from __future__ import annotations

import http.client
import json
import socket
import struct
import urllib.parse


class GatewayClient:
    """One persistent connection to a gateway; reconnects transparently."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._conn: http.client.HTTPConnection | None = None

    # -- plumbing -------------------------------------------------------

    def _request(self, method: str, path: str, params: dict | None = None,
                 body: bytes | None = None, content_type: str | None = None):
        if params and method == "GET":
            path = path + "?" + urllib.parse.urlencode(params)
            payload, ctype = None, None
        elif params:
            payload = urllib.parse.urlencode(params).encode()
            ctype = "application/x-www-form-urlencoded"
        else:
            payload, ctype = body, content_type
        headers = {"Content-Type": ctype} if ctype else {}
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout)
            try:
                self._conn.request(method, path, body=payload, headers=headers)
                resp = self._conn.getresponse()
                data = resp.read()
                if resp.status >= 400:
                    raise RuntimeError(
                        f"{method} {path} -> {resp.status}: {data[:200]!r}")
                return json.loads(data) if data else None
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def healthz(self) -> bool:
        try:
            self._request("GET", "/healthz")
            return True
        except Exception:
            return False

    # -- API surface (reference routes, nginx.conf:82-339) --------------

    def register(self, user_id: int, username: str, password: str):
        return self._request("POST", "/wrk2-api/user/register",
                             {"user_id": user_id, "username": username,
                              "password": password})

    def follow(self, user_id: int, followee_id: int):
        return self._request("POST", "/wrk2-api/user/follow",
                             {"user_id": user_id, "followee_id": followee_id})

    def unfollow(self, user_id: int, followee_id: int):
        return self._request("POST", "/wrk2-api/user/unfollow",
                             {"user_id": user_id, "followee_id": followee_id})

    def login(self, username: str, password: str):
        return self._request("POST", "/wrk2-api/user/login",
                             {"username": username, "password": password})

    def compose(self, user_id: int, username: str, text: str,
                media_id: str | None = None, media_type: str = "jpg"):
        params = {"user_id": user_id, "username": username, "text": text}
        if media_id is not None:
            params["media_id"] = media_id
            params["media_type"] = media_type
        return self._request("POST", "/wrk2-api/post/compose", params)

    def read_home_timeline(self, user_id: int, start: int = 0, stop: int = 9):
        return self._request("GET", "/wrk2-api/home-timeline/read",
                             {"user_id": user_id, "start": start, "stop": stop})

    def read_user_timeline(self, user_id: int, start: int = 0, stop: int = 9):
        return self._request("GET", "/wrk2-api/user-timeline/read",
                             {"user_id": user_id, "start": start, "stop": stop})

    # -- media frontend (reference: upload-media.lua) --------------------

    def upload_media(self, payload: bytes, media_type: str = "jpg"):
        return self._request(
            "POST", f"/upload-media?media_type={media_type}",
            body=payload, content_type="application/octet-stream")

    def get_media(self, media_id: str):
        return self._request("GET", "/get-media", {"media_id": media_id})


def register_with_collector(host: str, port: int, component: str, pid: int,
                            timeout: float = 2.0) -> None:
    """Register ``pid`` under ``component`` in the collector's metric
    sampler — 4-byte big-endian length-prefixed JSON frame (native/sns
    framing; the cryptojack burner uses this to attribute its CPU to a
    victim component the way the reference's pow.py rides inside a pod)."""
    payload = json.dumps({"register": component, "pid": pid}).encode()
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(struct.pack(">I", len(payload)) + payload)


def chaos_burn(host: str, port: int, seconds: float,
               timeout: float = 5.0) -> dict:
    """Fire the ChaosBurn fault injection at a service's RPC port: the
    service forks an UNREGISTERED cpu-burning child (simulated compromise;
    requires the cluster to run with DEEPREST_CHAOS=1).  Returns the
    injected child's pid — the collector must attribute its CPU to the
    victim with no cooperation from either."""
    req = json.dumps({"m": "ChaosBurn", "t": [0, 0, False],
                      "a": {"seconds": seconds}}).encode()
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(struct.pack(">I", len(req)) + req)
        hdr = _recv_exact(s, 4)
        (length,) = struct.unpack(">I", hdr)
        resp = json.loads(_recv_exact(s, length))
    if not resp.get("ok", False):
        raise RuntimeError(f"ChaosBurn failed: {resp.get('e')}")
    return resp.get("r", {})


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf += chunk
    return buf
