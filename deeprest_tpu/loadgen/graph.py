"""Synthetic social graph for warmup and user simulation.

The reference bootstraps its user population from the Facebook Reed College
graph (``socfb-Reed98.mtx``: 962 users — reference: locust/warmup.py,
locustfile-normal.py:29-44). Shipping that dataset is neither possible nor
the point; what the workload needs is a scale-free follower graph of the
same character, so we generate one deterministically by preferential
attachment (Barabási–Albert).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SocialGraph:
    """User ids are 1-based (the app treats 0 as "missing")."""

    num_users: int
    edges: tuple[tuple[int, int], ...]   # (follower, followee), both directions listed

    def friends(self, user_id: int) -> list[int]:
        """Users this user follows (mention / read-timeline candidates)."""
        return self._adjacency().get(user_id, [])

    def username(self, user_id: int) -> str:
        return f"user{user_id}"

    def password(self, user_id: int) -> str:
        return f"pw{user_id}"

    def _adjacency(self) -> dict[int, list[int]]:
        adj = getattr(self, "_adj", None)
        if adj is None:
            adj = {}
            for follower, followee in self.edges:
                adj.setdefault(follower, []).append(followee)
            object.__setattr__(self, "_adj", adj)
        return adj


def synthetic_social_graph(num_users: int = 96, attach: int = 3,
                           seed: int = 0) -> SocialGraph:
    """Preferential-attachment graph; follow edges are made bidirectional at
    warmup exactly as the reference does (warmup.py:69-84 follows both
    directions per .mtx edge)."""
    if num_users < 2:
        raise ValueError("need at least 2 users")
    attach = max(1, min(attach, num_users - 1))
    rng = np.random.default_rng(seed)
    targets = list(range(1, attach + 1))       # seed clique
    repeated: list[int] = list(targets)
    undirected: set[tuple[int, int]] = set()
    for new in range(attach + 1, num_users + 1):
        chosen: set[int] = set()
        while len(chosen) < min(attach, len(set(repeated))):
            chosen.add(int(repeated[rng.integers(0, len(repeated))]))
        for t in chosen:
            undirected.add((min(new, t), max(new, t)))
            repeated.extend((new, t))
    for i in range(1, attach + 1):             # connect the seed clique
        for j in range(i + 1, attach + 1):
            undirected.add((i, j))
    edges: list[tuple[int, int]] = []
    for a, b in sorted(undirected):
        edges.append((a, b))
        edges.append((b, a))
    return SocialGraph(num_users=num_users, edges=tuple(edges))
