"""Cryptojacking injection: a bitcoin-style double-SHA-256 proof-of-work
CPU burner (reference: locust/pow.py:29-38).

The reference injects ``pow.py`` into a running pod so its CPU shows up in
that pod's cadvisor metrics without any traffic to justify it — the anomaly
the estimator is meant to flag. The native equivalent: the burner runs as a
child process that *registers its own pid under a victim component's name*
with the trace collector, so the sampled CPU is attributed to that
component (see native/sns/collector.cpp RegisterProcess).
"""

from __future__ import annotations

import hashlib
import os
import struct
import subprocess
import sys
import time


def proof_of_work(header: bytes, difficulty_bits: int,
                  max_iters: int = 1 << 22, start_nonce: int = 0) -> tuple[int, bytes]:
    """Find a nonce whose double-SHA-256 meets the difficulty target.

    Returns ``(nonce, digest)``; nonce is -1 if ``max_iters`` ran out. The
    loop structure mirrors the reference burner (pow.py:29-38): increment
    nonce, hash(hash(header||nonce)), compare against target.
    """
    target = 1 << (256 - difficulty_bits)
    nonce = start_nonce
    for _ in range(max_iters):
        data = header + struct.pack("<Q", nonce)
        digest = hashlib.sha256(hashlib.sha256(data).digest()).digest()
        if int.from_bytes(digest, "big") < target:
            return nonce, digest
        nonce += 1
    return -1, b""


def burn(duration_s: float, difficulty_bits: int = 28) -> int:
    """Burn CPU for ``duration_s`` seconds; returns hash iterations done."""
    iters = 0
    header = os.urandom(32)
    deadline = time.monotonic() + duration_s
    nonce = 0
    while time.monotonic() < deadline:
        chunk = 20_000
        found, _ = proof_of_work(header, difficulty_bits, max_iters=chunk,
                                 start_nonce=nonce)
        if found < 0:
            iters += chunk
            nonce += chunk
        else:
            iters += found - nonce + 1
            nonce = 0
            header = os.urandom(32)
    return iters


class Burner:
    """Runs the burner as a child process, optionally attributed to a
    victim component via collector registration."""

    def __init__(self, duration_s: float, collector_addr: tuple[str, int] | None = None,
                 component: str | None = None):
        self.duration_s = duration_s
        self.collector_addr = collector_addr
        self.component = component
        self._proc: subprocess.Popen | None = None

    def start(self) -> "Burner":
        # Run the module FILE, not `-m deeprest_tpu...`: the package import
        # chain costs ~2s of child startup, during which a short burn window
        # would produce zero attributed samples.  The file itself only needs
        # the stdlib, so the child starts hashing almost immediately.
        # In a zipped install __file__ is not a real on-disk path — fall
        # back to the (slower) -m invocation, with the package's import
        # root (the zip itself) put on the child's PYTHONPATH: the child
        # does not inherit the parent's sys.path, so without this the -m
        # child would die instantly on ModuleNotFoundError into DEVNULL
        # and the anomaly would silently inject zero load.  (A PyInstaller
        # freeze, where sys.executable is not a Python interpreter at all,
        # is not supported.)
        script = os.path.abspath(__file__)
        env = None
        if os.path.isfile(script):
            cmd = [sys.executable, script, f"--duration={self.duration_s}"]
        else:
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(script)))
            env = dict(os.environ)
            # No trailing empty entry: CPython reads one as "cwd", which
            # could shadow the real package with a stray checkout.
            existing = env.get("PYTHONPATH")
            env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                                 if existing else pkg_root)
            cmd = [sys.executable, "-m", "deeprest_tpu.loadgen.burner",
                   f"--duration={self.duration_s}"]
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env,
        )
        if self.collector_addr and self.component:
            # Register from the parent — the child pid is known the moment
            # Popen returns, so attribution starts at t=0 instead of racing
            # the child's interpreter startup.  If registration fails the
            # burner must not keep running unattributed (it would burn CPU
            # that no component's metrics can explain): kill it and re-raise.
            from deeprest_tpu.loadgen.client import register_with_collector

            host, port = self.collector_addr
            try:
                register_with_collector(host, port, self.component,
                                        self._proc.pid)
            except OSError:
                self.stop()
                raise
        return self

    def wait(self) -> None:
        if self._proc is not None:
            self._proc.wait()

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()

    def __enter__(self) -> "Burner":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _main(argv: list[str]) -> int:
    """Standalone entry point.

    Two registration paths exist deliberately: :class:`Burner` registers
    the child pid from the PARENT (no startup race, used by loadgen and
    tests on a shared host), while the ``--collector``/``--component``
    flags here support the reference's in-pod injection route — copying
    this single stdlib-only file into a victim's pod and running it there,
    where no parent exists (reference: locust/pow.py into a pod).
    """
    duration, collector, component = 5.0, None, None
    for arg in argv:
        if arg.startswith("--duration="):
            duration = float(arg.split("=", 1)[1])
        elif arg.startswith("--collector="):
            host, port = arg.split("=", 1)[1].rsplit(":", 1)
            collector = (host, int(port))
        elif arg.startswith("--component="):
            component = arg.split("=", 1)[1]
    if collector and component:
        # Inlined registration (same frame as loadgen.client.register_with_
        # collector) so this file stays stdlib-only and runs copied into a
        # pod with no deeprest_tpu package installed.
        import json
        import socket

        payload = json.dumps({"register": component,
                              "pid": os.getpid()}).encode()
        with socket.create_connection(collector, timeout=2.0) as s:
            s.sendall(struct.pack(">I", len(payload)) + payload)
    burn(duration)
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
