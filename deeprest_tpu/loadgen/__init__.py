"""L3 — the load-generation plane (reference: locust/ — SURVEY.md §2.3).

Drives the *real* native application (native/sns/snsd) over HTTP the way the
reference drives its social network with locust: a synthetic social graph is
registered and followed (warmup), then open-loop simulated users execute the
scenario's per-cycle API composition under the scenario's user curve, with
think times. The crypto scenario pairs with :mod:`burner` — a double-SHA-256
proof-of-work CPU burner whose usage the trace collector attributes to a
victim component, reproducing the reference's cryptojack injection
(locust/pow.py into a pod).

The five load envelopes (normal/shape/scale/composition/crypto) are shared
with the offline simulator — :mod:`deeprest_tpu.workload.scenarios` is the
single source of truth for user curves and API mixes.
"""

from deeprest_tpu.loadgen.graph import SocialGraph, synthetic_social_graph
from deeprest_tpu.loadgen.cluster import SnsCluster, snsd_available, snsd_path
from deeprest_tpu.loadgen.client import GatewayClient, register_with_collector
from deeprest_tpu.loadgen.warmup import warmup
from deeprest_tpu.loadgen.runner import LoadRunner, RunnerConfig
from deeprest_tpu.loadgen.burner import proof_of_work, Burner

__all__ = [
    "SocialGraph",
    "synthetic_social_graph",
    "SnsCluster",
    "snsd_available",
    "snsd_path",
    "GatewayClient",
    "register_with_collector",
    "warmup",
    "LoadRunner",
    "RunnerConfig",
    "proof_of_work",
    "Burner",
]
