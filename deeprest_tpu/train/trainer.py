"""The jit-compiled training/eval loop.

Replaces the reference driver (reference: resource-estimation/
estimate.py:60-123) with a TPU-native loop: one compiled train step (donated
state, fused forward/backward, optax Adam), static batch shapes via
zero-weight padding of the ragged trailing batch, batches sharded over the
mesh's ``data`` axis and parameters over ``expert``/``model`` — gradient
and mixing collectives all GSPMD-inserted.

Evaluation reproduces the reference's exact semantics before improving on
them: every ``eval_stride``-th test window, capped at ``eval_max_cycles``,
de-normalized, median-quantile point estimates floored at 1e-6, absolute
errors pooled across windows (reference: estimate.py:85-123) — but runs as
one batched jit call instead of batch-1 Python loops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprest_tpu.config import Config
from deeprest_tpu.models.qrnn import QuantileGRU, fold_feature_mask
from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans
from deeprest_tpu.ops.densify import SparseBase, gather_densify_normalize
from deeprest_tpu.ops.quantile import pinball_loss
from deeprest_tpu.parallel.distributed import (
    feed_replicated, gather_to_host, prefetch_to_device, stage_plan,
    stage_sparse_base,
)
from deeprest_tpu.parallel.elastic import (
    FaultInjector, RemeshExhaustedError, enumerate_healthy, is_device_loss,
)
from deeprest_tpu.parallel.mesh import (
    NoValidMeshError, make_mesh, mesh_config_of, shrink_mesh_config,
)
from deeprest_tpu.parallel.sharding import shard_params, state_sharding
from deeprest_tpu.train.data import DatasetBundle, eval_window_indices
from deeprest_tpu.train.metrics import Throughput, mae_report


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array


@dataclasses.dataclass
class EpochResult:
    epoch: int
    train_loss: float
    test_loss: float | None
    report: dict | None


class Trainer:
    """Owns the model, optimizer, mesh, and compiled steps."""

    def __init__(self, config: Config, feature_dim: int, metric_names: list[str],
                 mesh=None):
        self.config = config
        self.metric_names = list(metric_names)
        self.model_config = dataclasses.replace(
            config.model, feature_dim=feature_dim, num_metrics=len(metric_names)
        )
        self.model = QuantileGRU(config=self.model_config)
        self.tx = optax.adam(config.train.learning_rate)
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh)
        self.throughput = Throughput()
        self._warmed = False       # first-ever step (jit compile) excluded
        self._global_step = 0      # host-side mirror of state.step for logging
        # Per-step losses of the most recent train_epoch (np [K], one host
        # readback per epoch/superstep) — the superstep-vs-per-step parity
        # tests and callers that want the full curve read this.
        self._last_epoch_losses: np.ndarray | None = None
        # Preemption-safe snapshot state (enable_snapshots / ROADMAP item
        # 7 dynamic half).  The epoch-plan cursor lives here between the
        # fit loop (which pins the epoch index + the shuffle rng's
        # bit-generator state at epoch START) and the epoch drivers
        # (which advance the step offset at step/superstep boundaries).
        self._snapshot_dir: str | None = None
        self._snapshot_every = 0
        self._snapshot_extra_fn = None
        self._steps_since_snapshot = 0
        self._snapshots_written = 0
        self._cursor_epoch: int | None = None
        self._cursor_rng_state: dict | None = None
        self._epoch_steps_done = 0
        self._epoch_num_steps = 0
        # Elastic remeshing (TrainConfig.elastic): the deterministic CPU
        # fault injector (None on hardware — real XlaRuntimeErrors are
        # the detect signal there), the in-flight flag the streaming
        # trainer defers refresh decisions on, and the per-fit remesh
        # ledger (attempt count + the last recovery's facts, which the
        # chaos bench and tests read).
        self._fault_injector: FaultInjector | None = None
        self._remesh_in_flight = False
        self.remesh_count = 0
        self.last_remesh: dict | None = None
        self.remesh_history: list[dict] = []
        self._build_programs()
        self._build_metrics()

    def _build_programs(self) -> None:
        """(Re)build every jitted program against the CURRENT mesh.

        Called from ``__init__`` and again by :meth:`remesh`: the
        programs close over ``self.mesh`` through ``pin_state``'s
        rule-table constraint, and a cached jit wrapper pins its device
        set — dispatching new-mesh arguments into an old-mesh wrapper is
        an "incompatible devices" error, not a retrace.  Rebuilding the
        wrappers keeps the executable story flat: each wrapper holds one
        executable per signature ON THE CURRENT SHAPE (the chaos bench's
        flatness gate), and XLA's persistent compilation cache absorbs
        any recurring shape.
        """
        quantiles = self.model_config.quantiles

        def pin_state(state: TrainState) -> TrainState:
            """Constrain every leaf to its CANONICAL named sharding, all
            resolved from the ONE rule table (parallel/sharding.py
            PARTITION_RULES — params, their optimizer mirrors, and the
            replicated step/rng bookkeeping; strict mode errors at trace
            time on any leaf the table does not place).

            Without this, GSPMD collapses the output params' specs (e.g.
            P('expert', None) → P() on a trivial mesh axis) and flips
            committedness, so the step's output state has a different
            signature than init_state's — the second call then silently
            compiles a SECOND executable whose fusion can round the last
            bit differently.  Pinning both init_state and every step
            output to one signature keeps the jit cache at one executable
            per step function (the no-recompile probe) and is what makes
            the superstep scan bit-identical to the per-step loop.
            """
            return jax.tree.map(jax.lax.with_sharding_constraint,
                                state, state_sharding(self.mesh, state))

        self._pin_state = jax.jit(pin_state)

        def train_step(state: TrainState, xb, yb, wb):
            dropout_rng = jax.random.fold_in(state.rng, state.step)

            def loss_fn(params):
                preds = self.model.apply(
                    {"params": params}, xb, deterministic=False,
                    rngs={"dropout": dropout_rng},
                )
                return pinball_loss(preds, yb, quantiles, sample_weight=wb)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            updates, opt_state = self.tx.update(grads, state.opt_state)
            params = optax.apply_updates(state.params, updates)
            return (
                pin_state(TrainState(step=state.step + 1, params=params,
                                     opt_state=opt_state, rng=state.rng)),
                loss,
            )

        def gather_x(x_base, idx):
            # The one place the staged feed's two forms meet: a dense
            # normalized [T, F] base gathers directly; a SparseBase
            # (padded-COO cols/vals + staged stats) gathers [.., W, K]
            # rows, densifies via one scatter-add, and normalizes ON
            # DEVICE — all inside the caller's existing jit, so the
            # sparse feed adds no executables beyond the per-form
            # signature (ops/densify.py for the numerics contract).
            if isinstance(x_base, SparseBase):
                return gather_densify_normalize(x_base, idx)
            return x_base[idx]

        def train_step_indexed(state: TrainState, x_base, y_base, starts, wb):
            # Device-resident feed: the normalized BASE series live in HBM
            # (stage_dataset) and each step gathers its windows by start
            # index — per-step host→device traffic is [B] int32 + weights
            # instead of the [B,W,F] window tensor (windows overlap W−1 of
            # W rows, so materialized shipping re-sends every row W times;
            # at F=10240 over the tunneled chip that was a 200× feed gap).
            w = self.config.train.window_size
            idx = starts[:, None] + jnp.arange(w)[None, :]    # [B, W]
            return train_step(state, gather_x(x_base, idx), y_base[idx], wb)

        def train_superstep(state: TrainState, x_base, y_base,
                            starts_plan, weights_plan, chunk):
            # One donated dispatch = S train steps via lax.scan.  The
            # whole epoch's [C, S, B] plan is device-resident (stage_plan)
            # and the chunk index is a TRACED scalar, so every chunk of
            # every epoch — including the zero-weight-padded trailing one
            # — reuses one executable.  Padded steps (weights all zero)
            # take lax.cond's skip branch: the prior state passes through
            # untouched (step counter, fold_in(rng, step) dropout stream,
            # params — exactly as if the padding never ran) and the wasted
            # step compute is skipped outright.  cond rather than a
            # select over the state: fusing a where into the loop body
            # changed last-bit rounding of the backward pass, breaking
            # the bit-exactness contract with the per-step loop; the cond
            # sub-computation preserves the standalone step's rounding
            # (verified by tests/test_superstep.py).
            starts_c = jax.lax.dynamic_index_in_dim(
                starts_plan, chunk, 0, keepdims=False)       # [S, B]
            weights_c = jax.lax.dynamic_index_in_dim(
                weights_plan, chunk, 0, keepdims=False)      # [S, B]

            def body(st, step_plan):
                starts, wb = step_plan

                def run(s):
                    s2, loss = train_step_indexed(s, x_base, y_base,
                                                  starts, wb)
                    # f32 losses regardless of compute dtype so the skip
                    # branch's zero matches the run branch's aval.
                    return s2, loss.astype(jnp.float32)

                def skip(s):
                    return s, jnp.zeros((), jnp.float32)

                return jax.lax.cond(jnp.any(wb > 0), run, skip, st)

            return jax.lax.scan(body, state, (starts_c, weights_c))

        # -- window-coalesced gradient accumulation (round 11) ---------
        #
        # G consecutive plan steps (microbatches) fold into ONE fused
        # forward/backward — the recurrence's per-step dot sees G·B rows
        # instead of B — and the optimizer update applies once per G with
        # grads summed in microbatch order.  Three modes (TrainConfig.
        # grad_accum_mode); "exact" is the default and is bit-identical
        # to the unfused "loop" reference:
        #
        #   exact: per-microbatch value_and_grad under jax.vmap.  Two
        #     subtleties make this BIT-equal to the loop: (1) the soft
        #     feature mask is params-only, so under vmap its backward
        #     would run once on a pre-summed cotangent (different float
        #     association than per-microbatch backwards) — the mask fold
        #     therefore stages through an explicit jax.vjp prologue
        #     outside the vmap, and each microbatch's fold cotangent is
        #     pushed through that unbatched vjp separately, in microbatch
        #     order; (2) dropout draws per-microbatch fold_in(key, g)
        #     streams, which jax.random reproduces bit-for-bit under
        #     vmap.  XLA still flattens the shared-weight matmuls to G·B
        #     rows (the RHS carries no group axis), so the fat-dot win
        #     survives the exactness.
        #   flat: the G batches reshape to one [G·B] row batch through
        #     the model's group axis — the kernel-level row fold (the
        #     pallas recurrence sees G·B rows directly).  Microbatch
        #     LOSSES stay bit-exact (rows are independent); weight-grad
        #     contractions re-associate across groups (~1e-7 relative on
        #     f32, measured — PERF.md round 11), because one fma-chain
        #     over G·B rows cannot reproduce "sum of per-group chains".
        #   loop: G sequential unfused passes — the pinned reference.
        #
        # Zero-weight pad microbatches contribute exactly-zero grads
        # (pinball_loss allow_empty guards the 0/0) so partially-padded
        # trailing groups need no per-microbatch cond; a fully-padded
        # group takes the update-level cond skip.  The step counter keeps
        # counting REAL microbatches, and the per-update dropout key is
        # fold_in(rng, step)-then-fold_in(·, g) — a stream of its own
        # (grad accumulation is a different training algorithm; it is
        # pinned against its OWN loop reference, not against G=1).
        accum_g = int(self.config.train.grad_accum_windows)
        accum_mode = self.config.train.grad_accum_mode

        def _gather_windows(x_base, y_base, starts):
            w = self.config.train.window_size
            idx = starts[:, None] + jnp.arange(w)[None, :]    # [B, W]
            return gather_x(x_base, idx), y_base[idx]

        def _accum_grads_exact(params, x_base, y_base, starts, wb, step_key):
            folded, fold_vjp = jax.vjp(fold_feature_mask, params)
            keys = jax.vmap(lambda g: jax.random.fold_in(step_key, g))(
                jnp.arange(accum_g))

            def micro(s, wb_g, key):
                xb, yb = _gather_windows(x_base, y_base, s)

                def loss_fn(pf):
                    preds = self.model.apply(
                        {"params": pf}, xb, deterministic=False,
                        rngs={"dropout": key}, mask_folded=True)
                    return pinball_loss(preds, yb, quantiles,
                                        sample_weight=wb_g, allow_empty=True)

                return jax.value_and_grad(loss_fn)(folded)

            losses, gfolded = jax.vmap(micro)(starts, wb, keys)
            total = None
            for g in range(accum_g):
                gg, = fold_vjp(jax.tree.map(lambda a, g=g: a[g], gfolded))
                total = gg if total is None else jax.tree.map(
                    jnp.add, total, gg)
            return losses.astype(jnp.float32), total

        def _accum_grads_flat(params, x_base, y_base, starts, wb, step_key):
            g, b = starts.shape
            xb, yb = _gather_windows(x_base, y_base, starts.reshape(-1))
            x4 = xb.reshape(g, b, *xb.shape[1:])
            y4 = yb.reshape(g, b, *yb.shape[1:])

            def loss_fn(params):
                preds = self.model.apply(
                    {"params": params}, x4, deterministic=False,
                    rngs={"dropout": step_key})              # [G,B,T,E,Q]
                losses = jax.vmap(
                    lambda p, y, w: pinball_loss(p, y, quantiles,
                                                 sample_weight=w,
                                                 allow_empty=True)
                )(preds, y4, wb)
                return jnp.sum(losses), losses

            (_, losses), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return losses.astype(jnp.float32), grads

        def _accum_grads_loop(params, x_base, y_base, starts, wb, step_key):
            losses, total = [], None
            for g in range(accum_g):
                xb, yb = _gather_windows(x_base, y_base, starts[g])

                def loss_fn(params, g=g, xb=xb, yb=yb):
                    preds = self.model.apply(
                        {"params": params}, xb, deterministic=False,
                        rngs={"dropout": jax.random.fold_in(step_key, g)})
                    return pinball_loss(preds, yb, quantiles,
                                        sample_weight=wb[g], allow_empty=True)

                lg, gg = jax.value_and_grad(loss_fn)(params)
                losses.append(lg)
                total = gg if total is None else jax.tree.map(jnp.add,
                                                              total, gg)
            return jnp.stack(losses).astype(jnp.float32), total

        _accum_grads = {"exact": _accum_grads_exact,
                        "flat": _accum_grads_flat,
                        "loop": _accum_grads_loop}[accum_mode]

        def train_accum_update(state: TrainState, x_base, y_base, starts, wb):
            """One optimizer update from G coalesced microbatches.
            starts/wb: [G, B]."""
            step_key = jax.random.fold_in(state.rng, state.step)
            losses, grads = _accum_grads(state.params, x_base, y_base,
                                         starts, wb, step_key)
            updates, opt_state = self.tx.update(grads, state.opt_state)
            params = optax.apply_updates(state.params, updates)
            n_real = jnp.sum(jnp.any(wb > 0, axis=1).astype(jnp.int32))
            return (
                pin_state(TrainState(step=state.step + n_real, params=params,
                                     opt_state=opt_state, rng=state.rng)),
                losses,
            )

        def train_accum_superstep(state: TrainState, x_base, y_base,
                                  starts_plan, weights_plan, chunk):
            # The G>1 twin of train_superstep: the [S, B] chunk reshapes
            # to [S/G, G, B] (the epoch planner guarantees S % G == 0) and
            # the scan advances one UPDATE (G microbatches) per step.
            # Fully-padded groups take the cond skip — prior state passes
            # through untouched, exactly like padded steps at G=1.
            starts_c = jax.lax.dynamic_index_in_dim(
                starts_plan, chunk, 0, keepdims=False)       # [S, B]
            weights_c = jax.lax.dynamic_index_in_dim(
                weights_plan, chunk, 0, keepdims=False)      # [S, B]
            s, b = starts_c.shape
            starts_c = starts_c.reshape(s // accum_g, accum_g, b)
            weights_c = weights_c.reshape(s // accum_g, accum_g, b)

            def body(st, update_plan):
                starts, wb = update_plan

                def run(s):
                    return train_accum_update(s, x_base, y_base, starts, wb)

                def skip(s):
                    return s, jnp.zeros((accum_g,), jnp.float32)

                return jax.lax.cond(jnp.any(wb > 0), run, skip, st)

            state, losses = jax.lax.scan(body, state, (starts_c, weights_c))
            return state, losses.reshape(-1)                 # [S] f32

        def eval_step(params, xb, yb):
            preds = self.model.apply({"params": params}, xb, deterministic=True)
            loss = pinball_loss(preds, yb, quantiles)
            return preds, loss

        def eval_step_indexed(params, x_base, y_base, starts):
            w = self.config.train.window_size
            idx = starts[:, None] + jnp.arange(w)[None, :]    # [n, W]
            return eval_step(params, gather_x(x_base, idx), y_base[idx])

        self._train_step = jax.jit(train_step, donate_argnums=0)
        self._train_step_indexed = jax.jit(train_step_indexed, donate_argnums=0)
        self._superstep = jax.jit(train_superstep, donate_argnums=0)
        self._accum_superstep = jax.jit(train_accum_superstep, donate_argnums=0)
        self._eval_step = jax.jit(eval_step)
        self._eval_step_indexed = jax.jit(eval_step_indexed)
        self._predict_step = jax.jit(
            lambda params, xb: self.model.apply(
                {"params": params}, xb, deterministic=True
            )
        )

    def _build_metrics(self) -> None:
        # Training-plane obs metrics (process-wide registry singletons —
        # step time itself rides in via Throughput.stop): superstep
        # dispatch counts, the designed host-readback counter, and the
        # compile-event gauge fed from the jit cache probes.  One
        # increment per epoch/superstep/log-boundary — never per step.
        self._m_dispatches = obs_metrics.REGISTRY.counter(
            "deeprest_train_superstep_dispatches_total",
            "fused lax.scan superstep dispatches")
        self._m_readbacks = obs_metrics.REGISTRY.counter(
            "deeprest_train_readbacks_total",
            "designed device->host readbacks by sink",
            labelnames=("sink",))
        self._m_executables = obs_metrics.REGISTRY.gauge(
            "deeprest_train_jit_executables",
            "compiled executables across the trainer's jitted programs "
            "(compile events = increases)")
        self._m_snapshots = obs_metrics.REGISTRY.counter(
            "deeprest_train_snapshots_total",
            "preemption-safe cursor snapshots written")
        # Elastic-remeshing legs (detect -> rebuild -> restore -> resume),
        # one increment per event — never on the step path.
        self._m_device_losses = obs_metrics.REGISTRY.counter(
            "deeprest_train_device_losses_total",
            "device-loss events caught by the elastic fault barrier")
        self._m_remeshes = obs_metrics.REGISTRY.counter(
            "deeprest_train_remeshes_total",
            "elastic remesh outcomes", labelnames=("outcome",))
        self._m_mesh_devices = obs_metrics.REGISTRY.gauge(
            "deeprest_train_mesh_devices",
            "devices in the trainer's current mesh")
        self._m_recovery = obs_metrics.REGISTRY.gauge(
            "deeprest_train_remesh_recovery_seconds",
            "wall seconds of the last remesh recovery "
            "(detect through restore; the first post-restore dispatch "
            "additionally pays one compile per new mesh shape)")
        self._m_mesh_devices.set(self.mesh.devices.size)

    def _jit_cache_size(self) -> int | None:
        """Total compiled-executable count across the trainer's jitted
        programs (None when the running jax version has no cache probe) —
        the compile-event source for the obs gauge and the no-recompile
        probes' shared hook."""
        sizes = []
        for fn in (self._train_step, self._train_step_indexed,
                   self._superstep, self._accum_superstep,
                   self._eval_step, self._eval_step_indexed,
                   self._predict_step, self._pin_state):
            probe = getattr(fn, "_cache_size", None)
            if callable(probe):
                sizes.append(int(probe()))
        return sum(sizes) if sizes else None

    def _publish_epoch_metrics(self) -> None:
        cache = self._jit_cache_size()
        if cache is not None:
            self._m_executables.set(cache)

    # -- preemption-safe snapshots (ROADMAP item 7, dynamic half) ------

    def enable_snapshots(self, directory: str, every_steps: int,
                         extra_fn=None) -> None:
        """Periodic preemption-safe snapshots: every ``every_steps`` REAL
        train steps (the superstep path fires at the first chunk boundary
        at or past the cadence — its state only exists at boundaries) the
        full TrainState checkpoints atomically (``deeprest-sharded-v1``,
        tmp+fsync+rename) together with the epoch-plan cursor: epoch
        index, steps completed within the epoch, the shuffle rng's
        bit-generator state at epoch start, and the global step.
        :meth:`resume_training` restarts from the newest cursor — onto
        whatever mesh the restarted process has — and is bit-identical
        to the uninterrupted run at the same step (tests/test_chaos.py).

        ``extra_fn`` (optional) supplies extra sidecar keys per snapshot
        (the streaming trainer rides its refresh counter, stats union,
        and retained-ring watermarks here, so a mid-refresh snapshot is
        a complete stream-resume point too).
        """
        if every_steps < 1:
            raise ValueError(
                f"enable_snapshots(every_steps={every_steps}): must be "
                ">= 1 (leave snapshots unconfigured to disable)")
        self._snapshot_dir = directory
        self._snapshot_every = int(every_steps)
        self._snapshot_extra_fn = extra_fn
        self._steps_since_snapshot = 0

    def _begin_epoch_cursor(self, epoch: int,
                            data_rng: np.random.Generator) -> None:
        """Pin the cursor base for one epoch: the epoch index and the rng
        state BEFORE the epoch plan consumes its permutation, so a resume
        regenerates the identical shuffle and skips into it."""
        import copy

        self._cursor_epoch = epoch
        self._cursor_rng_state = copy.deepcopy(data_rng.bit_generator.state)
        self._epoch_steps_done = 0

    def _note_steps(self, state: TrainState, bundle: DatasetBundle,
                    n: int, on_step=None) -> None:
        """Advance the epoch cursor by ``n`` real steps; write a snapshot
        when the cadence is due (never at the epoch's final step — the
        epoch-end snapshot, whose cursor already points at the next
        epoch, covers that boundary without a redundant save)."""
        self._epoch_steps_done += n
        if self._snapshot_every:
            self._steps_since_snapshot += n
            if (self._steps_since_snapshot >= self._snapshot_every
                    and self._epoch_steps_done < self._epoch_num_steps):
                self.snapshot(state, bundle)
        if on_step is not None:
            on_step(self._global_step)

    def snapshot(self, state: TrainState, bundle: DatasetBundle) -> str:
        """One atomic cursor snapshot (see :meth:`enable_snapshots`)."""
        if self._snapshot_dir is None:
            raise RuntimeError("snapshots not enabled (enable_snapshots)")
        extra = dict(self._snapshot_extra_fn()) \
            if self._snapshot_extra_fn is not None else {}
        extra["train_cursor"] = {
            "epoch": self._cursor_epoch,
            "steps_done": int(self._epoch_steps_done),
            "rng_state": self._cursor_rng_state,
            "global_step": int(self._global_step),
        }
        self._steps_since_snapshot = 0
        path = self.save(self._snapshot_dir, state, bundle,
                         extra_host_state=extra)
        self._snapshots_written += 1
        self._m_snapshots.inc()
        # Retention GC AFTER the durable save: only cursor snapshots are
        # candidates and the newest `snapshot_keep` always survive, so
        # the restore target of any concurrent resume/remesh is never
        # pruned (train/checkpoint.prune_cursor_snapshots).
        keep = self.config.train.snapshot_keep
        if keep:
            from deeprest_tpu.train.checkpoint import prune_cursor_snapshots

            prune_cursor_snapshots(self._snapshot_dir, keep)
        return path

    # -- elastic remeshing (ROADMAP item 7, the last training gap) -----

    def install_fault_injector(self, injector: FaultInjector) -> None:
        """Arm the deterministic synthetic device-loss injector (CPU
        testability for the whole detect→rebuild→restore→resume path;
        on hardware the detect signal is the real ``XlaRuntimeError``
        and no injector is installed)."""
        self._fault_injector = injector

    def _fault_check(self, n: int) -> None:
        """Probe the injector right after a train dispatch covering the
        next ``n`` global steps — before any cursor/snapshot/logging
        bookkeeping, so a raised loss rolls back to the newest durable
        snapshot exactly like a dispatch that failed on hardware."""
        if self._fault_injector is not None:
            self._fault_injector.note_steps(self._global_step, n)

    @property
    def remesh_in_flight(self) -> bool:
        """True while the fault barrier is rebuilding/restoring — the
        streaming trainer defers refresh decisions (never drops them)
        while this holds."""
        return self._remesh_in_flight

    def remesh(self, attempt: int = 1, reason: str = "") -> int:
        """The DETECT + REBUILD legs: re-enumerate healthy devices,
        shrink the mesh (data axis first, expert/model preserved —
        :func:`parallel.mesh.shrink_mesh_config`), and swap
        ``self.mesh`` in place.  Every jitted program re-derives its
        shardings from the one rule table at the first new-mesh trace,
        so the jit caches stay at one executable per program per
        DISTINCT mesh shape — old-shape executables remain cached, new
        shapes compile once.  Returns the healthy-device count; raises
        :class:`NoValidMeshError` (typed, counted) when fewer than
        ``expert * model`` devices survive."""
        import time

        with obs_spans.RECORDER.span("elastic.detect",
                                     component="deeprest-elastic") as sp:
            devices = list(self.mesh.devices.flat)
            if self._fault_injector is not None:
                healthy = self._fault_injector.healthy(devices)
            else:
                healthy = enumerate_healthy(devices)
            sp.tag(attempt=attempt, reason=reason[:200],
                   devices=len(devices), healthy=len(healthy))
        backoff_s = self.config.train.remesh_backoff_ms / 1e3 * attempt
        if backoff_s:
            time.sleep(backoff_s)
        with obs_spans.RECORDER.span("elastic.rebuild",
                                     component="deeprest-elastic") as sp:
            try:
                cfg = shrink_mesh_config(mesh_config_of(self.mesh),
                                         len(healthy))
            except NoValidMeshError:
                self._m_remeshes.inc(outcome="no_valid_mesh")
                raise
            self.mesh = make_mesh(cfg, devices=healthy)
            # Shardings re-derive from the one rule table at the first
            # new-mesh trace; the wrappers must be rebuilt because a
            # cached jit pins its device set (dispatching new-mesh
            # arguments into an old-mesh wrapper raises, it does not
            # retrace).  One program set per live mesh shape.
            self._build_programs()
            self._m_mesh_devices.set(cfg.size)
            sp.tag(mesh=f"{cfg.data}x{cfg.expert}x{cfg.model}")
        return len(healthy)

    def _handle_device_loss(self, bundle: DatasetBundle, directory: str,
                            attempt: int, reason: str):
        """The remesh handler the fault barrier routes every caught
        device loss to: rebuild the mesh over the survivors, restore the
        newest fsync'd cursor snapshot IN-PROCESS through the cross-mesh
        assembly, and hand back the exact resume coordinates
        ``resume_training`` would compute in a fresh process — the
        post-remesh trajectory is the restart-resume trajectory, bit for
        bit (tests/test_chaos.py pins it).

        Returns ``(state, data_rng, start_epoch, skip_steps)``.
        """
        from deeprest_tpu.train.checkpoint import (
            latest_cursor_step, restore_checkpoint,
        )

        sw = obs_metrics.Stopwatch()
        self._remesh_in_flight = True
        try:
            self._m_device_losses.inc()
            self.remesh(attempt=attempt, reason=reason)
            with obs_spans.RECORDER.span(
                    "elastic.restore", component="deeprest-elastic") as sp:
                step = latest_cursor_step(directory)
                template = self.init_state(self.sample_input(bundle))
                if step is None:
                    # Lost before the first durable snapshot: nothing to
                    # restore — re-init on the new mesh, exactly what a
                    # restarted process would be forced to do.
                    state = template
                    data_rng = np.random.default_rng(self.config.train.seed)
                    start_epoch = skip_steps = 0
                    self._global_step = 0
                else:
                    state, extra = restore_checkpoint(directory, template,
                                                      step=step)
                    cursor = extra["train_cursor"]
                    self._global_step = int(cursor["global_step"])
                    data_rng = np.random.default_rng(self.config.train.seed)
                    data_rng.bit_generator.state = cursor["rng_state"]
                    start_epoch = int(cursor["epoch"])
                    skip_steps = int(cursor["steps_done"])
                sp.tag(restored_step=step, epoch=start_epoch,
                       skip_steps=skip_steps)
            self._steps_since_snapshot = 0
            recovery_s = sw.elapsed()
            self.remesh_count += 1
            self.last_remesh = {
                "attempt": attempt,
                "restored_step": step,
                "mesh": {a: int(self.mesh.shape[a])
                         for a in ("data", "expert", "model")},
                "recovery_s": recovery_s,
            }
            self.remesh_history.append(self.last_remesh)
            self._m_recovery.set(recovery_s)
            self._m_remeshes.inc(outcome="ok")
            with obs_spans.RECORDER.span(
                    "elastic.resume", component="deeprest-elastic") as sp:
                # The resume leg proper is the re-entered epoch driver
                # (re-stage + first new-shape compile); this span marks
                # the handoff so the recovery trace is complete.
                sp.tag(global_step=self._global_step,
                       recovery_s=round(recovery_s, 4))
            return state, data_rng, start_epoch, skip_steps
        finally:
            self._remesh_in_flight = False

    def _run_epochs_elastic(self, bundle, state, data_rng, start_epoch,
                            skip_steps, baseline_preds, on_epoch,
                            num_epochs, on_step):
        """THE fault barrier (the only sanctioned swallow point for the
        device-loss family — graftlint EX004 keeps it that way): run the
        epochs; on device loss, remesh + restore in-process and
        continue, bounded by ``remesh_max_attempts`` with per-attempt
        backoff."""
        cfg = self.config.train
        directory = self._snapshot_dir or cfg.checkpoint_dir
        if not directory or not cfg.snapshot_every_steps:
            raise ValueError(
                "TrainConfig.elastic=True requires cursor snapshots: set "
                "checkpoint_dir and snapshot_every_steps >= 1 (the "
                "remesh barrier restores from the newest one)")
        attempts = 0
        while True:
            reason = None
            try:
                return self._run_epochs(bundle, state, data_rng,
                                        start_epoch, skip_steps,
                                        baseline_preds, on_epoch,
                                        num_epochs, on_step)
            except Exception as exc:
                if not is_device_loss(exc):
                    raise
                attempts += 1
                if attempts > cfg.remesh_max_attempts:
                    self._m_remeshes.inc(outcome="exhausted")
                    raise RemeshExhaustedError(
                        f"device loss #{attempts} exceeds "
                        f"remesh_max_attempts={cfg.remesh_max_attempts}; "
                        "surfacing the failure instead of respinning"
                    ) from exc
                reason = f"{type(exc).__name__}: {exc}"
            # Recovery runs OUTSIDE the except block: the exception's
            # traceback pins the failed epoch driver's frame (its staged
            # feed and old-mesh state) alive; leaving the handler first
            # releases those buffers before the rebuild re-stages.
            state = None
            state, data_rng, start_epoch, skip_steps = \
                self._handle_device_loss(bundle, directory, attempts,
                                         reason)

    # ------------------------------------------------------------------

    def sample_input(self, bundle: DatasetBundle) -> np.ndarray:
        """A ``[1, W, F]`` init sample for ``init_state``.  Flax parameter
        initialization depends on shapes and the init rng, never on the
        sample's values, so sparse bundles (no dense windows) use zeros —
        identical params to a dense-bundle init of the same shape."""
        if bundle.x_train is not None:
            return bundle.x_train[:1]
        return np.zeros((1, bundle.window_size, bundle.feature_dim),
                        np.float32)

    def init_state(self, sample_x: np.ndarray, seed: int | None = None) -> TrainState:
        """Initialize (and shard) params + optimizer state."""
        seed = self.config.train.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        init_rng, train_rng = jax.random.split(rng)
        variables = self.model.init(init_rng, jnp.asarray(sample_x[:1]))
        params = shard_params(self.mesh, dict(variables["params"]))
        opt_state = jax.jit(self.tx.init)(params)
        # Pinned through the same jitted constraint the train step applies
        # to its output, so the first step's input signature equals every
        # later step's — one executable, bit-stable numerics (see
        # pin_state in __init__).
        return self._pin_state(TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=opt_state, rng=train_rng,
        ))

    # ------------------------------------------------------------------

    def _batches(self, n: int, rng: np.random.Generator):
        """Shuffled index batches, trailing batch padded to full size with
        zero-weight duplicates (static shapes → single compilation)."""
        bs = self.config.train.batch_size
        order = rng.permutation(n)
        for lo in range(0, n, bs):
            sel = order[lo:lo + bs]
            weight = np.ones(bs, np.float32)
            if len(sel) < bs:
                weight[len(sel):] = 0.0
                # wrap-pad (resize repeats `order` as needed, so corpora
                # smaller than the batch size still yield full batches)
                sel = np.concatenate([sel, np.resize(order, bs - len(sel))])
            yield sel, weight

    # Per-chunk plan-slice byte cap for steps_per_superstep="auto": at
    # 8 bytes/step/sample (int32 start + f32 weight) this only binds for
    # pathologically long log intervals; it keeps the sliced [S, B] feed
    # buffers (and the per-superstep loss readback) comfortably small.
    _PLAN_CHUNK_MAX_BYTES = 1 << 20

    def _superstep_len(self, num_steps: int) -> int:
        """Resolve ``steps_per_superstep`` to a concrete S for this epoch.

        ``"epoch"`` fuses the whole epoch into one dispatch; ``"auto"``
        balances dispatch amortization against logging granularity
        (log boundaries are reported at most one superstep late) and the
        plan-chunk byte cap.  Ints clamp to the epoch length so a single
        ragged chunk never pads beyond one epoch.
        """
        v = self.config.train.steps_per_superstep
        if v == "epoch":
            s = num_steps
        elif v == "auto":
            log_every = self.config.train.log_every_steps
            s = min(num_steps, log_every if log_every else 32)
        else:
            s = min(int(v), num_steps)
        cap = max(1, self._PLAN_CHUNK_MAX_BYTES
                  // (8 * self.config.train.batch_size))
        s = max(1, min(s, cap))
        g = self.config.train.grad_accum_windows
        if g > 1:
            # Coalesced updates consume G microbatches at a time: round S
            # UP to a multiple of G (the plan's zero-weight padding makes
            # any overhang a cond-skipped group, exactly like ragged
            # chunks at G=1).
            s = -(-s // g) * g
        return s

    def _epoch_plan(self, n: int, rng: np.random.Generator,
                    s: int) -> tuple[np.ndarray, np.ndarray, int]:
        """The epoch's full shuffled batch plan, superstep-chunked.

        Returns ``(starts [C, S, B] int32, weights [C, S, B] float32,
        num_steps)`` where ``num_steps = ceil(n / B)`` is the count of
        REAL steps; the trailing chunk is padded to S with zero-weight
        steps (starts 0 — in-bounds for the gather, skipped by the
        superstep's ``lax.cond`` pass-through branch).  Consumes exactly
        one
        ``rng.permutation`` like the per-step loop, so the two paths see
        identical shuffles from a shared rng stream.
        """
        bs = self.config.train.batch_size
        batches = list(self._batches(n, rng))
        num_steps = len(batches)
        n_chunks = -(-num_steps // s)
        starts = np.zeros((n_chunks * s, bs), np.int32)
        weights = np.zeros((n_chunks * s, bs), np.float32)
        for i, (sel, w) in enumerate(batches):
            starts[i] = sel
            weights[i] = w
        return (starts.reshape(n_chunks, s, bs),
                weights.reshape(n_chunks, s, bs), num_steps)

    def stage_dataset(self, bundle: DatasetBundle):
        """Ship the normalized base series to HBM for index-gather feeding.

        Returns ``(x_base, y_base)`` device arrays (replicated over the
        mesh) or None when staging is off, the bundle predates base-series
        capture, or the series exceed ``device_data_max_bytes`` ("auto").
        For bf16 models ``x_base`` stages in bf16 — the model casts inputs
        there anyway, and it halves both HBM residency and the one-time
        transfer (885 MB for a month at F=10240).
        """
        cfg = self.config.train
        if cfg.device_data not in ("auto", "always", "off"):
            raise ValueError(
                f"TrainConfig.device_data={cfg.device_data!r}: must be "
                f"'auto', 'always', or 'off' (an unknown value silently "
                f"skipping the byte budget could OOM the chip)")
        if cfg.sparse_feed and bundle.is_sparse:
            return self._stage_sparse(bundle)
        if bundle.x_base is None and bundle.is_sparse:
            # A sparse-only bundle (streaming 10k tier) has no dense base
            # or windows to fall back to; reaching here means sparse_feed
            # was turned off against a sparse corpus.
            raise ValueError(
                "bundle carries only sparse (padded-COO) traffic but "
                "TrainConfig.sparse_feed is off; enable sparse_feed or "
                "rebuild the bundle with dense traffic")
        if (cfg.device_data == "off" or bundle.x_base is None
                or bundle.y_base is None):
            return None
        if cfg.device_data == "auto" and jax.default_backend() == "cpu":
            # Staging buys nothing on CPU (the "transfer" is a memcpy) and
            # XLA's CPU gather lowers to scalar loops — the staged feed
            # measured ~3× SLOWER than host streaming on the month-scale
            # CPU dossier.  "always" forces it (tests, virtual meshes).
            return None
        x = np.asarray(bundle.x_base)
        bf16 = jnp.dtype(self.model_config.compute_dtype) == jnp.bfloat16
        # Budget check BEFORE the cast: the over-budget case is exactly the
        # multi-GB corpus where a host-side bf16 copy would hurt most.
        staged_x_bytes = x.size * 2 if bf16 else x.nbytes
        total = staged_x_bytes + bundle.y_base.nbytes
        if cfg.device_data == "auto" and total > cfg.device_data_max_bytes:
            return None
        if bf16:
            import ml_dtypes

            x = x.astype(ml_dtypes.bfloat16)
        return (feed_replicated(self.mesh, x),
                feed_replicated(self.mesh, np.asarray(bundle.y_base)))

    def _stage_sparse(self, bundle: DatasetBundle):
        """Stage the padded-COO traffic base + its normalization stats.

        The sparse twin of the dense staging: RAW ``cols``/``vals`` rows
        ship once (~F/(2K) fewer bytes than the dense base at 10k width)
        and every step's gather densifies + normalizes on device
        (ops/densify.py — stats ride as runtime arguments so XLA cannot
        strength-reduce the divide; bit parity with the host-normalized
        dense path is pinned by tests/test_sparse.py).  Unlike the dense
        "auto" rule this stages on the CPU backend too: the sparse feed
        IS the staged feed — there is no host-windowed fallback to
        prefer."""
        cfg = self.config.train
        if bundle.y_base is None:
            raise ValueError("sparse bundle lacks y_base; the targets "
                             "stay dense and must be stageable")
        total = (bundle.x_cols.nbytes + bundle.x_vals.nbytes
                 + bundle.y_base.nbytes)
        if cfg.device_data == "auto" and total > cfg.device_data_max_bytes:
            raise ValueError(
                f"sparse base ({total} bytes) exceeds "
                f"device_data_max_bytes ({cfg.device_data_max_bytes}); "
                "there is no host-feed fallback for the sparse form — "
                "raise the budget or shrink history_max/nnz_cap")
        x_stats = bundle.x_stats
        mn = np.asarray(x_stats.min, np.float32).reshape(-1)
        rg = np.asarray(x_stats.range, np.float32).reshape(-1)
        base = stage_sparse_base(
            self.mesh,
            np.ascontiguousarray(bundle.x_cols, dtype=np.int32),
            np.ascontiguousarray(bundle.x_vals, dtype=np.float32),
            mn, rg, int(bundle.sparse_capacity or bundle.feature_dim))
        return base, feed_replicated(self.mesh, np.asarray(bundle.y_base))

    def train_epoch(self, state: TrainState, bundle: DatasetBundle,
                    epoch_rng: np.random.Generator,
                    staged=None, skip_steps: int = 0,
                    on_step=None) -> tuple[TrainState, float]:
        """One epoch.  ``skip_steps`` (resume) fast-forwards past the
        first N REAL steps of the epoch's plan WITHOUT running them — the
        plan rng is still consumed identically, so the remaining steps
        see exactly the batches an uninterrupted run would have; the
        returned epoch-mean loss then covers only the executed remainder
        (the resumed epoch's mean is not comparable to the uninterrupted
        one — state parity is, and is what tests/test_chaos.py pins).
        ``on_step(global_step)`` fires at every real-step (superstep:
        chunk) boundary — the chaos tests' preemption injection point."""
        accum = self.config.train.grad_accum_windows
        if staged is None and bundle.is_sparse:
            raise ValueError(
                "sparse (padded-COO) bundles train only through the "
                "staged device-resident feed — the on-device densify "
                "lives inside the staged executables; call "
                "stage_dataset(bundle) with TrainConfig.sparse_feed=True")
        if staged is None and accum > 1:
            raise ValueError(
                f"grad_accum_windows={accum} requires the staged "
                "(device-resident) feed — the coalesced update consumes "
                "its microbatches from the on-device plan; stage the "
                "dataset (device_data='always' forces it on the CPU "
                "backend) or set grad_accum_windows=1")
        if staged is not None:
            num_steps = -(-bundle.num_train_windows
                          // self.config.train.batch_size)
            s = self._superstep_len(num_steps)
            if s > 1:
                return self._train_epoch_superstep(state, bundle, epoch_rng,
                                                   staged, s,
                                                   skip_steps=skip_steps,
                                                   on_step=on_step)
        self._epoch_num_steps = -(-bundle.num_train_windows
                                  // self.config.train.batch_size)
        self._epoch_steps_done = skip_steps
        if skip_steps >= self._epoch_num_steps:
            raise ValueError(
                f"skip_steps={skip_steps} >= epoch length "
                f"{self._epoch_num_steps}: a finished epoch resumes at "
                "the NEXT epoch's cursor, never by skipping a whole plan")
        log_every = self.config.train.log_every_steps
        losses = []
        steps = 0
        measuring = self._warmed
        if measuring:
            self.throughput.start()
        if staged is None:
            def host_batches():
                # feed_global_batch (inside prefetch): sharded device_put on
                # one host; on a pod, each process ships only its
                # process_batch_slice of the (identical, rng-deterministic)
                # global selection.  Resume: the first skip_steps batches
                # of the (identical) shuffle are discarded host-side —
                # never staged, never run.
                for i, (sel, weight) in enumerate(self._batches(
                        bundle.num_train_windows, epoch_rng)):
                    if i < skip_steps:
                        continue
                    yield bundle.x_train[sel], bundle.y_train[sel], weight

            batches = prefetch_to_device(self.mesh, host_batches(),
                                         depth=self.config.train.prefetch_depth)
            run = self._train_step
        else:
            x_base, y_base = staged

            def index_batches():
                # Train window i starts at base row i (stride-1 windows),
                # so the shuffled selection IS the start-index batch.
                # Prefetch (feed_global_batch's default axes shard the
                # leading axis over "data", same as the old explicit feed)
                # keeps the [B] start/weight copies of step t+1 in flight
                # behind the step on batch t — the superstep-disabled
                # fallback overlaps transfer with compute too.
                for i, (sel, weight) in enumerate(self._batches(
                        bundle.num_train_windows, epoch_rng)):
                    if i < skip_steps:
                        continue
                    yield sel.astype(np.int32), weight

            batches = prefetch_to_device(self.mesh, index_batches(),
                                         depth=self.config.train.prefetch_depth)
            run = lambda st, starts, wb: self._train_step_indexed(
                st, x_base, y_base, starts, wb)

        for batch in batches:
            state, loss = run(state, *batch)
            # Fault barrier probe BEFORE any bookkeeping: a device lost
            # during this dispatch means the step never happened — the
            # cursor must not advance past it and no snapshot may
            # include it (the barrier restores the newest durable one).
            self._fault_check(1)
            losses.append(loss)
            self._global_step += 1
            if not self._warmed:
                # The first step ever pays jit trace+compile; keep it out of
                # the throughput window so steps/sec reflects steady state.
                jax.block_until_ready(loss)
                self._warmed = True
                self.throughput.start()
                measuring = True
            else:
                steps += 1
            if log_every and self._global_step % log_every == 0:
                self._m_readbacks.inc(sink="log_boundary")
                # graftlint: disable=JX003 -- designed sink: one scalar readback per log_every steps, the logging contract
                print(f"step {self._global_step}: loss {float(loss):.6f}")
            self._note_steps(state, bundle, 1, on_step)
        jax.block_until_ready(state.params)
        if measuring:
            self.throughput.stop(steps)
        self._publish_epoch_metrics()
        # One stacked host readback for the epoch mean instead of a
        # device round-trip per element; f64 accumulation over the f32
        # per-step values reproduces the historical list-of-floats mean
        # bit-for-bit.
        self._m_readbacks.inc(sink="epoch_losses")
        epoch_losses = np.asarray(jnp.stack(losses))
        self._last_epoch_losses = epoch_losses
        return state, float(np.mean(epoch_losses, dtype=np.float64))

    def _train_epoch_superstep(self, state: TrainState, bundle: DatasetBundle,
                               epoch_rng: np.random.Generator, staged,
                               s: int, skip_steps: int = 0,
                               on_step=None) -> tuple[TrainState, float]:
        """Fused epoch driver: ceil(K/S) donated dispatches instead of K.

        The epoch's whole shuffled plan ships to HBM once (stage_plan);
        each dispatch scans S steps on device and returns the [S] per-step
        loss vector — one readback per superstep (and none until the epoch
        mean / a log boundary needs values).  Numerics are bit-identical
        to the per-step indexed loop: same plan rng, same fold_in(rng,
        step) stream, padded steps select the prior state.

        ``skip_steps`` (resume) must land on a superstep boundary — the
        snapshot cadence only ever fires there, so a cursor that does not
        divide is a corrupted sidecar, not a rounding case.  The whole
        plan is still built (one permutation off ``epoch_rng``, identical
        to the uninterrupted epoch) and the first ``skip_steps/s`` chunks
        are never dispatched.
        """
        cfg = self.config.train
        log_every = cfg.log_every_steps
        x_base, y_base = staged
        starts, weights, num_steps = self._epoch_plan(
            bundle.num_train_windows, epoch_rng, s)
        self._epoch_num_steps = num_steps
        self._epoch_steps_done = skip_steps
        if skip_steps >= num_steps:
            raise ValueError(
                f"skip_steps={skip_steps} >= epoch length {num_steps}: a "
                "finished epoch resumes at the NEXT epoch's cursor")
        if skip_steps % s:
            raise ValueError(
                f"resume cursor steps_done={skip_steps} is not a "
                f"superstep boundary (S={s}): snapshots only fire at "
                "chunk boundaries — the sidecar is inconsistent with "
                "this config's steps_per_superstep/grad_accum_windows")
        skip_chunks = skip_steps // s
        starts_d, weights_d = stage_plan(self.mesh, starts, weights)
        # The coalesced (grad-accum) superstep and the per-step superstep
        # share the whole driver: only the compiled scan differs.
        superstep = (self._accum_superstep if cfg.grad_accum_windows > 1
                     else self._superstep)
        measuring = self._warmed
        if measuring:
            self.throughput.start()
        chunk_losses = []
        steps = 0
        for c in range(skip_chunks, starts.shape[0]):
            real = min(s, num_steps - c * s)
            state, losses_c = superstep(state, x_base, y_base,
                                        starts_d, weights_d, c)
            # Mid-superstep (and mid-grad-accum-group) device loss: the
            # whole chunk's dispatch is the unit that fails, so the probe
            # sits before ANY of the chunk's bookkeeping — progress since
            # the last durable snapshot is what the barrier rolls back.
            self._fault_check(real)
            chunk_losses.append(losses_c)
            if not self._warmed:
                # First-ever superstep pays the scan's trace+compile.
                jax.block_until_ready(losses_c)
                self._warmed = True
                self.throughput.start()
                measuring = True
            else:
                steps += real
            prev = self._global_step
            self._global_step += real
            if log_every and prev // log_every != self._global_step // log_every:
                self._m_readbacks.inc(sink="log_boundary")
                # graftlint: disable=JX003 -- designed sink: one [S] readback per superstep, only when a log boundary passed
                vals = np.asarray(losses_c)     # one readback, ≥1 boundary
                for gs in range(prev + 1, self._global_step + 1):
                    if gs % log_every == 0:
                        print(f"step {gs}: loss {vals[gs - prev - 1]:.6f}")
            self._note_steps(state, bundle, real, on_step)
        self._m_dispatches.inc(starts.shape[0] - skip_chunks)
        jax.block_until_ready(state.params)
        if measuring:
            self.throughput.stop(steps)
        self._publish_epoch_metrics()
        # Padding only ever trails the real steps, so clipping the
        # concatenated chunks to the executed real-step count recovers
        # exactly the (remaining) per-step loss curve.
        self._m_readbacks.inc(sink="epoch_losses")
        epoch_losses = np.asarray(
            jnp.concatenate(chunk_losses))[:num_steps - skip_steps]
        self._last_epoch_losses = epoch_losses
        return state, float(np.mean(epoch_losses, dtype=np.float64))

    # ------------------------------------------------------------------

    def evaluate(
        self,
        state: TrainState,
        bundle: DatasetBundle,
        baseline_preds: Mapping[str, np.ndarray] | None = None,
        staged=None,
    ) -> tuple[float, dict]:
        """Reference-semantics eval: strided windows, de-normalized MAE.

        ``baseline_preds`` maps method name → *de-normalized* ``[N_test, W, E]``
        predictions aligned with ``bundle.x_test``; errors for those methods
        are computed on the same windows for a comparable report.
        ``staged`` (from :meth:`stage_dataset`) gathers the eval windows
        from the device-resident base series — test window i starts at
        base row ``split + i`` — shipping only start indices per chunk.
        """
        cfg = self.config.train
        if staged is None and bundle.is_sparse:
            raise ValueError(
                "sparse (padded-COO) bundles evaluate only through the "
                "staged device-resident feed (see train_epoch)")
        idx = eval_window_indices(bundle.num_test_windows, cfg.eval_stride,
                                  cfg.eval_max_cycles)
        if len(idx) == 0:
            raise ValueError("no eval windows: test split shorter than stride")
        # Batched, replicated feed (the windows need not divide the data
        # axis, and every process holds the same windows).  One giant batch
        # would OOM at a large ``eval_max_cycles`` on a wide model (the
        # F=10240 flagship at 500 windows), so eval pages through the
        # windows like ``predict`` does; the loss is the window-weighted
        # mean of the per-chunk pinball means.
        bs = cfg.eval_batch_size
        preds_chunks, loss_terms = [], []
        for lo in range(0, len(idx), bs):
            sel = idx[lo:lo + bs]
            if staged is not None:
                starts = feed_replicated(
                    self.mesh, (bundle.split + sel).astype(np.int32))
                p, l = self._eval_step_indexed(state.params, *staged, starts)
            else:
                xb = feed_replicated(self.mesh, bundle.x_test[sel])
                yb = feed_replicated(self.mesh, bundle.y_test[sel])
                p, l = self._eval_step(state.params, xb, yb)
            # graftlint: disable=JX003 -- designed sink: eval pages through windows precisely so only one chunk is device-resident; the loss stays on device (loss_terms)
            preds_chunks.append(np.asarray(gather_to_host(p)))
            # Window-weighted loss accumulates as a DEVICE scalar (f32 even
            # for bf16 models) — no per-chunk float(l) sync; one readback
            # after the paging loop.
            loss_terms.append(l.astype(jnp.float32) * len(sel))
        preds = np.concatenate(preds_chunks, axis=0)
        loss = float(jnp.sum(jnp.stack(loss_terms))) / len(idx)

        # Floor the *normalized* median prediction at 1e-6 before
        # de-normalizing — the reference's clamp order (estimate.py:100-103);
        # flooring after de-normalization gives different MAE for metrics
        # with a large train-split minimum.
        med = self.model.median_index()
        preds_denorm = bundle.denorm_targets(
            np.maximum(np.asarray(preds[..., med]), 1e-6)
        )

        # Delta-trained columns come back as per-bucket increments: report
        # them in LEVEL space — integrate the predictions from each
        # window's first observed level, and swap the labels for the raw
        # level windows (bundle.level_labels / integrate_test_preds, the
        # single owner of that contract).  Baseline predictions (already
        # levels) are re-anchored to the same window anchor, so every
        # method is compared on shape from a shared anchor — the reference
        # demo's semantics for these series (web-demo/dataloader.py:143-156).
        mask = bundle.delta_mask
        labels_denorm = bundle.level_labels(idx)
        preds_denorm = bundle.integrate_test_preds(preds_denorm, idx)

        errors = {"deepr": np.abs(preds_denorm - labels_denorm)}
        if baseline_preds:
            for method, series in baseline_preds.items():
                # graftlint: disable=JX003 -- host data: baseline predictions are numpy arrays, no device sync happens here
                series = np.array(np.asarray(series)[idx], copy=True)
                if bundle._has_delta():
                    series[..., mask] += (labels_denorm[:, :1, mask]
                                          - series[:, :1, mask])
                errors[method] = np.abs(series - labels_denorm)
        return float(loss), mae_report(errors, bundle.metric_names)

    # ------------------------------------------------------------------

    def fit(
        self,
        bundle: DatasetBundle,
        state: TrainState | None = None,
        baseline_preds: Mapping[str, np.ndarray] | None = None,
        on_epoch: Callable[[EpochResult, TrainState], None] | None = None,
        num_epochs: int | None = None,
        on_step=None,
    ) -> tuple[TrainState, list[EpochResult]]:
        if state is None:
            state = self.init_state(self.sample_input(bundle))
        data_rng = np.random.default_rng(self.config.train.seed)
        run = (self._run_epochs_elastic if self.config.train.elastic
               else self._run_epochs)
        return run(bundle, state, data_rng, 0, 0,
                   baseline_preds, on_epoch, num_epochs, on_step)

    def resume_training(
        self,
        bundle: DatasetBundle,
        directory: str | None = None,
        baseline_preds: Mapping[str, np.ndarray] | None = None,
        on_epoch: Callable[[EpochResult, TrainState], None] | None = None,
        num_epochs: int | None = None,
        on_step=None,
    ) -> tuple[TrainState, list[EpochResult]]:
        """Restart a preempted :meth:`fit` from its newest cursor
        snapshot and run to completion, bit-identical to the
        uninterrupted run at every later step.

        The restore lands on WHATEVER MESH this trainer was built with —
        the cross-mesh sharded restore (round 12) assembles by global
        index, so a run preempted on a 2×2×2 slice resumes on the 1×1×1
        that survived.  The epoch plan replays from the cursor: the
        shuffle rng's bit-generator state is restored to the interrupted
        epoch's start, the plan regenerates identically, and the first
        ``steps_done`` steps are skipped without running (subsequent
        steps therefore see exactly the batches, dropout streams, and
        step counters of the uninterrupted run — the kill-at-step-K
        parity contract tests/test_chaos.py pins).
        """
        from deeprest_tpu.train.checkpoint import (
            latest_cursor_step, restore_checkpoint,
        )

        cfg = self.config.train
        directory = directory or self._snapshot_dir or cfg.checkpoint_dir
        if not directory:
            raise ValueError("resume_training needs a snapshot directory "
                             "(TrainConfig.checkpoint_dir or the "
                             "directory argument)")
        step = latest_cursor_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no cursor-bearing snapshot under {directory!r} — "
                "nothing to resume (run fit with "
                "TrainConfig.snapshot_every_steps > 0 first)")
        template = self.init_state(self.sample_input(bundle))
        state, extra = restore_checkpoint(directory, template, step=step)
        cursor = extra["train_cursor"]
        self._global_step = int(cursor["global_step"])
        data_rng = np.random.default_rng(cfg.seed)
        data_rng.bit_generator.state = cursor["rng_state"]
        run = (self._run_epochs_elastic if cfg.elastic
               else self._run_epochs)
        return run(bundle, state, data_rng,
                   int(cursor["epoch"]), int(cursor["steps_done"]),
                   baseline_preds, on_epoch, num_epochs, on_step)

    def _run_epochs(
        self,
        bundle: DatasetBundle,
        state: TrainState,
        data_rng: np.random.Generator,
        start_epoch: int,
        skip_steps: int,
        baseline_preds: Mapping[str, np.ndarray] | None,
        on_epoch: Callable[[EpochResult, TrainState], None] | None,
        num_epochs: int | None,
        on_step=None,
    ) -> tuple[TrainState, list[EpochResult]]:
        cfg = self.config.train
        if cfg.snapshot_every_steps and cfg.checkpoint_dir \
                and self._snapshot_dir is None:
            self.enable_snapshots(cfg.checkpoint_dir,
                                  cfg.snapshot_every_steps)
        history: list[EpochResult] = []
        total = num_epochs if num_epochs is not None else cfg.num_epochs
        staged = self.stage_dataset(bundle) if total > start_epoch else None
        for epoch in range(start_epoch, total):
            self._begin_epoch_cursor(epoch, data_rng)
            state, train_loss = self.train_epoch(
                state, bundle, data_rng, staged=staged,
                skip_steps=(skip_steps if epoch == start_epoch else 0),
                on_step=on_step)
            test_loss, report = self.evaluate(state, bundle, baseline_preds,
                                              staged=staged)
            result = EpochResult(epoch=epoch, train_loss=train_loss,
                                 test_loss=test_loss, report=report)
            history.append(result)
            if on_epoch is not None:
                on_epoch(result, state)
            # Epoch-boundary cursor: the NEXT epoch at step 0, with the
            # rng state the plan draw left behind — a kill between epochs
            # resumes exactly at the boundary.  The epoch-end snapshot
            # subsumes the plain epoch-cadence save (same full sidecar,
            # plus the cursor); writing the cursorless save AFTER it
            # would overwrite the cursor at the same step directory.
            self._begin_epoch_cursor(epoch + 1, data_rng)
            cadence_due = cfg.checkpoint_dir and (
                (epoch + 1) % cfg.checkpoint_every_epochs == 0
                or epoch + 1 == total)
            if self._snapshot_dir is not None:
                self.snapshot(state, bundle)
            elif cadence_due:
                self.save(cfg.checkpoint_dir, state, bundle)
        return state, history

    def save(self, directory: str, state: TrainState, bundle: DatasetBundle,
             extra_host_state: Mapping[str, Any] | None = None) -> str:
        """Checkpoint the state plus the host-side stats needed to serve.

        ``extra_host_state`` rides in the same sidecar, so caller state
        (e.g. the streaming refresh counter) is atomically bound to the
        step it describes.
        """
        from deeprest_tpu.train.checkpoint import save_checkpoint

        extra = {
            "metric_names": bundle.metric_names,
            "x_stats": bundle.x_stats.to_dict(),
            "y_stats": bundle.y_stats.to_dict(),
            "window_size": bundle.window_size,
            "feature_dim": bundle.feature_dim,
            "model_config": dataclasses.asdict(self.model_config),
            "space": bundle.space_dict,
            # Which metrics the model predicts as per-bucket increments —
            # serving must integrate these back to levels (predictor.py).
            "delta_mask": (np.asarray(bundle.delta_mask, bool).tolist()
                           if bundle.delta_mask is not None else None),
        }
        if extra_host_state:
            clash = set(extra_host_state) & set(extra)
            if clash:
                raise ValueError(
                    f"extra_host_state would overwrite reserved sidecar "
                    f"keys: {sorted(clash)}")
            extra.update(extra_host_state)
        return save_checkpoint(directory, state, int(state.step), extra)

    # ------------------------------------------------------------------

    def predict(self, state: TrainState, x: np.ndarray, batch_size: int = 64) -> np.ndarray:
        """Normalized quantile predictions ``[N, W, E, Q]`` for windows x."""
        outs = []
        for lo in range(0, len(x), batch_size):
            xb = feed_replicated(self.mesh, x[lo:lo + batch_size])
            outs.append(gather_to_host(self._predict_step(state.params, xb)))
        return np.concatenate(outs, axis=0)
