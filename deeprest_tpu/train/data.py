"""Dataset preparation: featurized corpus → normalized train/test windows.

Mirrors the reference driver's data path (reference:
resource-estimation/estimate.py:26-57): sliding windows over traffic and
stacked resource series, leading-fraction train split, global min-max on the
traffic, per-metric min-max on the targets — with the scales kept as
explicit :class:`MinMaxStats` state instead of loose tuples.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from deeprest_tpu.config import TrainConfig
from deeprest_tpu.data.featurize import FeaturizedData
from deeprest_tpu.data.windows import MinMaxStats, minmax_fit, sliding_windows




class SeriesRing:
    """Bounded row history as one preallocated, always-contiguous block.

    The streaming trainer's retained corpus was a ``deque[np.ndarray]``:
    every refresh re-stacked the whole history (O(history) Python-level
    copies) before it could window.  This ring keeps the newest ``maxlen``
    rows physically contiguous inside a ``[2·maxlen, width]`` buffer —
    ``view()`` is a zero-copy slice that ``sliding_windows`` strides over
    directly, so refresh-time assembly is O(1) and the per-append cost is
    amortized O(width) (one block memmove per ``maxlen`` appends when the
    write cursor hits the end).

    ``append_slot()`` exposes the next row for in-place writes
    (``extract(out=...)``) so the ingest path allocates nothing.  Rows
    handed out by ``view()``/iteration are views into the buffer: valid
    until ~maxlen further appends (the compaction memmove), so consumers
    that outlive the refresh they were built in must copy.
    """

    def __init__(self, maxlen: int, width: int, dtype=np.float32):
        if maxlen < 1:
            raise ValueError(f"maxlen must be >= 1, got {maxlen}")
        self.maxlen = maxlen
        self._buf = np.zeros((2 * maxlen, width), dtype)
        self._start = 0
        self._len = 0

    def __len__(self) -> int:
        return self._len

    @property
    def width(self) -> int:
        return self._buf.shape[1]

    def append_slot(self) -> np.ndarray:
        """Advance the ring by one row and return it for in-place writing.

        The returned row holds stale bytes — callers must fully overwrite
        it (``extract(out=...)`` does)."""
        if self._start + self._len == len(self._buf):
            # Cursor at the physical end: memmove the retained rows to the
            # front.  Here len == maxlen (eviction keeps len <= maxlen and
            # the buffer is 2*maxlen), so source and destination are the
            # disjoint halves.
            self._buf[:self._len] = self._buf[self._start:self._start + self._len]
            self._start = 0
        if self._len == self.maxlen:
            self._start += 1          # evict the oldest row
            self._len -= 1
        row = self._buf[self._start + self._len]
        self._len += 1
        return row

    def append(self, row: np.ndarray) -> None:
        self.append_slot()[:] = row

    def view(self) -> np.ndarray:
        """Zero-copy contiguous ``[len, width]`` of the retained history,
        oldest first.  Invalidated by later appends (see class docstring)."""
        return self._buf[self._start:self._start + self._len]

    def __iter__(self):
        return iter(self.view())

    def clear(self) -> None:
        self._start = 0
        self._len = 0


class SparseSeriesRing:
    """Bounded padded-COO row history: the sparse-first twin of
    :class:`SeriesRing` for the traffic half of the streaming corpus.

    Each retained row is ``(cols[K], vals[K], nnz)`` — the
    ``CallPathSpace.extract_sparse`` output padded to the fixed
    ``nnz_cap`` with ``(0, 0.0)`` entries — instead of a dense
    ``[capacity]`` float32 vector.  At F=10240, K=64 the resident bytes
    drop ~F/(2K) (int32 cols + float32 vals vs dense float32): a
    month-scale retained corpus goes from ~3.5 GB of ring to ~44 MB.

    Storage is three lock-stepped :class:`SeriesRing` buffers so the
    wrap/eviction/zero-copy-view semantics (and their tests) are shared,
    not re-implemented; ``view()`` returns the same oldest-first
    contiguous views, valid until ~maxlen further appends.

    A row with more than ``nnz_cap`` nonzero columns RAISES — the
    documented K-cap policy (silently dropping call paths would corrupt
    the count vector; size ``--sparse-nnz-cap`` to the corpus instead).
    """

    def __init__(self, maxlen: int, capacity: int, nnz_cap: int):
        if nnz_cap < 1:
            raise ValueError(f"nnz_cap must be >= 1, got {nnz_cap}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.nnz_cap = int(nnz_cap)
        self._cols = SeriesRing(maxlen, nnz_cap, np.int32)
        self._vals = SeriesRing(maxlen, nnz_cap, np.float32)
        self._nnz = SeriesRing(maxlen, 1, np.int32)

    def __len__(self) -> int:
        return len(self._cols)

    @property
    def maxlen(self) -> int:
        return self._cols.maxlen

    @property
    def nbytes(self) -> int:
        """Resident buffer bytes (the memory-ceiling number
        benchmarks/tenk_bench.py banks)."""
        return (self._cols._buf.nbytes + self._vals._buf.nbytes
                + self._nnz._buf.nbytes)

    def append_sparse(self, cols: np.ndarray, vals: np.ndarray) -> None:
        """Append one ``(cols, vals)`` sparse row (unpadded, as
        ``extract_sparse`` returns it)."""
        n = len(cols)
        if n != len(vals):
            raise ValueError(f"cols/vals length mismatch: {n} vs {len(vals)}")
        if n > self.nnz_cap:
            raise ValueError(
                f"sparse traffic row has {n} nonzero columns, over the "
                f"nnz cap {self.nnz_cap}; raise --sparse-nnz-cap (or "
                f"disable --sparse-feed) — silently dropping call paths "
                f"would corrupt the count vector")
        cslot = self._cols.append_slot()
        cslot[:n] = cols
        cslot[n:] = 0
        vslot = self._vals.append_slot()
        vslot[:n] = vals
        vslot[n:] = 0.0
        self._nnz.append_slot()[0] = n

    def view(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Zero-copy ``(cols[T, K], vals[T, K], nnz[T])`` of the retained
        history, oldest first (SeriesRing.view validity contract)."""
        return self._cols.view(), self._vals.view(), self._nnz.view()[:, 0]

    def densify(self) -> np.ndarray:
        """Dense ``[T, capacity]`` reconstruction — the parity reference
        (bit-identical to a SeriesRing fed from ``extract``) and the
        escape hatch for dense-only consumers.  Materializes the full
        matrix: never call this on the 10k-wide hot path (graftlint
        DN001 guards the watchlisted modules)."""
        from deeprest_tpu.ops.densify import densify_rows

        cols, vals, _ = self.view()
        return densify_rows(cols, vals, self.capacity)

    def clear(self) -> None:
        self._cols.clear()
        self._vals.clear()
        self._nnz.clear()


def delta_mask(metric_names: Sequence[str],
               resources: Sequence[str]) -> np.ndarray:
    """Boolean [E] mask of metrics (named ``component_resource``) whose
    resource is trained in increment space."""
    res = set(resources)
    return np.asarray(
        [name.rsplit("_", 1)[-1] in res for name in metric_names], bool)


def to_increments(targets: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """[T, E] levels → per-bucket increments for the masked columns.

    ``d[t] = y[t] − y[t−1]`` with ``d[0] = 0`` (the first bucket has no
    predecessor; one bucket of a month-scale corpus).  Unmasked columns
    pass through untouched."""
    if not mask.any():
        return targets
    out = np.array(targets, np.float32, copy=True)
    out[1:, mask] = targets[1:, mask] - targets[:-1, mask]
    out[0, mask] = 0.0
    return out


def integrate_level_columns(preds: np.ndarray, mask: np.ndarray,
                            anchors: np.ndarray | None = None) -> np.ndarray:
    """Integrate per-bucket increment predictions back to levels.

    ``preds``: ``[..., W, E]`` de-normalized window predictions whose
    masked columns are increments.  The cumulative sum runs along the
    window axis; with ``anchors`` (``[..., 1, E]`` levels, e.g. each
    window's first observation) the integrated series is shifted so its
    first element equals the anchor — the reference demo's re-anchoring
    contract.  Without anchors the offset is arbitrary (callers that
    re-anchor later, e.g. the what-if demo, pass None)."""
    if not mask.any():
        return preds
    out = np.array(preds, copy=True)
    c = np.cumsum(out[..., mask], axis=-2)
    if anchors is not None:
        c += anchors[..., mask] - c[..., :1, :]
    out[..., mask] = c
    return out


@dataclasses.dataclass
class DatasetBundle:
    """Normalized windows plus everything needed to de-normalize and compare."""

    # Dense traffic windows are None for sparse-first bundles (the 10k-
    # endpoint streaming path never materializes [N, W, F]); consumers go
    # through num_train_windows/num_test_windows and the staged feed.
    x_train: np.ndarray | None  # [N_train, W, F] normalized traffic windows
    y_train: np.ndarray        # [N_train, W, E] normalized targets
    x_test: np.ndarray | None  # [N_test, W, F]
    y_test: np.ndarray         # [N_test, W, E]
    x_stats: MinMaxStats
    y_stats: MinMaxStats       # per-metric (broadcast shape [1, E])
    metric_names: list[str]
    split: int                 # number of train windows
    window_size: int
    # Serialized CallPathSpace of the corpus (featurize.py to_dict): rides
    # into the checkpoint sidecar so serving-time featurization of raw
    # corpora is column-exact with the trained features.
    space_dict: dict | None = None
    # [E] bool: metrics whose normalized targets are per-bucket increments
    # (delta_resources); None for pre-delta bundles (restored checkpoints).
    delta_mask: np.ndarray | None = None
    # Raw LEVEL series [T, E] (pre-transform) — evaluation reconstructs
    # level-space labels/predictions for the masked columns from these.
    raw_targets: np.ndarray | None = None
    # Normalized BASE series [T, F]/[T, E] the windows are strided views
    # of.  The device-resident feed (Trainer.stage_dataset) ships these to
    # HBM once and gathers windows on device by start index — windows
    # overlap W−1 of W rows, so shipping materialized windows per step
    # re-sends the same bytes W times (the 10k-wide host-feed wall).
    x_base: np.ndarray | None = None
    y_base: np.ndarray | None = None
    # Sparse-first traffic (padded-COO): RAW (un-normalized) [T, K] rows
    # + [T] row lengths, the 10k-endpoint alternative to x_base.  The
    # staged feed densifies + normalizes ON DEVICE (ops/densify.py)
    # inside the existing train/eval executables; host→device bytes
    # drop ~F/(2K).  When set, x_train/x_test may be None — the windows
    # were never materialized — and n_train/n_test carry the counts.
    x_cols: np.ndarray | None = None       # [T, K] int32
    x_vals: np.ndarray | None = None       # [T, K] float32 raw counts
    x_nnz: np.ndarray | None = None        # [T] int32 row lengths
    sparse_capacity: int | None = None     # dense width F of the COO rows
    n_train: int | None = None             # window counts for sparse bundles
    n_test: int | None = None

    @property
    def num_metrics(self) -> int:
        return len(self.metric_names)

    @property
    def is_sparse(self) -> bool:
        return self.x_cols is not None

    @property
    def num_train_windows(self) -> int:
        return self.n_train if self.n_train is not None else len(self.x_train)

    @property
    def num_test_windows(self) -> int:
        return self.n_test if self.n_test is not None else len(self.x_test)

    @property
    def feature_dim(self) -> int:
        if self.x_train is not None:
            return self.x_train.shape[-1]
        return int(self.sparse_capacity)

    def denorm_targets(self, y: np.ndarray) -> np.ndarray:
        return self.y_stats.invert(y)

    # -- level-space reconstruction (delta-trained columns) -------------
    # The single owner of the test-window delta→level contract, shared by
    # trainer.evaluate and the CLI's plots so reported MAE and rendered
    # curves cannot drift apart.

    def _has_delta(self) -> bool:
        return (self.delta_mask is not None and self.delta_mask.any()
                and self.raw_targets is not None)

    def _level_windows(self, idx: np.ndarray) -> np.ndarray:
        """Raw level windows aligned with ``x_test[idx]``."""
        return sliding_windows(
            self.raw_targets, self.window_size)[self.split + np.asarray(idx)]

    def level_labels(self, idx: np.ndarray) -> np.ndarray:
        """De-normalized test labels with delta columns swapped for the
        raw LEVEL windows."""
        labels = self.denorm_targets(np.asarray(self.y_test[idx]))
        if self._has_delta():
            lvl = self._level_windows(idx)
            labels[..., self.delta_mask] = lvl[..., self.delta_mask]
        return labels

    def integrate_test_preds(self, preds_denorm: np.ndarray,
                             idx: np.ndarray) -> np.ndarray:
        """Integrate delta columns of de-normalized test predictions from
        each window's first observed level."""
        if not self._has_delta():
            return preds_denorm
        return integrate_level_columns(
            preds_denorm, self.delta_mask,
            anchors=self._level_windows(idx)[:, :1])


def prepare_dataset(data: FeaturizedData, config: TrainConfig) -> DatasetBundle:
    """Window, split, and normalize a featurized corpus.

    Normalization happens on the BASE ``[T, F]``/``[T, E]`` series and the
    windows are zero-copy strided views into the normalized series — never
    a materialized ``[N, W, F]`` tensor, which at month-scale × 10k-endpoint
    width would be ~100 GB (the reference materializes the stack,
    estimate.py:26-27, at 480-bucket scale where it doesn't matter).  This
    is exactly equivalent: min/max over the train windows equals min/max
    over their union ``base[:split + w - 1]``, and scaling commutes with
    window selection.

    Level-type resources (``config.delta_resources``, default disk usage)
    are transformed to per-bucket increments BEFORE normalization: the
    model learns what traffic *causes* (the change) instead of an
    absolute level that encodes unseen history.  The bundle carries the
    mask and the raw level series so evaluation/serving can integrate
    predictions back (``integrate_level_columns``).
    """
    w = config.window_size
    traffic = data.traffic                        # [T, F]
    raw_targets = data.targets()                  # [T, E] level space
    mask = delta_mask(data.metric_names, config.delta_resources)
    targets = to_increments(raw_targets, mask)
    n_windows = len(traffic) - w
    if n_windows <= 0:
        raise ValueError(
            f"series of length {len(traffic)} too short for window_size={w}")
    split = int(n_windows * config.train_split)
    if split < 1 or split >= n_windows:
        raise ValueError(
            f"train_split={config.train_split} gives {split} train windows "
            f"of {n_windows} total; corpus too short for window_size={w}"
        )

    base_span = split + w - 1   # union of the train windows' rows
    x_stats = minmax_fit(traffic, base_span)                   # global
    # [T, 1, E] view so the fitted stats keep the [1, E] broadcast shape
    # the windowed path produced (checkpoint-sidecar compatibility).
    y_stats = minmax_fit(targets[:, None, :], base_span, axis=(0, 1))
    x_n = x_stats.apply(traffic).astype(np.float32)            # [T, F] copy
    y_n = y_stats.apply(targets).astype(np.float32)
    x = sliding_windows(x_n, w)                   # [N, W, F] view
    y = sliding_windows(y_n, w)                   # [N, W, E] view

    # Sparse-first feed (config.sparse_feed): carry the RAW traffic as
    # padded-COO rows alongside the dense views (the offline corpus is
    # already dense in host memory; what the sparse form saves here is
    # the host→device feed bytes — the trainer stages cols/vals instead
    # of x_base and densifies on device).  Overflowing the K cap raises
    # loudly (ops/densify.sparsify_rows).
    x_cols = x_vals = x_nnz = None
    if getattr(config, "sparse_feed", False):
        from deeprest_tpu.ops.densify import sparsify_rows

        x_cols, x_vals, x_nnz = sparsify_rows(traffic,
                                              config.sparse_nnz_cap)

    return DatasetBundle(
        x_train=x[:split],
        y_train=y[:split],
        x_test=x[split:],
        y_test=y[split:],
        x_stats=x_stats,
        y_stats=y_stats,
        metric_names=list(data.metric_names),
        split=split,
        window_size=w,
        space_dict=data.space.to_dict(),
        delta_mask=mask,
        raw_targets=raw_targets,
        x_base=x_n,
        y_base=y_n,
        x_cols=x_cols,
        x_vals=x_vals,
        x_nnz=x_nnz,
        sparse_capacity=(traffic.shape[-1] if x_cols is not None else None),
    )


def eval_window_indices(num_test: int, stride: int, max_cycles: int) -> np.ndarray:
    """Non-overlapping test windows: every ``stride``-th, capped at
    ``max_cycles`` (reference: resource-estimation/estimate.py:85-88)."""
    idx = np.arange(0, num_test, stride)
    return idx[:max_cycles]
