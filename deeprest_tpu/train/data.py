"""Dataset preparation: featurized corpus → normalized train/test windows.

Mirrors the reference driver's data path (reference:
resource-estimation/estimate.py:26-57): sliding windows over traffic and
stacked resource series, leading-fraction train split, global min-max on the
traffic, per-metric min-max on the targets — with the scales kept as
explicit :class:`MinMaxStats` state instead of loose tuples.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.config import TrainConfig
from deeprest_tpu.data.featurize import FeaturizedData
from deeprest_tpu.data.windows import MinMaxStats, minmax_fit, sliding_windows


@dataclasses.dataclass
class DatasetBundle:
    """Normalized windows plus everything needed to de-normalize and compare."""

    x_train: np.ndarray        # [N_train, W, F] normalized traffic windows
    y_train: np.ndarray        # [N_train, W, E] normalized targets
    x_test: np.ndarray         # [N_test, W, F]
    y_test: np.ndarray         # [N_test, W, E]
    x_stats: MinMaxStats
    y_stats: MinMaxStats       # per-metric (broadcast shape [1, E])
    metric_names: list[str]
    split: int                 # number of train windows
    window_size: int
    # Serialized CallPathSpace of the corpus (featurize.py to_dict): rides
    # into the checkpoint sidecar so serving-time featurization of raw
    # corpora is column-exact with the trained features.
    space_dict: dict | None = None

    @property
    def num_metrics(self) -> int:
        return len(self.metric_names)

    @property
    def feature_dim(self) -> int:
        return self.x_train.shape[-1]

    def denorm_targets(self, y: np.ndarray) -> np.ndarray:
        return self.y_stats.invert(y)


def prepare_dataset(data: FeaturizedData, config: TrainConfig) -> DatasetBundle:
    """Window, split, and normalize a featurized corpus."""
    w = config.window_size
    x = sliding_windows(data.traffic, w)          # [N, W, F]
    y = sliding_windows(data.targets(), w)        # [N, W, E]
    split = int(len(x) * config.train_split)
    if split < 1 or split >= len(x):
        raise ValueError(
            f"train_split={config.train_split} gives {split} train windows "
            f"of {len(x)} total; corpus too short for window_size={w}"
        )

    x_stats = minmax_fit(x, split)                    # global, traffic
    y_stats = minmax_fit(y, split, axis=(0, 1))       # per metric
    x_n = x_stats.apply(x).astype(np.float32)
    y_n = y_stats.apply(y).astype(np.float32)

    return DatasetBundle(
        x_train=x_n[:split],
        y_train=y_n[:split],
        x_test=x_n[split:],
        y_test=y_n[split:],
        x_stats=x_stats,
        y_stats=y_stats,
        metric_names=list(data.metric_names),
        split=split,
        window_size=w,
        space_dict=data.space.to_dict(),
    )


def eval_window_indices(num_test: int, stride: int, max_cycles: int) -> np.ndarray:
    """Non-overlapping test windows: every ``stride``-th, capped at
    ``max_cycles`` (reference: resource-estimation/estimate.py:85-88)."""
    idx = np.arange(0, num_test, stride)
    return idx[:max_cycles]
