"""Dataset preparation: featurized corpus → normalized train/test windows.

Mirrors the reference driver's data path (reference:
resource-estimation/estimate.py:26-57): sliding windows over traffic and
stacked resource series, leading-fraction train split, global min-max on the
traffic, per-metric min-max on the targets — with the scales kept as
explicit :class:`MinMaxStats` state instead of loose tuples.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deeprest_tpu.config import TrainConfig
from deeprest_tpu.data.featurize import FeaturizedData
from deeprest_tpu.data.windows import MinMaxStats, minmax_fit, sliding_windows


@dataclasses.dataclass
class DatasetBundle:
    """Normalized windows plus everything needed to de-normalize and compare."""

    x_train: np.ndarray        # [N_train, W, F] normalized traffic windows
    y_train: np.ndarray        # [N_train, W, E] normalized targets
    x_test: np.ndarray         # [N_test, W, F]
    y_test: np.ndarray         # [N_test, W, E]
    x_stats: MinMaxStats
    y_stats: MinMaxStats       # per-metric (broadcast shape [1, E])
    metric_names: list[str]
    split: int                 # number of train windows
    window_size: int
    # Serialized CallPathSpace of the corpus (featurize.py to_dict): rides
    # into the checkpoint sidecar so serving-time featurization of raw
    # corpora is column-exact with the trained features.
    space_dict: dict | None = None

    @property
    def num_metrics(self) -> int:
        return len(self.metric_names)

    @property
    def feature_dim(self) -> int:
        return self.x_train.shape[-1]

    def denorm_targets(self, y: np.ndarray) -> np.ndarray:
        return self.y_stats.invert(y)


def prepare_dataset(data: FeaturizedData, config: TrainConfig) -> DatasetBundle:
    """Window, split, and normalize a featurized corpus.

    Normalization happens on the BASE ``[T, F]``/``[T, E]`` series and the
    windows are zero-copy strided views into the normalized series — never
    a materialized ``[N, W, F]`` tensor, which at month-scale × 10k-endpoint
    width would be ~100 GB (the reference materializes the stack,
    estimate.py:26-27, at 480-bucket scale where it doesn't matter).  This
    is exactly equivalent: min/max over the train windows equals min/max
    over their union ``base[:split + w - 1]``, and scaling commutes with
    window selection.
    """
    w = config.window_size
    traffic = data.traffic                        # [T, F]
    targets = data.targets()                      # [T, E]
    n_windows = len(traffic) - w
    if n_windows <= 0:
        raise ValueError(
            f"series of length {len(traffic)} too short for window_size={w}")
    split = int(n_windows * config.train_split)
    if split < 1 or split >= n_windows:
        raise ValueError(
            f"train_split={config.train_split} gives {split} train windows "
            f"of {n_windows} total; corpus too short for window_size={w}"
        )

    base_span = split + w - 1   # union of the train windows' rows
    x_stats = minmax_fit(traffic, base_span)                   # global
    # [T, 1, E] view so the fitted stats keep the [1, E] broadcast shape
    # the windowed path produced (checkpoint-sidecar compatibility).
    y_stats = minmax_fit(targets[:, None, :], base_span, axis=(0, 1))
    x_n = x_stats.apply(traffic).astype(np.float32)            # [T, F] copy
    y_n = y_stats.apply(targets).astype(np.float32)
    x = sliding_windows(x_n, w)                   # [N, W, F] view
    y = sliding_windows(y_n, w)                   # [N, W, E] view

    return DatasetBundle(
        x_train=x[:split],
        y_train=y[:split],
        x_test=x[split:],
        y_test=y[split:],
        x_stats=x_stats,
        y_stats=y_stats,
        metric_names=list(data.metric_names),
        split=split,
        window_size=w,
        space_dict=data.space.to_dict(),
    )


def eval_window_indices(num_test: int, stride: int, max_cycles: int) -> np.ndarray:
    """Non-overlapping test windows: every ``stride``-th, capped at
    ``max_cycles`` (reference: resource-estimation/estimate.py:85-88)."""
    idx = np.arange(0, num_test, stride)
    return idx[:max_cycles]
