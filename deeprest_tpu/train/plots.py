"""Training diagnostics figures.

Capability parity with the reference driver's matplotlib output (reference:
resource-estimation/estimate.py:125-169): per-metric learning curves of
train/test loss over epochs, and prediction-vs-ground-truth series plots of
the de-normalized median-quantile estimate on the evaluation windows, with
the .05-.95 quantile band added (the reference plots only the median).

Headless-safe: the Agg backend is forced before pyplot import.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def learning_curves(history: Sequence, path: str) -> str:
    """Train/test loss per epoch (reference: estimate.py:125-134).

    ``history`` is the list of Trainer ``EpochResult``s.
    """
    plt = _plt()
    epochs = [h.epoch for h in history]
    fig, ax = plt.subplots(figsize=(7, 4))
    ax.plot(epochs, [h.train_loss for h in history], label="train")
    if any(h.test_loss is not None for h in history):
        ax.plot(epochs, [h.test_loss for h in history], label="test")
    ax.set_xlabel("epoch")
    ax.set_ylabel("pinball loss")
    ax.set_title("learning curve")
    ax.legend()
    fig.tight_layout()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def prediction_plots(
    preds: np.ndarray,
    truth: np.ndarray,
    metric_names: Sequence[str],
    out_dir: str,
    quantile_band: tuple[np.ndarray, np.ndarray] | None = None,
) -> list[str]:
    """Per-metric prediction-vs-truth series (reference: estimate.py:136-169).

    Args:
      preds: ``[N_windows, W, E]`` de-normalized median predictions over the
        evaluation windows; windows are concatenated on the time axis, the
        reference's presentation for its strided non-overlapping eval.
      truth: same-shape ground truth.
      metric_names: length-E labels (``component_resource``).
      out_dir: one PNG per metric is written here.
      quantile_band: optional (lower, upper) arrays of the same shape; drawn
        as a shaded band around the median.
    """
    plt = _plt()
    os.makedirs(out_dir, exist_ok=True)
    n, w, e = preds.shape
    t_axis = np.arange(n * w)
    written = []
    for idx, name in enumerate(metric_names):
        fig, ax = plt.subplots(figsize=(9, 3.5))
        ax.plot(t_axis, truth[:, :, idx].ravel(), label="measurement",
                linewidth=1.0)
        ax.plot(t_axis, preds[:, :, idx].ravel(), label="prediction (q50)",
                linewidth=1.0)
        if quantile_band is not None:
            lo, hi = quantile_band
            ax.fill_between(t_axis, lo[:, :, idx].ravel(),
                            hi[:, :, idx].ravel(), alpha=0.25,
                            label="q05-q95 band", linewidth=0)
        for b in range(1, n):
            ax.axvline(b * w, color="grey", alpha=0.3, linewidth=0.6)
        ax.set_title(name)
        ax.set_xlabel("eval step")
        ax.legend(fontsize=8)
        fig.tight_layout()
        path = os.path.join(out_dir, f"{name.replace('/', '_')}.png")
        fig.savefig(path, dpi=120)
        plt.close(fig)
        written.append(path)
    return written
