"""Training/eval loops, dataset preparation, checkpointing, metrics."""

from deeprest_tpu.train.data import DatasetBundle, prepare_dataset
from deeprest_tpu.train.trainer import Trainer, TrainState
from deeprest_tpu.train.metrics import mae_report, format_report, Throughput
from deeprest_tpu.train.checkpoint import (
    latest_cursor_step, latest_step, restore_checkpoint, save_checkpoint,
)

__all__ = [
    "DatasetBundle",
    "prepare_dataset",
    "Trainer",
    "TrainState",
    "mae_report",
    "format_report",
    "Throughput",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "latest_cursor_step",
]
