"""Evaluation metrics and throughput accounting.

The MAE percentile report matches the reference's console evaluation
line-for-line (reference: resource-estimation/estimate.py:100-123): absolute
errors of the de-normalized median-quantile prediction, pooled over all
evaluated windows, reported at median/95th/99th/max per metric and method.
Steps/sec accounting is the capability the reference lacks entirely
(SURVEY.md §5.1) and the headline benchmark metric (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Mapping

import numpy as np


def mae_report(
    errors_by_method: Mapping[str, np.ndarray],
    metric_names: list[str],
) -> dict[str, dict[str, dict[str, float]]]:
    """Pooled absolute errors → per-metric percentile table.

    Args:
      errors_by_method: method name → ``[num_windows, W, E]`` absolute errors.
      metric_names: length-E metric labels.

    Returns: ``{metric: {method: {median, p95, p99, max}}}``.
    """
    report: dict[str, dict[str, dict[str, float]]] = {}
    for idx, name in enumerate(metric_names):
        report[name] = {}
        for method, errs in errors_by_method.items():
            pooled = np.asarray(errs)[:, :, idx].ravel()
            report[name][method] = {
                "median": float(np.median(pooled)),
                "p95": float(np.percentile(pooled, 95)),
                "p99": float(np.percentile(pooled, 99)),
                "max": float(np.max(pooled)),
            }
    return report


def format_report(report: Mapping[str, Mapping[str, Mapping[str, float]]]) -> str:
    """Render the reference-style eval block (estimate.py:112-122)."""
    lines = []
    for metric, methods in report.items():
        lines.append(f"===== {metric} =====")
        for method, stats in methods.items():
            lines.append(
                f"   {method.upper():6s}=> Median: {stats['median']:.4f} | "
                f"95-th: {stats['p95']:.4f} | 99-th: {stats['p99']:.4f} | "
                f"Max: {stats['max']:.4f}"
            )
    return "\n".join(lines)


@dataclasses.dataclass
class Throughput:
    """Steps/sec meter; ``jax.block_until_ready`` at the measurement edges
    is the caller's responsibility.

    Every ``stop()`` also publishes the measured window into the obs
    metrics registry (``deeprest_train_steps_total`` /
    ``deeprest_train_measured_seconds_total``) so the trainer's step-time
    signal reaches ``GET /metrics`` scrapes and the self-ingestion loop
    — this meter IS the obs layer's step-time source, which is why its
    raw clock carries the OB001 suppression below rather than migrating
    onto itself.
    """

    steps: int = 0
    _t0: float | None = None
    elapsed: float = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, steps: int) -> None:
        if self._t0 is None:
            raise RuntimeError("Throughput.stop() without start()")
        # graftlint: disable=OB001 -- this meter IS the obs step-time source; the registry publish below is the migration target other sites use
        window = time.perf_counter() - self._t0
        self.elapsed += window
        self.steps += steps
        self._t0 = None
        from deeprest_tpu.obs import metrics as obs_metrics

        obs_metrics.REGISTRY.counter(
            "deeprest_train_steps_total",
            "train steps inside measured throughput windows").inc(steps)
        obs_metrics.REGISTRY.counter(
            "deeprest_train_measured_seconds_total",
            "wall seconds of measured train windows").inc(window)

    @property
    def steps_per_sec(self) -> float:
        return self.steps / self.elapsed if self.elapsed > 0 else 0.0
