"""Continuous retraining from a live, growing raw-data corpus.

The reference is strictly offline: capture a corpus with minikube + locust,
run featurize.py, run estimate.py (reference: resource-estimation/
README.md:64-83).  This module closes the loop the reference leaves open
(SURVEY.md §7.3 "streaming retrain ... no reference prior art; design
explicitly"): tail the collector's JSONL as it grows, featurize buckets
incrementally in hash mode (fixed width — no vocabulary pass, no recompile),
and periodically fine-tune the model from its latest state, re-checkpointing
after every refresh.

Design decisions, explicit because there is no reference behavior to match:

- **Hash featurization only.**  Dictionary mode needs a global vocabulary
  pass and can change width; a stream has neither a "global" view nor any
  tolerance for shape changes.  `FeaturizeConfig(hash_features=True,
  capacity=F)` keeps the model input static forever.
- **Expanding min-max normalization.**  Stats are the monotone union of
  every refresh's observed range (never shrink).  Alternatives considered:
  frozen initial stats (reference semantics — breaks under drift: values
  outside the day-one range clip the model's usable dynamic range forever)
  and sliding-window stats (adapt both ways, but re-anchor the output scale
  every refresh, so two checkpoints' predictions are not comparable).  The
  monotone union keeps every checkpoint's de-normalization consistent with
  all earlier ones while still covering drifted ranges; windows are re-
  normalized with the current stats at every refresh.
- **Per-feature traffic stats.**  The offline path fits one scalar min/max
  over the whole traffic tensor (reference semantics,
  resource-estimation/qrnn.py:69-75) — fine for a one-shot corpus, where a
  hot column costs one normalization at worst.  Under the monotone-union
  rule a scalar is a ratchet: a single traffic spike on one hash column
  would permanently compress every other column's dynamic range.  Streaming
  therefore fits min/max **per feature column** (shape ``[1, F]``; the
  MinMaxStats contract is broadcast-shape-agnostic, so checkpoints,
  serving, and resume are unaffected).  A hot endpoint then saturates only
  its own column.  Columns whose observed range is degenerate get derived
  *effective* stats — their own level if constant-nonzero, the global max
  if never active — because MinMaxStats passes zero-range columns through
  raw, which would feed unnormalized serve-time traffic to the model the
  first time such a column activates.  The honest observed union is kept
  separately (and persisted in the checkpoint sidecar) so a column that
  merely goes quiet for one refresh is not misdetected as never-active
  and ratcheted up to the global scale.
- **Bounded refresh cost.**  ``refresh()`` re-windows and fine-tunes over
  the retained corpus — deliberately *not* incremental, so every refresh
  sees the newest normalization of the oldest data.  Cost is bounded by
  ``history_max``: at most ``history_max - window_size`` windows ≈
  ``finetune_epochs * history_max / batch_size`` train steps per refresh,
  all at one static compiled shape (batch padding in Trainer._batches).
  At defaults that is ≤ 256 steps per refresh, forever.
- **Frozen metric set.**  The expert axis E is part of the compiled model.
  The metric set freezes at the first refresh; components that stop
  reporting fill with zeros, metrics that appear later are dropped (warned
  once).  Restarting the stream from its checkpoint re-adopts the frozen
  set.
- **Recency-holdout eval.**  Each refresh trains on all windows but the
  trailing ``eval_holdout`` and evaluates on those — the stream's notion of
  "unseen" is "newest", which is what capacity planning on drifting traffic
  actually faces.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Iterator

import numpy as np

from deeprest_tpu.config import Config, FeaturizeConfig
from deeprest_tpu.data.featurize import CallPathSpace
from deeprest_tpu.obs import metrics as obs_metrics
from deeprest_tpu.obs import spans as obs_spans
from deeprest_tpu.data.schema import Bucket
from deeprest_tpu.data.windows import MinMaxStats, minmax_fit, sliding_windows
from deeprest_tpu.ops.densify import sparse_minmax
from deeprest_tpu.train.data import (
    DatasetBundle, SeriesRing, SparseSeriesRing, delta_mask, to_increments,
)
from deeprest_tpu.train.trainer import Trainer, TrainState


class BucketTailer:
    """Incrementally parse complete JSONL lines appended to a growing file.

    Safe against torn tails: only lines terminated by a newline are parsed;
    a partially-written last line stays buffered until its newline arrives.
    The file may not exist yet at construction (collector still booting).

    Rotation is ZERO-LOSS for every generation that exists at some poll
    instant: the tailer holds the file open between polls, so a rename/
    unlink rotation leaves the old inode readable through the held fd; the
    tailer drains it to EOF (however many capped polls that takes) before
    switching.  While draining, each poll also checks the path and opens a
    handle to any NEW generation it sees, so successive rotations during a
    long drain queue up instead of vanishing (``_pending``).  The round-3
    advisor flagged that the per-poll read cap widened the rotation-loss
    window from one poll's delta to a whole cold-start backlog — holding
    fds removes the window instead of just measuring it.  (Cost: a
    rotated-away file's disk space lives until its drain finishes.)  Two
    residual lossy cases, all documented: a generation created AND rotated
    away entirely between two polls was never observable; truncate-in-place
    (same inode shrinks) overwrites its tail before the tailer can see it —
    counted in ``truncated_events``; and a producer that keeps appending to
    a rotated-away or unlinked file more than one poll interval after the
    tailer last saw data there (the switch waits one extra EOF poll as
    grace for exactly this writer-keeps-fd rotation style).
    """

    # Per-poll read cap: a cold start against a month-scale backlog (tens
    # of GB) must stream through bounded memory, not parse the whole delta
    # into one Python list (observed: >50 GB RSS on a 20 GB backlog).  The
    # run loop drains the backlog across successive polls, refreshing along
    # the way.
    MAX_POLL_BYTES = 64 << 20
    # Wall-clock grace between a rotated-away generation's first observed
    # EOF and the switch away from it: a rename-rotation writer keeps its
    # fd (and may still flush a torn line's remainder) until it reopens
    # the path.
    GRACE_S = 0.25

    def __init__(self, path: str, max_poll_bytes: int | None = None):
        self.path = path
        self._f = None                  # persistent handle (see class doc)
        self._pending = []              # successor-generation fds, in order
        self._carry = b""
        self.max_poll_bytes = max_poll_bytes or self.MAX_POLL_BYTES
        # True when more data is already on disk (read cap hit, or a drained
        # rotation left a fresh file pending): poll again without sleeping.
        self.backlog = False
        # Malformed complete lines are skipped, never wedge the stream — but
        # visibly: counted here and logged, so a corrupted producer degrades
        # to a diagnosable signal instead of silent "no data".
        self.dropped = 0
        # Truncate-in-place occurrences — the only rotation style that can
        # still lose data (its loss is unquantifiable: the overwritten tail
        # was never observable).
        self.truncated_events = 0
        # Wall-clock instant the current (rotated-away or unlinked)
        # generation was first seen at EOF — the switch grace anchor (see
        # poll()).  Wall-clock, not a poll count: callers may re-poll
        # microseconds apart (run() skips its sleep after a non-empty
        # poll), which would make a counted grace effectively zero.
        self._eof_since: float | None = None

    def close(self) -> None:
        """Release every held file handle.  For shutdown: a reused tailer
        would re-read the path from the start (duplicates)."""
        if self._f is not None:
            self._f.close()
            self._f = None
        for f in self._pending:
            f.close()
        self._pending.clear()
        self._carry = b""

    def _parse(self, chunk: bytes) -> list[Bucket]:
        data = self._carry + chunk
        lines = data.split(b"\n")
        self._carry = lines.pop()  # empty when data ends with a newline
        buckets = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                buckets.append(Bucket.from_dict(json.loads(line)))
            except (ValueError, KeyError, TypeError):
                self.dropped += 1
                # First drop verbatim, then every 1000th — a half-garbage
                # backlog must not stall the poll loop on print I/O.
                if self.dropped == 1 or self.dropped % 1000 == 0:
                    print(f"stream: dropped malformed line "
                          f"(total {self.dropped}) from {self.path} "
                          f"({line[:80]!r})")
        return buckets

    def _watch_for_rotation(self) -> None:
        """Open a handle to a new path generation the moment it is seen, so
        rotations during a long drain queue up instead of vanishing."""
        try:
            st = os.stat(self.path)
        except OSError:
            return
        tail = self._pending[-1] if self._pending else self._f
        tst = os.fstat(tail.fileno())
        if (st.st_ino, st.st_dev) == (tst.st_ino, tst.st_dev):
            return
        try:
            nf = open(self.path, "rb")
        except OSError:
            return  # rotated away again before we could open; retry next poll
        nst = os.fstat(nf.fileno())
        if (nst.st_ino, nst.st_dev) == (tst.st_ino, tst.st_dev):
            nf.close()  # raced back to the generation we already hold
            return
        self._pending.append(nf)
        print(f"stream: {self.path} was rotated; current generation will "
              f"be drained first (zero loss), new generation queued "
              f"({len(self._pending)} pending)")

    def poll(self) -> list[Bucket]:
        out: list[Bucket] = []
        # Second iteration only after a generation switch, so new data is
        # returned in the same poll that finished the old generation (at
        # most 2 × max_poll_bytes per poll).
        for attempt in (0, 1):
            if self._f is None:
                if self._pending:
                    self._f = self._pending.pop(0)
                else:
                    try:
                        self._f = open(self.path, "rb")
                    except OSError:
                        # File absent (producer still booting / rotating):
                        # clear the backlog flag or run() would busy-spin
                        # instead of sleeping between polls.
                        self.backlog = False
                        return out
                self._carry = b""
            chunk = self._f.read(self.max_poll_bytes)
            if chunk:
                self._eof_since = None
                out.extend(self._parse(chunk))
            fst = os.fstat(self._f.fileno())
            pos = self._f.tell()
            if fst.st_size < pos:
                # Truncated in place (same inode shrank): the old tail is
                # unrecoverable and what it held beyond `pos` was never
                # observable.  Re-read from the top.
                self.truncated_events += 1
                print(f"stream: {self.path} TRUNCATED in place (size "
                      f"{fst.st_size} < consumed {pos}); unread old-tail "
                      f"data is lost (event {self.truncated_events}); "
                      f"re-reading from start")
                self._f.seek(0)
                self._carry = b""
                self.backlog = True
                if attempt == 0:
                    continue
                return out
            self._watch_for_rotation()
            if fst.st_size > pos:
                # Current generation not yet drained (read cap hit).
                self.backlog = True
                return out
            if not self._pending:
                # At EOF of the newest known generation: idle.  (If the
                # path rotated but the open raced, _watch retries next
                # poll; the held fd keeps the data safe meanwhile.)  If
                # the path itself is gone — unlinked with nothing
                # recreated — holding the drained fd would pin the
                # unlinked inode's disk space for the process lifetime:
                # release it after flushing the carry.  Appends a
                # still-running producer makes to the unlinked file after
                # this point are a documented residual loss.
                try:
                    os.stat(self.path)
                except OSError:
                    now = time.monotonic()
                    if self._eof_since is None:
                        self._eof_since = now
                    elif now - self._eof_since >= self.GRACE_S:
                        if self._carry:
                            out.extend(self._parse(b"\n"))
                        self._f.close()
                        self._f = None
                        self._eof_since = None
                self.backlog = False
                return out
            # Drained a rotated-away generation — but a momentary EOF is
            # not proof the producer is done: a standard rename-rotation
            # writer keeps its fd (and may still flush a torn line's
            # remainder) until it reopens the path.  Hold the fd for
            # GRACE_S of WALL CLOCK after the first EOF sighting before
            # switching; only then is an unterminated final line treated
            # as complete and flushed.  (backlog stays False meanwhile so
            # a sleeping caller isn't spun; an eager caller re-polling
            # instantly still cannot shrink the wall-clock grace.)
            now = time.monotonic()
            if self._eof_since is None:
                self._eof_since = now
            if now - self._eof_since < self.GRACE_S:
                self.backlog = False
                return out
            if self._carry:
                out.extend(self._parse(b"\n"))
            self._f.close()
            self._f = None
            self._eof_since = None
            print(f"stream: {self.path} rotation drain complete (zero "
                  f"loss); switching to the next generation "
                  f"({len(self._pending)} queued)")
            self.backlog = True
            if attempt == 0:
                continue
            return out
        return out


def expand_minmax(old: MinMaxStats | None, new: MinMaxStats) -> MinMaxStats:
    """Monotone union of observed ranges (see module docstring)."""
    if old is None:
        return new
    return MinMaxStats(
        min=np.minimum(old.min, new.min),
        max=np.maximum(old.max, new.max),
    )


@dataclasses.dataclass
class StreamConfig:
    refresh_buckets: int = 60        # fine-tune after this many new buckets
    finetune_epochs: int = 2
    history_max: int = 4096          # retained buckets (memory bound)
    eval_holdout: int = 8            # newest windows held out per refresh
    poll_interval_s: float = 0.5
    keep_checkpoints: int = 3        # newest steps retained (disk bound)


@dataclasses.dataclass
class RefreshResult:
    refresh: int
    num_buckets: int                 # retained corpus length at refresh time
    train_loss: float
    eval_loss: float
    checkpoint_path: str | None
    # What fired this refresh: "cadence" (the refresh_buckets counter),
    # "drift" (DriftController auto-trigger), or "manual"
    # (DriftController.force_retrain).
    trigger: str = "cadence"
    # Host-ETL health counters (filled by run(); zero for direct refresh()
    # calls).  etl_stall_s is the train thread's host-ETL cost since the
    # previous refresh: with overlap OFF it is time spent featurizing
    # inline; with overlap ON it is time spent blocked on the ETL queue
    # for data that did arrive (idle waits on a quiet source don't count —
    # that is the source's cadence, not ETL falling behind).
    etl_stall_s: float = 0.0
    # Buckets featurized by the ETL thread but not yet ingested when this
    # refresh started (queue depth = how far ETL ran ahead; 0 when serial).
    etl_lag_buckets: int = 0
    # Cumulative malformed lines dropped by the tailer.
    etl_dropped: int = 0


class StreamingTrainer:
    """Tail → featurize → fine-tune → checkpoint, repeatedly.

    >>> st = StreamingTrainer(config, stream_cfg, ckpt_dir="/ckpts")
    >>> for result in st.run(tailer):           # forever, or until stopped
    ...     print(result.refresh, result.eval_loss)
    """

    def __init__(self, config: Config, stream: StreamConfig,
                 ckpt_dir: str | None = None,
                 feature_config: FeaturizeConfig | None = None):
        fc = feature_config or FeaturizeConfig(
            hash_features=True, capacity=config.model.feature_dim)
        if not fc.hash_features or fc.capacity <= 0:
            raise ValueError(
                "streaming requires hash featurization with fixed capacity "
                "(see module docstring)")
        self.config = config
        self.stream = stream
        self.ckpt_dir = ckpt_dir
        self.space = CallPathSpace(config=fc).freeze()
        # Retained corpus: preallocated contiguous rings (train/data.py
        # SeriesRing), not deques of per-bucket arrays — ingest featurizes
        # straight into the traffic ring's next slot (zero allocation on
        # the poll/ETL path) and refresh() windows the zero-copy contiguous
        # views in O(1) instead of re-stacking O(history) rows.
        #
        # Sparse-first mode (TrainConfig.sparse_feed — the 10k-endpoint
        # tier): the traffic half is a padded-COO SparseSeriesRing
        # instead, ingested via extract_sparse and fed to the device as
        # (cols, vals) with a single on-device densify inside the train
        # executables; no dense [T, F] (or [N, W, F]) traffic tensor ever
        # materializes on this path — ~F/(2K) less ring memory AND feed
        # bytes at F=10240, with refresh losses bit-identical to the
        # dense reference (tests/test_sparse.py).  Targets stay dense
        # (E is small).
        self.sparse = bool(config.train.sparse_feed)
        if self.sparse:
            self.traffic = SparseSeriesRing(
                stream.history_max, self.space.capacity,
                config.train.sparse_nnz_cap)
        else:
            self.traffic = SeriesRing(stream.history_max,
                                      self.space.capacity)
        self.metrics: deque[dict[str, float]] = deque(maxlen=stream.history_max)
        # Targets ring mirrors the metrics deque as float32 rows once the
        # metric set freezes (same [t, i] = v writes _targets() used to do
        # per refresh, done once per bucket instead of once per refresh).
        self._target_ring: SeriesRing | None = None
        self._name_pos: dict[str, int] | None = None
        self.metric_names: list[str] | None = None
        self.trainer: Trainer | None = None
        self.state: TrainState | None = None
        self.x_stats: MinMaxStats | None = None
        self.y_stats: MinMaxStats | None = None
        # honest observed per-column traffic ranges (x_stats are derived
        # from this each refresh — module docstring, per-feature stats)
        self.x_union: MinMaxStats | None = None
        self._warned_new_metrics: set[str] = set()
        self._pending = 0
        self._refresh_count = 0
        # Monotone ingest watermark (buckets ever committed, across
        # resumes): rides in every checkpoint/snapshot sidecar so a
        # restarted stream knows how far the corpus had advanced — the
        # retained-ring half of the preemption cursor (ROADMAP item 7).
        self._ingested_total = 0
        # The active stream source (set by run()) and the source-side
        # half of the watermark convention it shares with the sidecar:
        # sources exposing ingest_watermark()/resume_from() — the wire
        # receiver (data/wire.py) and LiveEndpointTailer — persist their
        # cursor next to _ingested_total and get it back on resume, so a
        # restarted stream never double-counts replayed spans.
        self._source = None
        self._resume_source_watermark: dict | None = None
        # Set on resume: the delta mask the restored params were TRAINED
        # with.  refresh() must keep using it — y_stats and params both
        # encode the target space, so silently switching a resumed stream
        # to this config's delta_resources would collapse the normalized
        # range and cumsum level-scale outputs.
        self._resumed_delta_mask: np.ndarray | None = None
        # The drift→retrain loop (DriftController via attach_quality):
        # on_bucket fires after every ingest, on_refresh after every
        # fine-tune; request_refresh() below is its trigger.
        self.quality: "DriftController | None" = None
        self._force_refresh: str | None = None
        self._maybe_resume()

    # -- ingestion ------------------------------------------------------

    def ingest(self, bucket: Bucket) -> None:
        if self.sparse:
            # The sparse ingest never touches a [capacity]-wide buffer:
            # extract_sparse returns the bucket's (cols, counts) pair and
            # the ring stores it padded to the K cap.
            row = self.space.extract_sparse(bucket.traces)
            self.traffic.append_sparse(*row)
        else:
            # extract(out=...) fills the ring's next slot in place: no
            # fresh [capacity] float32 per bucket on the poll thread.
            row = self.space.extract(bucket.traces,
                                     out=self.traffic.append_slot())
        metrics_row = {m.key: m.value for m in bucket.metrics}
        self._commit_metrics(metrics_row)
        if self.quality is not None:
            self.quality.on_bucket(row, metrics_row)

    def _featurize(self, bucket: Bucket) -> tuple:
        """Featurize off the train thread (overlap mode): the returned row
        (dense [capacity] vector, or a sparse ``(cols, vals)`` pair) is
        owned by the caller and committed later via _ingest_featurized,
        so the shared rings are only ever touched by the train thread."""
        row = (self.space.extract_sparse(bucket.traces) if self.sparse
               else self.space.extract(bucket.traces))
        return (row, {m.key: m.value for m in bucket.metrics})

    def _ingest_featurized(self, feat: tuple) -> None:
        row, metrics_row = feat
        if self.sparse:
            self.traffic.append_sparse(*row)
        else:
            self.traffic.append_slot()[:] = row
        self._commit_metrics(metrics_row)
        if self.quality is not None:
            self.quality.on_bucket(row, metrics_row)

    def _commit_metrics(self, row: dict[str, float]) -> None:
        self.metrics.append(row)
        if self._target_ring is not None:
            self._append_target_row(row)
        self._pending += 1
        self._ingested_total += 1

    def _append_target_row(self, row: dict[str, float]) -> None:
        slot = self._target_ring.append_slot()
        slot[:] = 0.0
        for k, v in row.items():
            i = self._name_pos.get(k)
            if i is None:
                if k not in self._warned_new_metrics:
                    self._warned_new_metrics.add(k)
                    print(f"stream: metric {k!r} appeared after the "
                          "metric set froze; dropping it")
                continue
            slot[i] = v

    @property
    def num_buckets(self) -> int:
        return len(self.traffic)

    def clear_history(self) -> None:
        """Drop every retained bucket (traffic, metrics, targets) while
        keeping the frozen metric set, stats, and model state — the
        history-rotation scenario the quiet-column stats policy covers."""
        self.traffic.clear()
        self.metrics.clear()
        if self._target_ring is not None:
            self._target_ring.clear()

    def _ensure_target_ring(self) -> None:
        """Build the float32 targets ring for the frozen metric set and
        backfill it from the retained metric dicts (one-time O(history);
        every later bucket appends incrementally)."""
        self._name_pos = {n: i for i, n in enumerate(self.metric_names)}
        self._target_ring = SeriesRing(self.stream.history_max,
                                       len(self.metric_names))
        for row in self.metrics:
            self._append_target_row(row)

    def _freeze_metrics(self) -> list[str]:
        if self.metric_names is None:
            union: set[str] = set()
            for row in self.metrics:
                union |= set(row)
            self.metric_names = sorted(union)
            self._ensure_target_ring()
        return self.metric_names

    def _targets(self) -> np.ndarray:
        """Zero-copy [T, E] float32 target matrix for the retained corpus.

        Incrementally maintained (_append_target_row writes the identical
        ``out[t, i] = v`` float32 conversions the historical per-refresh
        rebuild performed, so the matrix is bit-identical to a full
        recompute — tests/test_stream.py pins this).  Valid until the next
        ingest (SeriesRing.view contract)."""
        self._freeze_metrics()
        return self._target_ring.view()

    # -- refresh --------------------------------------------------------

    def attach_quality(self, controller: "DriftController") -> None:
        """Wire the drift→retrain loop: ``controller.on_bucket`` fires
        after every ingest (both ETL modes — ingest happens on the train
        thread either way), ``controller.on_refresh`` after every
        fine-tune."""
        self.quality = controller

    def request_refresh(self, reason: str = "manual") -> None:
        """Queue an out-of-cadence refresh (the DriftController's
        trigger): the next readiness check fires a fine-tune regardless
        of the ``refresh_buckets`` counter, provided the corpus is big
        enough to train at all.  The reason rides in
        ``RefreshResult.trigger``."""
        self._force_refresh = reason

    def current_delta_mask(self) -> np.ndarray:
        """The delta mask the CURRENT params encode (the resumed
        checkpoint's when one exists — see refresh())."""
        if self._resumed_delta_mask is not None:
            return self._resumed_delta_mask
        return delta_mask(self._freeze_metrics(),
                          self.config.train.delta_resources)

    def ready(self) -> bool:
        if self.trainer is not None and self.trainer.remesh_in_flight:
            # A remesh is rebuilding/restoring: refresh decisions are
            # DEFERRED, never dropped — the pending count and any queued
            # _force_refresh trigger survive untouched and fire at the
            # next readiness check.
            return False
        w = self.config.train.window_size
        min_windows = self.stream.eval_holdout + 2
        due = (self._pending >= self.stream.refresh_buckets
               or self._force_refresh is not None)
        return due and self.num_buckets > w + min_windows

    def refresh(self) -> RefreshResult:
        """Fine-tune on the retained corpus; returns the refresh record."""
        trigger, self._force_refresh = (self._force_refresh or "cadence",
                                        None)
        w = self.config.train.window_size
        # Zero-copy contiguous views of the retained corpus (SeriesRing):
        # assembly is O(1) where the deque-era np.stack + per-dict target
        # rebuild were O(history).  Both views are consumed (normalized or
        # windowed into device arrays) before refresh returns, within the
        # rings' validity window.
        raw_targets = self._targets()
        # Level-type resources train as per-bucket increments (the same
        # transform prepare_dataset applies — train/data.py).  Recomputed
        # over the full retained series each refresh, so there is no
        # cross-chunk carry to track; the deque holds raw levels.  A
        # resumed stream keeps the mask its checkpoint was trained with
        # (_maybe_resume) — the restored y_stats/params encode it.
        dmask = delta_mask(self._freeze_metrics(),
                           self.config.train.delta_resources)
        if self._resumed_delta_mask is not None:
            if not np.array_equal(dmask, self._resumed_delta_mask):
                print("stream: config delta_resources disagrees with the "
                      "resumed checkpoint's delta mask; keeping the "
                      "checkpoint's (retrain from scratch to change it)")
            dmask = self._resumed_delta_mask
        targets = to_increments(raw_targets, dmask)

        if self.sparse:
            # Sparse-first: no dense traffic tensor, windowed or
            # otherwise, ever materializes here.  Window counts follow
            # sliding_windows semantics (N = T - w) and the per-feature
            # stats come from the padded-COO rows directly —
            # sparse_minmax is bit-identical to minmax_fit over the
            # equivalent dense train-span windows (the span rows
            # [0, split + w - 1) ARE the train windows' union, the same
            # equivalence prepare_dataset relies on).
            cols_v, vals_v, nnz_v = self.traffic.view()
            n_windows = len(self.traffic) - w
            x = None
        else:
            traffic = self.traffic.view()
            x = sliding_windows(traffic, w)
            n_windows = len(x)
        y = sliding_windows(targets, w)
        holdout = min(self.stream.eval_holdout, n_windows - 1)
        split = n_windows - holdout

        # Expanding stats: union with every past refresh (monotone), fit
        # per column — traffic per feature, targets per metric (module
        # docstring: "Per-feature traffic stats").
        if self.sparse:
            new_x_stats = sparse_minmax(cols_v, vals_v, nnz_v,
                                        split + w - 1, self.space.capacity)
        else:
            new_x_stats = minmax_fit(x, split, axis=(0, 1))
        self.x_union = expand_minmax(self.x_union, new_x_stats)
        self.y_stats = expand_minmax(self.y_stats,
                                     minmax_fit(y, split, axis=(0, 1)))
        # Effective traffic stats: degenerate columns would pass serve-time
        # values through raw (MinMaxStats zero-range passthrough), so give
        # them a usable scale — their own level if constant-nonzero, the
        # global max if never active.  Derived from the honest union every
        # refresh, so a column that merely went quiet keeps its own range.
        union = self.x_union
        degenerate = np.asarray(union.range == 0.0)
        glob = np.float32(np.max(union.max))
        self.x_stats = MinMaxStats(
            min=np.where(degenerate, np.minimum(union.min, 0.0),
                         union.min).astype(np.float32),
            max=np.where(degenerate,
                         np.where(union.max > 0, union.max, glob),
                         union.max).astype(np.float32))

        y_n = self.y_stats.apply(y).astype(np.float32)
        if self.sparse:
            # RAW cols/vals ride in the bundle (zero-copy ring views,
            # consumed by stage_dataset before refresh returns);
            # normalization happens on device with the staged stats.
            bundle = DatasetBundle(
                x_train=None, y_train=y_n[:split],
                x_test=None, y_test=y_n[split:],
                x_stats=self.x_stats, y_stats=self.y_stats,
                metric_names=self._freeze_metrics(), split=split,
                window_size=w, space_dict=self.space.to_dict(),
                delta_mask=dmask, raw_targets=raw_targets,
                x_base=None,
                y_base=self.y_stats.apply(targets).astype(np.float32),
                x_cols=cols_v, x_vals=vals_v, x_nnz=nnz_v,
                sparse_capacity=self.space.capacity,
                n_train=split, n_test=n_windows - split,
            )
        else:
            x_n = self.x_stats.apply(x).astype(np.float32)
            bundle = DatasetBundle(
                x_train=x_n[:split], y_train=y_n[:split],
                x_test=x_n[split:], y_test=y_n[split:],
                x_stats=self.x_stats, y_stats=self.y_stats,
                metric_names=self._freeze_metrics(), split=split,
                window_size=w, space_dict=self.space.to_dict(),
                delta_mask=dmask, raw_targets=raw_targets,
                x_base=self.x_stats.apply(traffic).astype(np.float32),
                y_base=self.y_stats.apply(targets).astype(np.float32),
            )

        if self.trainer is None:
            model = dataclasses.replace(
                self.config.model, feature_dim=self.space.capacity,
                num_metrics=len(bundle.metric_names))
            self.config = dataclasses.replace(self.config, model=model)
            self.trainer = Trainer(self.config, self.space.capacity,
                                   bundle.metric_names)
            self._wire_snapshots()
        if self.state is None:
            self.state = self.trainer.init_state(
                self.trainer.sample_input(bundle))

        data_rng = np.random.default_rng(
            self.config.train.seed + self._refresh_count)
        train_loss = float("nan")
        # Device-resident feed for the fine-tune epochs: the staged base
        # is W× less transfer than shipping overlapping windows even for
        # a single epoch (re-staged each refresh — the series grew).
        staged = self.trainer.stage_dataset(bundle)
        # The stream joins the trainer's elastic fault barrier
        # (TrainConfig.elastic): a device loss mid-fine-tune remeshes,
        # restores the newest durable checkpoint (a mid-refresh snapshot
        # or the last refresh-end save), and re-runs the interrupted
        # epoch — the refresh is DEFERRED through the remesh, never
        # dropped, and a DriftController trigger queued meanwhile stays
        # queued (self._force_refresh survives untouched).  The stream
        # deliberately does not plan-replay the interrupted fine-tune
        # (see _wire_snapshots); bounded attempts + backoff are the
        # trainer's knobs.
        from deeprest_tpu.parallel.elastic import (
            RemeshExhaustedError, is_device_loss,
        )

        elastic = self.config.train.elastic
        epochs_done = 0
        attempts = 0
        while True:
            reason = None
            try:
                while epochs_done < self.stream.finetune_epochs:
                    self.state, train_loss = self.trainer.train_epoch(
                        self.state, bundle, data_rng, staged=staged)
                    epochs_done += 1
                eval_loss, _ = self.trainer.evaluate(self.state, bundle,
                                                     staged=staged)
                break
            except Exception as exc:
                if not elastic or not is_device_loss(exc):
                    raise
                attempts += 1
                if attempts > self.config.train.remesh_max_attempts:
                    raise RemeshExhaustedError(
                        f"device loss #{attempts} mid-refresh exceeds "
                        "remesh_max_attempts="
                        f"{self.config.train.remesh_max_attempts}"
                    ) from exc
                reason = f"{type(exc).__name__}: {exc}"
            # Recovery outside the except block (the traceback pins the
            # failed epoch's old-mesh buffers — same discipline as
            # Trainer._run_epochs_elastic).
            staged = None
            staged = self._handle_device_loss(bundle, attempts, reason)

        path = None
        self._pending = 0
        self._refresh_count += 1
        if self.ckpt_dir:
            # The counter rides in the checkpoint sidecar so it is bound
            # atomically to the step it describes — a crash can never leave
            # counter and params disagreeing.
            path = self.trainer.save(
                self.ckpt_dir, self.state, bundle,
                extra_host_state={
                    "stream_refresh_count": self._refresh_count,
                    "stream_x_union": self.x_union.to_dict(),
                    "stream_ring_watermark": self._ring_watermark(),
                })
            from deeprest_tpu.train.checkpoint import prune_checkpoints

            prune_checkpoints(self.ckpt_dir, self.stream.keep_checkpoints)
        result = RefreshResult(
            refresh=self._refresh_count, num_buckets=self.num_buckets,
            train_loss=train_loss, eval_loss=float(eval_loss),
            checkpoint_path=path, trigger=trigger)
        if self.quality is not None:
            # After the checkpoint is on disk: the controller re-anchors
            # the drift reference to what these params just trained on
            # and (for drift/manual triggers) hot-swaps the serving plane.
            self.quality.on_refresh(result)
        return result

    # -- preemption snapshots (ROADMAP item 7, dynamic half) ------------

    def _ring_watermark(self) -> dict:
        """The retained-ring half of the preemption cursor: how far the
        corpus had advanced when this checkpoint was cut.  When the
        active source speaks the watermark convention (wire receiver,
        live tailer), its own cursor rides along under ``source`` so
        resume can hand it back via ``resume_from`` — the stream and its
        source re-anchor on the SAME instant and replays dedup instead
        of double-counting."""
        out = {
            "ingested_total": int(self._ingested_total),
            "retained_buckets": int(self.num_buckets),
            "pending_buckets": int(self._pending),
        }
        wm_fn = getattr(self._source, "ingest_watermark", None)
        if callable(wm_fn):
            sw = wm_fn()
            if isinstance(sw, dict):
                out["source"] = sw
        return out

    def _snapshot_extra(self) -> dict:
        out = {
            "stream_refresh_count": self._refresh_count,
            "stream_ring_watermark": self._ring_watermark(),
        }
        if self.x_union is not None:
            out["stream_x_union"] = self.x_union.to_dict()
        return out

    def _wire_snapshots(self) -> None:
        """Mid-refresh preemption snapshots (TrainConfig.
        snapshot_every_steps > 0): every N fine-tune steps the embedded
        trainer checkpoints atomically WITH the full stream sidecar
        (frozen metric set, stats, refresh counter, retained-ring
        watermarks via ``extra_fn``), so a stream killed mid-refresh
        resumes from params at most N steps stale instead of losing the
        whole refresh — _maybe_resume adopts a snapshot exactly like a
        refresh checkpoint.  The stream deliberately does NOT plan-replay
        the interrupted fine-tune (its refresh loop re-trains over the
        retained corpus every cycle anyway); the epoch-plan cursor
        resume is Trainer.resume_training's offline contract."""
        n = self.config.train.snapshot_every_steps
        if n and self.ckpt_dir and self.trainer is not None:
            self.trainer.enable_snapshots(self.ckpt_dir, n,
                                          extra_fn=self._snapshot_extra)

    def _handle_device_loss(self, bundle: DatasetBundle, attempt: int,
                            reason: str):
        """The stream's leg of the elastic fault barrier: remesh the
        embedded trainer onto the survivors, restore the newest durable
        checkpoint (mid-refresh snapshot or refresh-end save — both
        carry the full stream sidecar), and re-stage the refresh bundle
        onto the new mesh.  Returns the fresh ``staged`` feed.  The
        restored params are at most ``snapshot_every_steps`` stale; the
        interrupted fine-tune epoch re-runs from them (the stream never
        plan-replays — its refresh re-trains the retained corpus every
        cycle anyway)."""
        from deeprest_tpu.train.checkpoint import (
            list_steps, load_sidecar, restore_checkpoint,
        )

        tr = self.trainer
        sw = obs_metrics.Stopwatch()
        tr._remesh_in_flight = True
        try:
            tr._m_device_losses.inc()
            tr.remesh(attempt=attempt, reason=reason)
            state = step = None
            if self.ckpt_dir:
                for cand in reversed(list_steps(self.ckpt_dir)):
                    if load_sidecar(self.ckpt_dir, cand,
                                    missing_ok=True) is not None:
                        step = cand
                        break
            if step is not None:
                template = tr.init_state(tr.sample_input(bundle))
                state, _ = restore_checkpoint(self.ckpt_dir, template,
                                              step=step)
            if state is None:
                # lost before anything durable existed: re-init on the
                # new mesh, like a restarted stream process would
                state = tr.init_state(tr.sample_input(bundle))
            self.state = state
            recovery_s = sw.elapsed()
            tr.remesh_count += 1
            tr.last_remesh = {
                "attempt": attempt, "restored_step": step,
                "mesh": {a: int(tr.mesh.shape[a])
                         for a in ("data", "expert", "model")},
                "recovery_s": recovery_s,
            }
            tr.remesh_history.append(tr.last_remesh)
            tr._m_recovery.set(recovery_s)
            tr._m_remeshes.inc(outcome="ok")
            return tr.stage_dataset(bundle)
        finally:
            tr._remesh_in_flight = False

    # -- resume ---------------------------------------------------------

    def _maybe_resume(self) -> None:
        """Adopt the latest checkpoint's frozen state (metric set, stats,
        params) so a restarted stream continues rather than restarts."""
        if not self.ckpt_dir:
            return
        from deeprest_tpu.train.checkpoint import (
            list_steps, load_sidecar, restore_checkpoint,
        )

        # Newest step with a readable sidecar: a crash between the orbax
        # save and the sidecar write leaves an incomplete step dir, which
        # must not wedge resume from the last complete one.
        step = extra = None
        for candidate in reversed(list_steps(self.ckpt_dir)):
            extra = load_sidecar(self.ckpt_dir, candidate, missing_ok=True)
            if extra is not None:
                step = candidate
                break
            print(f"stream: checkpoint step {candidate} has no sidecar "
                  "(crash mid-save?); falling back to the previous one")
        if step is None:
            return
        feature_dim = int(extra["feature_dim"])
        if feature_dim != self.space.capacity:
            raise ValueError(
                f"checkpoint feature_dim {feature_dim} != "
                f"stream capacity {self.space.capacity}")
        self.metric_names = list(extra["metric_names"])
        self._ensure_target_ring()
        self.x_stats = MinMaxStats.from_dict(extra["x_stats"])
        self.y_stats = MinMaxStats.from_dict(extra["y_stats"])
        # The delta mask the checkpoint was trained with.  Pre-delta
        # sidecars have no key: those params predict absolute levels, so
        # resume with the transform OFF rather than silently flipping the
        # target semantics under restored y_stats/params.
        dm = extra.get("delta_mask")
        if dm is not None:
            self._resumed_delta_mask = np.asarray(dm, bool)
        else:
            self._resumed_delta_mask = np.zeros(len(self.metric_names), bool)
            if delta_mask(self.metric_names,
                          self.config.train.delta_resources).any():
                print("stream: checkpoint predates the delta formulation; "
                      "resuming with absolute-level targets (retrain from "
                      "scratch to adopt delta_resources)")
        # Old checkpoints predate the honest union; effective stats are the
        # closest available stand-in (slightly sticky for dead columns).
        self.x_union = MinMaxStats.from_dict(
            extra.get("stream_x_union", extra["x_stats"]))
        model = dataclasses.replace(
            self.config.model, feature_dim=feature_dim,
            num_metrics=len(self.metric_names))
        self.config = dataclasses.replace(self.config, model=model)
        self.trainer = Trainer(self.config, feature_dim, self.metric_names)
        self._wire_snapshots()
        target = self.trainer.init_state(np.zeros(  # graftlint: disable=DN001 -- one [1, W, F] init SAMPLE (shape donor for param init), not a corpus-scale materialization
            (1, self.config.train.window_size, feature_dim), np.float32))
        self.state, _ = restore_checkpoint(self.ckpt_dir, target, step=step)
        try:
            self._refresh_count = int(extra.get("stream_refresh_count", 0))
        except (TypeError, ValueError):
            print("stream: checkpoint carries a malformed "
                  "stream_refresh_count; numbering restarts at 0")
        wm = extra.get("stream_ring_watermark")
        if isinstance(wm, dict):
            try:
                # continue the monotone ingest watermark across restarts
                self._ingested_total = int(wm.get("ingested_total", 0))
            except (TypeError, ValueError):
                pass
            sw = wm.get("source")
            if isinstance(sw, dict):
                # handed to the source in run() via resume_from()
                self._resume_source_watermark = sw
        print(f"stream: resumed from {self.ckpt_dir} "
              f"(refresh {self._refresh_count}, "
              f"{len(self.metric_names)} metrics frozen)")

    # -- driver ---------------------------------------------------------

    def run(self, tailer: BucketTailer,
            max_refreshes: int | None = None,
            should_stop: Callable[[], bool] | None = None,
            deadline_s: float | None = None) -> Iterator[RefreshResult]:
        """Poll the tailer forever (or until bounded), yielding one
        RefreshResult per fine-tune cycle.

        ``max_refreshes`` bounds refreshes performed by *this* call — a
        resumed stream's persisted lifetime counter affects numbering
        only, so re-running the same bounded command always does the same
        amount of work.

        With ``Config.etl.overlap`` (default on) the tail→parse→featurize
        work runs on a background ETL thread, double-buffered against the
        device fine-tune: while refresh() trains, the ETL thread keeps
        draining the tailer into a bounded featurized-bucket queue
        (backpressure: a full queue blocks the ETL thread, which stops
        consuming the tailer), so the train thread ingests precomputed
        rows instead of stalling on host ETL.  Refresh BOUNDARIES are
        identical to the serial path: poll batches stay atomic through
        the queue and readiness is checked once per batch, exactly as the
        serial loop does — same buckets in, same refresh results out
        (tests/test_stream.py pins this determinism).

        A FEATURIZED source (``tailer.featurized`` — the wire receiver,
        which featurizes on its own connection threads) yields
        ready-made ``(row, metrics_row)`` tuples; both loops commit
        those via ``_ingest_featurized`` instead of re-featurizing.  A
        source speaking the watermark convention gets the sidecar's
        persisted cursor handed back here before the first poll.
        """
        self._source = tailer
        rf = getattr(tailer, "resume_from", None)
        if callable(rf) and self._resume_source_watermark is not None:
            rf(self._resume_source_watermark)
        if getattr(self.config, "etl", None) is not None \
                and self.config.etl.overlap:
            yield from self._run_overlapped(tailer, max_refreshes,
                                            should_stop, deadline_s)
        else:
            yield from self._run_serial(tailer, max_refreshes,
                                        should_stop, deadline_s)

    def _finish_refresh(self, stall_s: float, lag: int,
                        dropped: int) -> RefreshResult:
        r = self.refresh()
        r.etl_stall_s = stall_s
        r.etl_lag_buckets = lag
        r.etl_dropped = dropped
        # ETL-health signals into the obs registry (one write per refresh
        # — never on the poll/ingest path): the scrapeable twin of the
        # RefreshResult fields the stream CLI prints.
        reg = obs_metrics.REGISTRY
        reg.counter("deeprest_stream_refreshes_total",
                    "fine-tune refreshes performed").inc()
        reg.counter("deeprest_etl_stall_seconds_total",
                    "train-thread seconds blocked on host ETL").inc(stall_s)
        reg.gauge("deeprest_etl_lag_buckets",
                  "featurized-but-not-ingested backlog at refresh").set(lag)
        reg.gauge("deeprest_etl_dropped_total",
                  "cumulative malformed lines dropped by the tailer").set(
                      dropped)
        reg.gauge("deeprest_stream_retained_buckets",
                  "buckets retained in the streaming corpus").set(
                      r.num_buckets)
        return r

    def _run_serial(self, tailer, max_refreshes, should_stop,
                    deadline_s) -> Iterator[RefreshResult]:
        t0 = time.monotonic()
        performed = 0
        stall = 0.0     # train-thread time spent featurizing since last refresh
        while True:
            if should_stop is not None and should_stop():
                return
            if deadline_s is not None and time.monotonic() - t0 > deadline_s:
                return
            got = tailer.poll()
            if got:
                # Stopwatch (obs/metrics.py): the sanctioned elapsed-time
                # clock OB001 migrates hot serve/train modules onto.
                sw = obs_metrics.Stopwatch()
                if getattr(tailer, "featurized", False):
                    for feat in got:
                        self._ingest_featurized(feat)
                else:
                    for bucket in got:
                        self.ingest(bucket)
                stall += sw.elapsed()
            if self.ready():
                yield self._finish_refresh(
                    stall, 0, int(getattr(tailer, "dropped", 0)))
                stall = 0.0
                performed += 1
                if max_refreshes is not None and performed >= max_refreshes:
                    return
            elif not got and not getattr(tailer, "backlog", False):
                # Sleep only when caught up — while draining a cold-start
                # backlog the next poll should run immediately.
                time.sleep(self.stream.poll_interval_s)

    def _run_overlapped(self, tailer, max_refreshes, should_stop,
                        deadline_s) -> Iterator[RefreshResult]:
        depth = self.config.etl.queue_depth
        buf = _EtlBuffer(max_buckets=depth)
        stop = threading.Event()
        # Deferred commit (data/wire.py): a source whose poll() would
        # ACK-and-watermark at drain must not do so HERE — drained rows
        # sit in buf until the train thread ingests them, and a
        # checkpoint cut in that window would persist a watermark
        # covering rows that are not in the ring (the client, already
        # ACKed, has pruned them: a kill+resume would silently lose
        # them).  Such sources expose poll_deferred()/commit(); the
        # token rides the buffer and the train thread commits
        # post-ingest.
        poll_deferred = getattr(tailer, "poll_deferred", None)
        commit = getattr(tailer, "commit", None)
        deferred = callable(poll_deferred) and callable(commit)

        def etl_loop():
            # The tailer lives on THIS thread only: its counters cross to
            # the train loop through the buffer's lock-protected snapshot
            # (note_dropped), never as bare attribute reads across threads
            # (graftlint TH001 found the original off-lock sharing) — the
            # one sanctioned exception is commit(), which the wire
            # receiver locks internally precisely so the train thread
            # can call it.
            try:
                while not stop.is_set():
                    if deferred:
                        got, token = poll_deferred()
                    else:
                        got, token = tailer.poll(), None
                    buf.note_dropped(int(getattr(tailer, "dropped", 0)))
                    if got:
                        # One queue item per poll batch, kept atomic so the
                        # train thread's readiness checks land on the same
                        # batch boundaries as the serial loop's.  A
                        # featurized source's rows pass straight through
                        # (its own threads already did the ETL work).
                        buf.put(got if getattr(tailer, "featurized", False)
                                else [self._featurize(b) for b in got],
                                stop, token)
                    elif not getattr(tailer, "backlog", False):
                        stop.wait(self.stream.poll_interval_s)
            except BaseException as exc:  # deterministic tailer failures etc.
                buf.fail(exc)
            else:
                buf.fail(None)            # clean exit (stop requested)

        thread = threading.Thread(target=etl_loop, name="deeprest-etl",
                                  daemon=True)
        thread.start()
        t0 = time.monotonic()
        performed = 0
        stall = 0.0     # train-thread time blocked on ETL since last refresh
        try:
            while True:
                if should_stop is not None and should_stop():
                    return
                if deadline_s is not None \
                        and time.monotonic() - t0 > deadline_s:
                    return
                sw = obs_metrics.Stopwatch()
                item = buf.get(timeout=self.stream.poll_interval_s)
                if item is not None:
                    batch, token = item
                    # Only waits that produced data count as ETL stall —
                    # an idle timeout is the source's cadence, not the
                    # featurizer falling behind.
                    stall += sw.elapsed()
                    for feat in batch:
                        self._ingest_featurized(feat)
                    if token is not None:
                        # rows are in the ring: NOW the source may ACK
                        # them and advance the watermark the next
                        # checkpoint persists
                        commit(token)
                if self.ready():
                    yield self._finish_refresh(stall, buf.pending(),
                                               buf.dropped())
                    stall = 0.0
                    performed += 1
                    if max_refreshes is not None \
                            and performed >= max_refreshes:
                        return
        finally:
            stop.set()
            buf.unblock()
            thread.join(timeout=10.0)


def _accepts_reason(fn) -> bool:
    """Does ``fn`` take a ``reason`` keyword (directly or via
    ``**kwargs``)?  The DriftController's reload_fn contract predates
    reason labels; this probe lets reason-aware targets opt in without
    breaking single-arg closures already deployed."""
    if fn is None:
        return False
    import inspect

    try:
        params = inspect.signature(fn).parameters.values()
    except (TypeError, ValueError):
        return False
    return any(p.name == "reason"
               or p.kind is inspect.Parameter.VAR_KEYWORD
               for p in params)


class DriftController:
    """The drift→retrain→hot-reload loop over one StreamingTrainer
    (ROADMAP item 6's act half; obs/quality.py is the detect half).

    Wired via ``trainer.attach_quality(controller)``:

    - every ingested bucket feeds the quality monitor (O(nnz) — the
      traffic row is already featurized) and advances the sweep cadence;
    - every ``sweep_every_buckets`` buckets the monitors run over the
      trailing window (drift PSI/KS, band calibration, the continuous
      not-justified-by-traffic check) using a :class:`WindowBackend`
      whose jitted apply takes params as ARGUMENTS — one compiled
      executable serves every refresh's fresh params (the JX001
      discipline; a per-refresh Predictor would recompile every cycle);
    - when the drift verdict is ACTIVE (hysteresis already absorbed
      noise), ``auto_retrain`` queues an out-of-cadence refresh on the
      retained rings, bounded by ``retrain_cooldown_buckets`` and
      suppressed while an anomaly verdict is active (retraining on
      not-justified-by-traffic consumption would teach the model the
      very thing the sanity check exists to flag) — every suppression is
      counted, by reason;
    - after a drift/manual-triggered refresh lands its checkpoint,
      ``reload_fn(checkpoint_path)`` hot-swaps the serving plane (the
      e2e loop passes a closure over
      ``ReplicaRouter.rolling_reload_from``; a plane watching the
      checkpoint dir via ``serve --watch`` needs no reload_fn at all) —
      reason-aware targets additionally receive ``reason=<trigger>``,
      which labels the rolling reload AND eagerly invalidates the
      serving plane's capacity-surface cache (serve/surface.py);
    - every decision is observable: obs counters by reason + spans
      around retrain triggers and reloads.

    Manual override: ``auto_retrain=False`` keeps the verdicts flowing
    while a human pulls :meth:`force_retrain`.
    """

    def __init__(self, trainer: StreamingTrainer, config=None,
                 reload_fn: Callable[[str], None] | None = None,
                 monitor=None):
        from deeprest_tpu.config import QualityConfig

        self.config = config or QualityConfig(enabled=True)
        self._st = trainer
        self._reload_fn = reload_fn
        # Reason-aware reload targets (service.reload_from, a closure
        # over rolling_reload_from) get the TRIGGER as their reload
        # reason — the capacity-surface cache invalidates eagerly under
        # that label, and /metrics tells drift swaps from cadence ones.
        # Plain single-arg callables keep working unchanged.
        self._reload_takes_reason = _accepts_reason(reload_fn)
        self.monitor = monitor          # built at the first refresh
        self._apply = None              # jitted once, params as args
        self._since_sweep = 0
        self._bucket = 0                # buckets seen by on_bucket
        self._cooldown_until = -1
        self.stats = {"sweeps": 0, "retrains_triggered": 0,
                      "reloads": 0, "suppressed": {}}
        reg = obs_metrics.REGISTRY
        self._m_retrains = reg.counter(
            "deeprest_drift_retrains_total",
            "out-of-cadence retrains triggered by the drift loop",
            labelnames=("trigger",))
        self._m_suppressed = reg.counter(
            "deeprest_drift_retrain_suppressed_total",
            "drift-triggered retrains suppressed, by reason",
            labelnames=("reason",))
        self._m_reloads = reg.counter(
            "deeprest_drift_reloads_total",
            "serving-plane hot reloads pushed by the drift loop")
        trainer.attach_quality(self)

    # -- StreamingTrainer hooks (train thread only) ----------------------

    def on_bucket(self, row, metrics_row: dict) -> None:
        self._bucket += 1
        if self.monitor is None:
            return                      # arms at the first refresh
        if isinstance(row, tuple):
            self.monitor.observe(row[0], row[1], metrics_row)
        else:
            self.monitor.observe_dense(row, metrics_row)
        self._since_sweep += 1
        if self._since_sweep >= self.config.sweep_every_buckets:
            self._since_sweep = 0
            self._sweep()

    def on_refresh(self, result: RefreshResult) -> None:
        if self.monitor is None:
            from deeprest_tpu.obs.quality import QualityMonitor

            self.monitor = QualityMonitor(self._st.metric_names,
                                          self.config)
        # Cold-start warmup for the model-conditioned verdicts: an
        # undertrained band's one-sided excess is indistinguishable from
        # a real anomaly, so calibration/anomaly machines stay disarmed
        # until the model has matured through enough refreshes.
        self.monitor.set_model_armed(
            self._st._refresh_count >= self.config.model_warmup_refreshes)
        # The fresh params trained on the retained rings — those rows ARE
        # the new no-drift reference.
        self.monitor.set_reference(self._ring_rows())
        if result.trigger in ("drift", "manual"):
            # Only a DRIFT-triggered retrain restarts the model-
            # conditioned verdict streams (calibration, anomaly): that is
            # the disambiguation move — recovery is measured against the
            # deliberately-refreshed band, and the excess that SURVIVES
            # it is real anomaly.  Cadence fine-tunes are incremental;
            # resetting on every one would wipe an anomaly streak faster
            # than sustain_enter can accumulate it (measured: a
            # ransomware window spanning many cadence refreshes never
            # flagged) — exactly the flap the hysteresis exists to stop.
            self.monitor.on_model_refresh()
            self._cooldown_until = (self._bucket
                                    + self.config.retrain_cooldown_buckets)
            if self._reload_fn is not None and result.checkpoint_path:
                with obs_spans.RECORDER.span(
                        "drift.reload", component="deeprest-drift") as sp:
                    sp.tag(checkpoint=result.checkpoint_path,
                           trigger=result.trigger)
                    if self._reload_takes_reason:
                        self._reload_fn(result.checkpoint_path,
                                        reason=result.trigger)
                    else:
                        self._reload_fn(result.checkpoint_path)
                self.stats["reloads"] += 1
                self._m_reloads.inc()

    # -- the decide step -------------------------------------------------

    def force_retrain(self) -> None:
        """Manual trigger: next readiness check fires a refresh."""
        self._st.request_refresh("manual")

    def _sweep(self) -> None:
        if self._st.state is None:
            return
        summary = self.monitor.sweep(self._backend())
        if not summary.get("armed"):
            return
        self.stats["sweeps"] += 1
        self._decide()

    def _decide(self) -> None:
        from deeprest_tpu.obs.quality import VERDICT_ANOMALY, VERDICT_DRIFT

        cfg = self.config
        if not self.monitor.any_active(VERDICT_DRIFT):
            return
        reason = None
        if not cfg.auto_retrain:
            reason = "manual-override"
        elif self._bucket < self._cooldown_until:
            reason = "cooldown"
        elif (self.monitor.any_active(VERDICT_ANOMALY)
              and not cfg.retrain_during_anomaly):
            reason = "anomaly-active"
        if reason is not None:
            self.stats["suppressed"][reason] = \
                self.stats["suppressed"].get(reason, 0) + 1
            self._m_suppressed.inc(reason=reason)
            return
        if self._st._force_refresh is not None:
            return                      # a trigger is already queued
        with obs_spans.RECORDER.span("drift.retrain",
                                     component="deeprest-drift") as sp:
            sp.tag(bucket=self._bucket,
                   psi=round(self.monitor.verdicts()
                             ["feature_drift"]["psi"], 4))
            self._st.request_refresh("drift")
        self.stats["retrains_triggered"] += 1
        self._m_retrains.inc(trigger="drift")

    # -- plumbing --------------------------------------------------------

    def _ring_rows(self):
        """The drift-reference rows: the trailing ``reference_window``
        retained buckets (sparse pairs or dense row views — never a
        fresh F-wide allocation).  The tail, not the whole ring: the
        verdict asks whether the live stream differs from what the model
        most recently trained on, so a retrain that adapted to a new
        regime re-anchors the reference there and the drift verdict can
        EXIT instead of forever comparing against a pre/post mixture."""
        st = self._st
        n = len(st.traffic)
        lo = max(0, n - self.config.reference_window)
        if st.sparse:
            cols_v, vals_v, nnz_v = st.traffic.view()
            return [(cols_v[i, :nnz_v[i]], vals_v[i, :nnz_v[i]])
                    for i in range(lo, n)]
        view = st.traffic.view()
        return [view[i] for i in range(lo, n)]

    def _backend(self):
        from deeprest_tpu.obs.quality import WindowBackend

        if self._apply is None:
            import jax

            model = self._st.trainer.model
            self._apply = jax.jit(
                lambda p, x: model.apply({"params": p}, x,
                                         deterministic=True))
        st = self._st
        return WindowBackend(
            self._apply, st.state.params, st.x_stats, st.y_stats,
            st.metric_names, st.config.model.quantiles,
            st.config.train.window_size,
            delta_mask=st.current_delta_mask(),
            feature_dim=st.space.capacity)


class _EtlBuffer:
    """Bounded handoff between the ETL thread and the train loop.

    Items are whole poll batches (lists of featurized buckets); the bound
    is in BUCKETS — ``put`` blocks while the queued bucket count is at the
    limit (backpressure), but always admits at least one batch so a poll
    larger than the whole budget cannot deadlock.  Exceptions from the ETL
    thread are re-raised from ``get`` once the queue drains, so a
    deterministic tailer failure still surfaces to the caller.

    Each batch carries an opaque ``token`` (None for sources without
    deferred commit): a wire source's commit token, which the train
    thread hands back to ``tailer.commit`` only AFTER the batch's rows
    are in the ring — a batch discarded here (stop mid-put, kill) was
    therefore never committed and will be replayed, never lost.
    """

    def __init__(self, max_buckets: int):
        self.max_buckets = max_buckets
        self._cv = threading.Condition()
        self._batches: deque[tuple[list, object]] = deque()
        self._buckets = 0
        self._dropped = 0          # tailer's malformed-line counter snapshot
        self._exc: BaseException | None = None
        self._closed = False

    def put(self, batch: list, stop: threading.Event,
            token=None) -> None:
        with self._cv:
            while self._buckets >= self.max_buckets and not stop.is_set():
                self._cv.wait(0.05)
            if stop.is_set():
                return
            self._batches.append((batch, token))
            self._buckets += len(batch)
            self._cv.notify_all()

    def get(self, timeout: float) -> tuple[list, object] | None:
        with self._cv:
            if not self._batches and self._exc is None and not self._closed:
                self._cv.wait(timeout)
            if self._batches:
                batch, token = self._batches.popleft()
                self._buckets -= len(batch)
                self._cv.notify_all()
                return batch, token
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            return None

    def fail(self, exc: BaseException | None) -> None:
        with self._cv:
            self._exc = exc
            self._closed = True
            self._cv.notify_all()

    def note_dropped(self, total: int) -> None:
        """ETL-thread side: publish the tailer's cumulative malformed-line
        count.  The tailer object itself is owned by the ETL thread; this
        snapshot is the only form its counters cross the thread boundary
        in (lock-protected, so the train loop never reads them racily)."""
        with self._cv:
            self._dropped = total

    def dropped(self) -> int:
        with self._cv:
            return self._dropped

    def pending(self) -> int:
        with self._cv:
            return self._buckets

    def unblock(self) -> None:
        with self._cv:
            self._cv.notify_all()


__all__ = [
    "BucketTailer", "DriftController", "StreamConfig", "StreamingTrainer",
    "RefreshResult", "expand_minmax",
]
