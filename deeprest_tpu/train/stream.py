"""Continuous retraining from a live, growing raw-data corpus.

The reference is strictly offline: capture a corpus with minikube + locust,
run featurize.py, run estimate.py (reference: resource-estimation/
README.md:64-83).  This module closes the loop the reference leaves open
(SURVEY.md §7.3 "streaming retrain ... no reference prior art; design
explicitly"): tail the collector's JSONL as it grows, featurize buckets
incrementally in hash mode (fixed width — no vocabulary pass, no recompile),
and periodically fine-tune the model from its latest state, re-checkpointing
after every refresh.

Design decisions, explicit because there is no reference behavior to match:

- **Hash featurization only.**  Dictionary mode needs a global vocabulary
  pass and can change width; a stream has neither a "global" view nor any
  tolerance for shape changes.  `FeaturizeConfig(hash_features=True,
  capacity=F)` keeps the model input static forever.
- **Expanding min-max normalization.**  Stats are the monotone union of
  every refresh's observed range (never shrink).  Alternatives considered:
  frozen initial stats (reference semantics — breaks under drift: values
  outside the day-one range clip the model's usable dynamic range forever)
  and sliding-window stats (adapt both ways, but re-anchor the output scale
  every refresh, so two checkpoints' predictions are not comparable).  The
  monotone union keeps every checkpoint's de-normalization consistent with
  all earlier ones while still covering drifted ranges; windows are re-
  normalized with the current stats at every refresh.
- **Frozen metric set.**  The expert axis E is part of the compiled model.
  The metric set freezes at the first refresh; components that stop
  reporting fill with zeros, metrics that appear later are dropped (warned
  once).  Restarting the stream from its checkpoint re-adopts the frozen
  set.
- **Recency-holdout eval.**  Each refresh trains on all windows but the
  trailing ``eval_holdout`` and evaluates on those — the stream's notion of
  "unseen" is "newest", which is what capacity planning on drifting traffic
  actually faces.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from typing import Callable, Iterator

import numpy as np

from deeprest_tpu.config import Config, FeaturizeConfig
from deeprest_tpu.data.featurize import CallPathSpace
from deeprest_tpu.data.schema import Bucket
from deeprest_tpu.data.windows import MinMaxStats, sliding_windows
from deeprest_tpu.train.data import DatasetBundle
from deeprest_tpu.train.trainer import Trainer, TrainState


class BucketTailer:
    """Incrementally parse complete JSONL lines appended to a growing file.

    Safe against torn tails: only lines terminated by a newline are parsed;
    a partially-written last line stays buffered until its newline arrives.
    The file may not exist yet at construction (collector still booting).
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._carry = b""

    def poll(self) -> list[Bucket]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size <= self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read(size - self._offset)
        self._offset = size
        data = self._carry + chunk
        lines = data.split(b"\n")
        self._carry = lines.pop()  # empty when data ends with a newline
        buckets = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                buckets.append(Bucket.from_dict(json.loads(line)))
            except (ValueError, KeyError, TypeError):
                continue  # malformed line: skip, never wedge the stream
        return buckets


def expand_minmax(old: MinMaxStats | None, new: MinMaxStats) -> MinMaxStats:
    """Monotone union of observed ranges (see module docstring)."""
    if old is None:
        return new
    return MinMaxStats(
        min=np.minimum(old.min, new.min),
        max=np.maximum(old.max, new.max),
    )


@dataclasses.dataclass
class StreamConfig:
    refresh_buckets: int = 60        # fine-tune after this many new buckets
    finetune_epochs: int = 2
    history_max: int = 4096          # retained buckets (memory bound)
    eval_holdout: int = 8            # newest windows held out per refresh
    poll_interval_s: float = 0.5


@dataclasses.dataclass
class RefreshResult:
    refresh: int
    num_buckets: int                 # retained corpus length at refresh time
    train_loss: float
    eval_loss: float
    checkpoint_path: str | None


class StreamingTrainer:
    """Tail → featurize → fine-tune → checkpoint, repeatedly.

    >>> st = StreamingTrainer(config, stream_cfg, ckpt_dir="/ckpts")
    >>> for result in st.run(tailer):           # forever, or until stopped
    ...     print(result.refresh, result.eval_loss)
    """

    def __init__(self, config: Config, stream: StreamConfig,
                 ckpt_dir: str | None = None,
                 feature_config: FeaturizeConfig | None = None):
        fc = feature_config or FeaturizeConfig(
            hash_features=True, capacity=config.model.feature_dim)
        if not fc.hash_features or fc.capacity <= 0:
            raise ValueError(
                "streaming requires hash featurization with fixed capacity "
                "(see module docstring)")
        self.config = config
        self.stream = stream
        self.ckpt_dir = ckpt_dir
        self.space = CallPathSpace(config=fc).freeze()
        self.traffic: deque[np.ndarray] = deque(maxlen=stream.history_max)
        self.metrics: deque[dict[str, float]] = deque(maxlen=stream.history_max)
        self.metric_names: list[str] | None = None
        self.trainer: Trainer | None = None
        self.state: TrainState | None = None
        self.x_stats: MinMaxStats | None = None
        self.y_stats: MinMaxStats | None = None
        self._warned_new_metrics: set[str] = set()
        self._pending = 0
        self._refresh_count = 0
        self._maybe_resume()

    # -- ingestion ------------------------------------------------------

    def ingest(self, bucket: Bucket) -> None:
        self.traffic.append(self.space.extract(bucket.traces))
        self.metrics.append({m.key: m.value for m in bucket.metrics})
        self._pending += 1

    @property
    def num_buckets(self) -> int:
        return len(self.traffic)

    def _freeze_metrics(self) -> list[str]:
        if self.metric_names is None:
            union: set[str] = set()
            for row in self.metrics:
                union |= set(row)
            self.metric_names = sorted(union)
        return self.metric_names

    def _targets(self) -> np.ndarray:
        names = self._freeze_metrics()
        out = np.zeros((len(self.metrics), len(names)), np.float32)
        name_pos = {n: i for i, n in enumerate(names)}
        for t, row in enumerate(self.metrics):
            for k, v in row.items():
                i = name_pos.get(k)
                if i is None:
                    if k not in self._warned_new_metrics:
                        self._warned_new_metrics.add(k)
                        print(f"stream: metric {k!r} appeared after the "
                              "metric set froze; dropping it")
                    continue
                out[t, i] = v
        return out

    # -- refresh --------------------------------------------------------

    def ready(self) -> bool:
        w = self.config.train.window_size
        min_windows = self.stream.eval_holdout + 2
        return (self._pending >= self.stream.refresh_buckets
                and self.num_buckets > w + min_windows)

    def refresh(self) -> RefreshResult:
        """Fine-tune on the retained corpus; returns the refresh record."""
        w = self.config.train.window_size
        traffic = np.stack(list(self.traffic))
        targets = self._targets()

        x = sliding_windows(traffic, w)
        y = sliding_windows(targets, w)
        holdout = min(self.stream.eval_holdout, len(x) - 1)
        split = len(x) - holdout

        # Expanding stats: union with every past refresh (monotone).
        self.x_stats = expand_minmax(
            self.x_stats, MinMaxStats(min=np.float32(x[:split].min()),
                                      max=np.float32(x[:split].max())))
        self.y_stats = expand_minmax(
            self.y_stats,
            MinMaxStats(min=y[:split].min(axis=(0, 1)).astype(np.float32),
                        max=y[:split].max(axis=(0, 1)).astype(np.float32)))

        x_n = self.x_stats.apply(x).astype(np.float32)
        y_n = self.y_stats.apply(y).astype(np.float32)
        bundle = DatasetBundle(
            x_train=x_n[:split], y_train=y_n[:split],
            x_test=x_n[split:], y_test=y_n[split:],
            x_stats=self.x_stats, y_stats=self.y_stats,
            metric_names=self._freeze_metrics(), split=split,
            window_size=w, space_dict=self.space.to_dict(),
        )

        if self.trainer is None:
            model = dataclasses.replace(
                self.config.model, feature_dim=self.space.capacity,
                num_metrics=len(bundle.metric_names))
            self.config = dataclasses.replace(self.config, model=model)
            self.trainer = Trainer(self.config, self.space.capacity,
                                   bundle.metric_names)
        if self.state is None:
            self.state = self.trainer.init_state(bundle.x_train)

        data_rng = np.random.default_rng(
            self.config.train.seed + self._refresh_count)
        train_loss = float("nan")
        for _ in range(self.stream.finetune_epochs):
            self.state, train_loss = self.trainer.train_epoch(
                self.state, bundle, data_rng)
        eval_loss, _ = self.trainer.evaluate(self.state, bundle)

        path = None
        if self.ckpt_dir:
            path = self.trainer.save(self.ckpt_dir, self.state, bundle)
        self._pending = 0
        self._refresh_count += 1
        return RefreshResult(
            refresh=self._refresh_count, num_buckets=self.num_buckets,
            train_loss=train_loss, eval_loss=float(eval_loss),
            checkpoint_path=path)

    # -- resume ---------------------------------------------------------

    def _maybe_resume(self) -> None:
        """Adopt the latest checkpoint's frozen state (metric set, stats,
        params) so a restarted stream continues rather than restarts."""
        if not self.ckpt_dir:
            return
        from deeprest_tpu.train.checkpoint import latest_step

        if latest_step(self.ckpt_dir) is None:
            return
        from deeprest_tpu.serve.predictor import Predictor

        pred = Predictor.from_checkpoint(self.ckpt_dir)
        if pred.model_config.feature_dim != self.space.capacity:
            raise ValueError(
                f"checkpoint feature_dim {pred.model_config.feature_dim} != "
                f"stream capacity {self.space.capacity}")
        self.metric_names = list(pred.metric_names)
        self.x_stats = pred.x_stats
        self.y_stats = pred.y_stats
        model = dataclasses.replace(
            self.config.model, feature_dim=pred.model_config.feature_dim,
            num_metrics=len(pred.metric_names))
        self.config = dataclasses.replace(self.config, model=model)
        self.trainer = Trainer(self.config, model.feature_dim,
                               self.metric_names)
        target = self.trainer.init_state(np.zeros(
            (1, self.config.train.window_size, model.feature_dim), np.float32))
        from deeprest_tpu.train.checkpoint import restore_checkpoint

        self.state, _ = restore_checkpoint(self.ckpt_dir, target)

    # -- driver ---------------------------------------------------------

    def run(self, tailer: BucketTailer,
            max_refreshes: int | None = None,
            should_stop: Callable[[], bool] | None = None,
            deadline_s: float | None = None) -> Iterator[RefreshResult]:
        """Poll the tailer forever (or until bounded), yielding one
        RefreshResult per fine-tune cycle."""
        t0 = time.monotonic()
        while True:
            if should_stop is not None and should_stop():
                return
            if deadline_s is not None and time.monotonic() - t0 > deadline_s:
                return
            for bucket in tailer.poll():
                self.ingest(bucket)
            if self.ready():
                yield self.refresh()
                if (max_refreshes is not None
                        and self._refresh_count >= max_refreshes):
                    return
            else:
                time.sleep(self.stream.poll_interval_s)


__all__ = [
    "BucketTailer", "StreamConfig", "StreamingTrainer", "RefreshResult",
    "expand_minmax",
]
