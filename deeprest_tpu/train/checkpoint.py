"""Sharding-aware checkpointing — the capability the reference lacks
entirely (its model never touches disk; SURVEY.md §5.4).

Checkpoints hold the full train state (params, optimizer state, step, rng)
plus a JSON sidecar of host-side state that must survive restarts with it:
normalization statistics, metric names, and the config — so a restored
trainer predicts identically, not just resumes.

Format (``deeprest-sharded-v1``): a ``manifest.json`` naming every pytree
leaf (its "/"-joined path, global shape, dtype) plus the list of saved
shard files with their global index ranges, and one ``.npy`` per distinct
shard under ``arrays/``.  Writes are PER-SHARD: each leaf is written as
its mesh shards (replica 0 only, so replicated leaves cost one copy), and
on a multi-host pod each process writes only the shards it addresses —
no host ever materializes another host's parameters.  Restore assembles
whatever shard partitioning is on disk into the TARGET's shardings
(``jax.make_array_from_callback``), so a state saved on a 2×2×2 mesh
restores onto 1×1×1 or 8×1×1 (or a pod) unchanged: assembly is by global
index, not by saved topology.  Target shardings come from the one
partition-rule table (``parallel/sharding.py``) via the caller's
``init_state`` template.

Why not orbax: ``ocp.StandardCheckpointer`` drags in a grpc/aiohttp/
tensorstore import chain that corrupts the glibc heap in this container
(``corrupted size vs. prev_size`` aborts mid-train-step — the long-standing
"orbax save abort" in ROADMAP item 1; the crash fired even in runs that
never saved, because importing this module used to import orbax at module
scope).  The native writer needs numpy + jax only.  Checkpoints written by
older orbax-based builds still restore through a lazily-imported legacy
path, so nothing on disk is orphaned.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Sequence

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")
_SIDECAR = "host_state.json"
_MANIFEST = "manifest.json"
_FORMAT = "deeprest-sharded-v1"
# numpy's .npy format round-trips these natively; anything else (ml_dtypes
# extension types like bfloat16) is stored as a same-width integer view
# with the real dtype recorded in the manifest.
_NATIVE_DTYPES = frozenset("?bhilqBHILQefdFD")


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step:08d}")


def _leaf_name(path: Sequence[Any]) -> str:
    from deeprest_tpu.parallel.sharding import leaf_path_name

    return leaf_path_name(path)


def _norm_index(idx, shape) -> list[list[int]]:
    """A devices_indices_map / callback index → concrete [[lo, hi], ...]."""
    return [list(sl.indices(dim)[:2]) for sl, dim in zip(idx, shape)]


def _shard_file(leaf_ord: int, name: str, lo: Sequence[int]) -> str:
    safe = re.sub(r"[^A-Za-z0-9_.-]+", "-", name).strip("-") or "leaf"
    start = "_".join(str(int(v)) for v in lo) if lo else "scalar"
    return f"{leaf_ord:03d}.{safe}.{start}.npy"


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Durably commit a directory's entries (the rename itself is only
    durable once the PARENT directory is synced)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json_durable(path: str, obj: Any, **dump_kwargs) -> None:
    """json.dump + flush + fsync: the manifest/sidecar bytes must be on
    the platter BEFORE the step directory's atomic rename publishes them
    — a host crash after the rename but before writeback would otherwise
    leave a published manifest full of zeros pointing at shard files
    that never hit disk."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, **dump_kwargs)
        f.flush()
        os.fsync(f.fileno())


def _to_storage(arr: np.ndarray) -> tuple[np.ndarray, str]:
    """(.npy-safe array, recorded dtype name).  bf16 & friends go to disk
    as a same-width integer view."""
    dtype_name = arr.dtype.name
    if arr.dtype.char in _NATIVE_DTYPES:
        return arr, dtype_name
    width = {1: np.uint8, 2: np.uint16, 4: np.uint32,
             8: np.uint64}[arr.dtype.itemsize]
    return arr.view(width), dtype_name


def _from_storage(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if arr.dtype.name == dtype_name:
        return arr
    import ml_dtypes

    # an integer-view round-trip (see _to_storage); the real dtype is an
    # ml_dtypes extension type such as bfloat16
    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))


def _leaf_shards(leaf) -> tuple[list[dict], list[tuple[list[list[int]], np.ndarray]]]:
    """(manifest shard entries for ALL distinct shards, the [index, data]
    pairs THIS process must write).

    The manifest needs the full shard list; each process can compute it
    locally from the sharding's ``devices_indices_map`` — no cross-host
    traffic.  Replicated shards dedupe to one entry (replica 0 writes).
    """
    shape = tuple(np.shape(leaf))
    if not isinstance(leaf, jax.Array) or not hasattr(leaf, "sharding"):
        idx = _norm_index((slice(None),) * len(shape), shape)
        return [{"index": idx}], [(idx, np.asarray(leaf))]
    distinct: dict[tuple, list[list[int]]] = {}
    for dev_idx in leaf.sharding.devices_indices_map(shape).values():
        norm = _norm_index(dev_idx, shape)
        distinct[tuple(tuple(p) for p in norm)] = norm
    entries = [{"index": v} for v in distinct.values()]
    mine = []
    seen: set[tuple] = set()
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue
        key = tuple(tuple(p) for p in _norm_index(shard.index, shape))
        if key in seen:
            continue
        seen.add(key)
        mine.append((distinct[key], np.asarray(shard.data)))
    return entries, mine


def save_checkpoint(directory: str, state: Any, step: int,
                    extra: dict | None = None) -> str:
    """Write ``directory/step_NNNNNNNN/`` (atomic: staged under ``.tmp``
    then renamed) + sidecar, with per-shard array files.

    On a pod every process calls this with the same global state; each
    writes only its addressable replica-0 shards, processes sync, and
    process 0 writes the manifest/sidecar and performs the rename.
    """
    path = _step_dir(directory, step)
    tmp = path + ".tmp"
    arrays_dir = os.path.join(tmp, "arrays")
    os.makedirs(arrays_dir, exist_ok=True)

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    leaves = []
    for ord_, (leaf_path, leaf) in enumerate(flat):
        name = _leaf_name(leaf_path)
        entries, mine = _leaf_shards(leaf)
        dtype_name = None
        for idx, data in mine:
            data = np.asarray(data)
            if data.ndim and not data.flags["C_CONTIGUOUS"]:
                # NOT np.ascontiguousarray: it silently promotes 0-d
                # scalars to shape (1,), corrupting the manifest contract
                data = np.ascontiguousarray(data)
            stored, dtype_name = _to_storage(data)
            fname = _shard_file(ord_, name, [lo for lo, _ in idx])
            fpath = os.path.join(arrays_dir, fname)
            np.save(fpath, stored)
            _fsync_file(fpath)
        if dtype_name is None:       # no local shard: dtype from metadata
            dtype_name = np.dtype(leaf.dtype).name
        for e in entries:
            e["file"] = _shard_file(ord_, name, [lo for lo, _ in e["index"]])
        leaves.append({"name": name, "shape": list(np.shape(leaf)),
                       "dtype": dtype_name, "shards": entries})

    if jax.process_count() > 1:
        # Every shard file must exist before the manifest claims it does
        # (and before the atomic rename publishes the directory).
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deeprest_ckpt_shards_written")
    if jax.process_index() == 0:
        manifest = {"format": _FORMAT, "step": int(step), "leaves": leaves}
        # Manifest + sidecar are fsynced, then the tmp DIRECTORY (its
        # entries — the shard files synced above as they were written),
        # and only then the atomic rename + parent-dir sync publish the
        # step: a host crash at any instant leaves either no step_N dir
        # or a complete one, never a manifest naming missing shards.
        _write_json_durable(os.path.join(tmp, _MANIFEST), manifest,
                            indent=1, sort_keys=True)
        if extra is not None:
            # tmp dir + final rename: a crash mid-write must leave no torn
            # sidecar (a torn one would wedge every consumer that reads it
            # at startup)
            _write_json_durable(os.path.join(tmp, _SIDECAR), extra,
                                indent=2, sort_keys=True)
        _fsync_dir(arrays_dir)
        _fsync_dir(tmp)
        if os.path.isdir(path):
            import shutil

            shutil.rmtree(path)      # force-overwrite an existing step
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deeprest_ckpt_published")
    return path


def list_steps(directory: str) -> list[int]:
    """All checkpoint step numbers under ``directory``, ascending."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    )


def latest_step(directory: str) -> int | None:
    steps = list_steps(directory)
    return steps[-1] if steps else None


def load_sidecar(directory: str, step: int | None = None,
                 missing_ok: bool = False) -> dict | None:
    """Read one checkpoint's host-state sidecar without restoring arrays
    (for consumers that only need metadata: metric names, stats, config).

    ``missing_ok=True`` returns None for a sidecar that is absent *or
    unparseable* (e.g. a crash between an array save and the sidecar
    write in pre-atomic formats, or torn by a pre-atomic-write version)
    instead of raising.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = os.path.join(_step_dir(directory, step), _SIDECAR)
    if missing_ok and not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except ValueError:
        if missing_ok:
            return None
        raise


def _has_full_cursor(extra: dict | None) -> bool:
    """Does this sidecar carry a FULL epoch-plan cursor (a resume/remesh
    anchor)?  Streaming snapshots carry a light cursor (epoch None — the
    stream never plan-replays) and plain epoch-cadence checkpoints carry
    none; both are excluded."""
    if extra is None:
        return False
    cur = extra.get("train_cursor")
    return (isinstance(cur, dict) and cur.get("epoch") is not None
            and cur.get("rng_state") is not None)


def latest_cursor_step(directory: str) -> int | None:
    """Newest checkpoint step whose sidecar carries a full epoch-plan
    ``train_cursor`` (written by the trainer's preemption snapshots) —
    the anchor ``Trainer.resume_training`` restarts from.  Steps without
    a cursor (plain epoch-cadence checkpoints, streaming refresh
    checkpoints with the light cursor) are skipped, so a resumable
    snapshot behind a newer non-resumable save is still found."""
    for step in reversed(list_steps(directory)):
        if _has_full_cursor(load_sidecar(directory, step, missing_ok=True)):
            return step
    return None


def prune_cursor_snapshots(directory: str, keep: int) -> list[int]:
    """Snapshot retention GC: delete all but the newest ``keep`` CURSOR
    snapshots; returns the pruned step numbers.

    Only cursor-bearing steps (the preemption/remesh restore anchors)
    are candidates — epoch-cadence checkpoints and streaming refresh
    checkpoints are other consumers' property and are never touched.
    Called AFTER a durable newer save (the trainer's snapshot() orders
    it so), which is what makes the retention safe against a concurrent
    restore: the restore target is always among the newest ``keep``
    (``keep >= 1``), so a restore that resolved ``latest_cursor_step``
    before this prune ran reads a directory the prune does not touch.
    The parent directory is fsync'd after the removals so the deletions
    are as durable as the saves were.
    """
    import shutil

    if keep < 1:
        raise ValueError(f"prune_cursor_snapshots(keep={keep}): must be "
                         ">= 1 (the newest snapshot is the restore "
                         "target and must survive)")
    cursor_steps = [
        step for step in list_steps(directory)
        if _has_full_cursor(load_sidecar(directory, step, missing_ok=True))
    ]
    pruned = []
    for step in cursor_steps[:-keep]:
        shutil.rmtree(_step_dir(directory, step), ignore_errors=True)
        pruned.append(step)
    if pruned:
        _fsync_dir(os.path.abspath(directory))
    return pruned


def prune_checkpoints(directory: str, keep: int) -> list[int]:
    """Delete all but the newest ``keep`` checkpoint steps; returns the
    pruned step numbers. A forever-process (streaming retrain) would
    otherwise grow the checkpoint dir without bound."""
    import shutil

    if keep < 1:
        raise ValueError(f"keep={keep} must be >= 1")
    pruned = []
    for step in list_steps(directory)[:-keep]:
        shutil.rmtree(_step_dir(directory, step), ignore_errors=True)
        pruned.append(step)
    return pruned


def _intersect(a: list[list[int]], b: list[list[int]]):
    """Overlap of two [[lo, hi], ...] boxes → (dst slices, src slices) or
    None; dst is relative to box ``a``'s origin, src to box ``b``'s."""
    dst, src = [], []
    for (alo, ahi), (blo, bhi) in zip(a, b):
        lo, hi = max(alo, blo), min(ahi, bhi)
        if lo >= hi:
            return None
        dst.append(slice(lo - alo, hi - alo))
        src.append(slice(lo - blo, hi - blo))
    return tuple(dst), tuple(src)


def _assemble(arrays_dir: str, entry: dict, idx, shape) -> np.ndarray:
    """Materialize the requested global-index box of one saved leaf from
    whatever shard partitioning is on disk (mmap'd, so replicated
    restores of the same file stay cheap)."""
    dtype = np.dtype(entry["dtype"])
    box = _norm_index(idx, shape)
    out_shape = tuple(hi - lo for lo, hi in box)
    out = np.empty(out_shape, dtype)
    filled = 0
    for shard in entry["shards"]:
        hit = _intersect(box, shard["index"])
        if hit is None:
            continue
        dst, src = hit
        fpath = os.path.join(arrays_dir, shard["file"])
        # A torn/truncated shard (host crash mid-writeback on a
        # pre-fsync-era checkpoint, disk corruption, a copy that died)
        # must raise CLEANLY here, never hand garbage to the trainer:
        # np.load's failure modes on a short file range from ValueError
        # to OSError to a successful mmap whose data region is short —
        # normalize them all into one diagnosable error.
        try:
            data = np.load(fpath, mmap_mode="r")
            chunk = np.asarray(data[src])
        except (ValueError, OSError, EOFError, IndexError) as exc:
            raise ValueError(
                f"checkpoint shard {shard['file']!r} of leaf "
                f"{entry['name']!r} is truncated or corrupt ({exc}); "
                "the checkpoint step is unusable — restore an earlier "
                "step") from exc
        expect = tuple(s.stop - s.start for s in src)
        if chunk.shape != expect:
            raise ValueError(
                f"checkpoint shard {shard['file']!r} of leaf "
                f"{entry['name']!r} is truncated: stored shape "
                f"{chunk.shape} cannot satisfy the manifest's "
                f"{expect} slice; restore an earlier step")
        out[dst] = _from_storage(chunk, entry["dtype"])
        filled += int(np.prod([s.stop - s.start for s in dst], dtype=np.int64))
    if filled != int(np.prod(out_shape, dtype=np.int64)):
        raise ValueError(
            f"checkpoint leaf {entry['name']!r}: saved shards cover only "
            f"{filled} of {int(np.prod(out_shape))} requested elements "
            "(incomplete multi-host save?)")
    return out


def restore_checkpoint(directory: str, target: Any,
                       step: int | None = None) -> tuple[Any, dict | None]:
    """Restore the train state (sharded like ``target``) and the sidecar.

    ``target`` is a concrete or abstract state pytree (e.g. a freshly
    initialized TrainState) defining structure, dtypes, and shardings —
    typically rule-table shardings from ``Trainer.init_state`` under the
    RESTORING mesh, which need not match the mesh the checkpoint was
    saved under (assembly is by global index).
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = _step_dir(directory, step)
    manifest_path = os.path.join(path, _MANIFEST)
    if not os.path.exists(manifest_path):
        return (_commit_to_device(_restore_legacy_orbax(path, target)),
                load_sidecar(directory, step, missing_ok=True))
    with open(manifest_path, encoding="utf-8") as f:
        manifest = json.load(f)
    if manifest.get("format") != _FORMAT:
        raise ValueError(f"unknown checkpoint format "
                         f"{manifest.get('format')!r} at {path}")
    arrays_dir = os.path.join(path, "arrays")
    by_name = {e["name"]: e for e in manifest["leaves"]}

    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out_leaves = []
    for leaf_path, leaf in flat:
        name = _leaf_name(leaf_path)
        entry = by_name.get(name)
        if entry is None:
            raise KeyError(
                f"checkpoint at {path} has no leaf {name!r} "
                f"(saved leaves: {sorted(by_name)[:8]}...)")
        shape = tuple(entry["shape"])
        if shape != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint leaf {name!r} shape {shape} != target "
                f"{tuple(np.shape(leaf))} (architecture drift?)")
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            arr = jax.make_array_from_callback(
                shape, leaf.sharding,
                lambda idx, e=entry, s=shape: _assemble(arrays_dir, e,
                                                        idx, s))
        else:
            arr = _assemble(arrays_dir, entry,
                            (slice(None),) * len(shape), shape)
        out_leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, out_leaves)
    return _commit_to_device(state), load_sidecar(directory, step,
                                                  missing_ok=True)


def _commit_to_device(state: Any) -> Any:
    """Launder assembled arrays into XLA-owned buffers.

    ``make_array_from_callback`` results stay backed by host (numpy)
    buffers on the CPU backend; DONATING such a buffer into a compiled
    step (the trainer donates the whole TrainState every step) frees
    memory the host allocator still owns — measured in this container as
    glibc heap corruption ("corrupted double-linked list" / "corrupted
    size vs. prev_size") aborting mid-train after a restore.  One jitted
    sharding-constraint pass re-materializes every leaf as an
    XLA-allocated buffer with its target sharding intact; values are
    bit-identical (it is the identity computation).
    """
    shardings = jax.tree.map(
        lambda leaf: leaf.sharding
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding")
        else None, state)

    def pin(s):
        return jax.tree.map(
            lambda leaf, shd: (jax.lax.with_sharding_constraint(leaf, shd)
                               if shd is not None else leaf),
            s, shardings)

    return jax.jit(pin)(state)


def _restore_legacy_orbax(path: str, target: Any) -> Any:
    """Checkpoints written by the pre-v1 orbax format (no manifest.json).

    orbax is imported lazily and ONLY here: its grpc/aiohttp/tensorstore
    import chain is the heap-corruption source the native format exists
    to avoid, so the cost (and the risk) is paid exclusively by restores
    of legacy directories.
    """
    try:
        import orbax.checkpoint as ocp
    except Exception as exc:  # pragma: no cover - env without orbax
        raise RuntimeError(
            f"checkpoint at {path} predates the native sharded format and "
            f"orbax is unavailable to read it ({exc}); re-save it with a "
            "build that has orbax installed") from exc
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract)
