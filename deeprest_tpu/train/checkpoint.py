"""Orbax checkpointing — the capability the reference lacks entirely
(its model never touches disk; SURVEY.md §5.4).

Checkpoints hold the full train state (params, optimizer state, step, rng)
plus a JSON sidecar of host-side state that must survive restarts with it:
normalization statistics, metric names, and the config — so a restored
trainer predicts identically, not just resumes.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import jax
import orbax.checkpoint as ocp

_STEP_RE = re.compile(r"^step_(\d+)$")
_SIDECAR = "host_state.json"


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step:08d}")


def save_checkpoint(directory: str, state: Any, step: int,
                    extra: dict | None = None) -> str:
    """Write ``directory/step_NNNNNNNN/`` (atomic via orbax) + sidecar."""
    path = _step_dir(directory, step)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    if extra is not None:
        # tmp + rename: a crash mid-write must leave no torn sidecar (a
        # torn one would wedge every consumer that reads it at startup)
        sidecar = os.path.join(path, _SIDECAR)
        with open(sidecar + ".tmp", "w", encoding="utf-8") as f:
            json.dump(extra, f, indent=2, sort_keys=True)
        os.replace(sidecar + ".tmp", sidecar)
    return path


def list_steps(directory: str) -> list[int]:
    """All checkpoint step numbers under ``directory``, ascending."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    )


def latest_step(directory: str) -> int | None:
    steps = list_steps(directory)
    return steps[-1] if steps else None


def load_sidecar(directory: str, step: int | None = None,
                 missing_ok: bool = False) -> dict | None:
    """Read one checkpoint's host-state sidecar without restoring arrays
    (for consumers that only need metadata: metric names, stats, config).

    ``missing_ok=True`` returns None for a sidecar that is absent *or
    unparseable* (e.g. a crash between the orbax save and the sidecar
    write, or torn by a pre-atomic-write version) instead of raising.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = os.path.join(_step_dir(directory, step), _SIDECAR)
    if missing_ok and not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except ValueError:
        if missing_ok:
            return None
        raise


def prune_checkpoints(directory: str, keep: int) -> list[int]:
    """Delete all but the newest ``keep`` checkpoint steps; returns the
    pruned step numbers. A forever-process (streaming retrain) would
    otherwise grow the checkpoint dir without bound."""
    import shutil

    if keep < 1:
        raise ValueError(f"keep={keep} must be >= 1")
    pruned = []
    for step in list_steps(directory)[:-keep]:
        shutil.rmtree(_step_dir(directory, step), ignore_errors=True)
        pruned.append(step)
    return pruned


def restore_checkpoint(directory: str, target: Any,
                       step: int | None = None) -> tuple[Any, dict | None]:
    """Restore the train state (sharded like ``target``) and the sidecar.

    ``target`` is a concrete or abstract state pytree (e.g. a freshly
    initialized TrainState) defining structure, dtypes, and shardings.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = _step_dir(directory, step)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path, abstract)
    return state, load_sidecar(directory, step, missing_ok=True)
