"""``python -m deeprest_tpu`` — the pipeline CLI (see deeprest_tpu/cli.py)."""

import sys

from deeprest_tpu.cli import main

sys.exit(main())
