"""Logical device mesh over TPU ICI.

The reference's ML core is single-device (reference:
resource-estimation/estimate.py:10 — one cuda/cpu pick, no DDP/NCCL
anywhere); distribution is *introduced* here the TPU way: one logical mesh
with three axes, all parallelism expressed as sharding annotations, all
collectives inserted by the GSPMD partitioner and riding ICI.

Axes (SURVEY.md §2.5):
- ``data``   — batch dimension (DP; gradient all-reduce over ICI),
- ``expert`` — the stacked per-metric experts (EP; the only cross-expert
  dataflow is the mixing sum, one all-reduce over this axis),
- ``model``  — the call-path feature dimension of the mask/GRU input
  projections (TP; pressure point when |M| reaches 10k endpoints).

Pipeline and sequence axes are deliberately absent: window length is 60 and
the recurrent core is the reference's long-context answer (SURVEY.md §5.7).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from deeprest_tpu.config import MeshConfig

AXES = ("data", "expert", "model")


def make_mesh(config: MeshConfig | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the (data, expert, model) mesh.

    Defaults to all available devices on the data axis when no config is
    given; a 1×1×1 config is a valid single-device mesh, so the trainer uses
    one code path everywhere.
    """
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(data=len(devices))
    if config.size > len(devices):
        raise ValueError(
            f"mesh {config.data}x{config.expert}x{config.model} needs "
            f"{config.size} devices, only {len(devices)} available"
        )
    grid = np.asarray(devices[: config.size]).reshape(
        config.data, config.expert, config.model
    )
    return Mesh(grid, AXES)
