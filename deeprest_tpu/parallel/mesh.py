"""Logical device mesh over TPU ICI.

The reference's ML core is single-device (reference:
resource-estimation/estimate.py:10 — one cuda/cpu pick, no DDP/NCCL
anywhere); distribution is *introduced* here the TPU way: one logical mesh
with three axes, all parallelism expressed as sharding annotations, all
collectives inserted by the GSPMD partitioner and riding ICI.

Axes (SURVEY.md §2.5):
- ``data``   — batch dimension (DP; gradient all-reduce over ICI),
- ``expert`` — the stacked per-metric experts (EP; the only cross-expert
  dataflow is the mixing sum, one all-reduce over this axis),
- ``model``  — the call-path feature dimension of the mask/GRU input
  projections (TP; pressure point when |M| reaches 10k endpoints).

Pipeline and sequence axes are deliberately absent: window length is 60 and
the recurrent core is the reference's long-context answer (SURVEY.md §5.7).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from deeprest_tpu.config import MeshConfig

AXES = ("data", "expert", "model")


class NoValidMeshError(RuntimeError):
    """No mesh shape fits the surviving devices (elastic remeshing):
    the expert/model axes are load-bearing — shrinking them would
    re-partition parameters mid-run — so when ``expert * model`` devices
    no longer exist there is nothing left to rebuild onto.  The caller
    (the trainer's fault barrier) surfaces this typed error instead of
    respinning."""


def shrink_mesh_config(config: MeshConfig, healthy_count: int) -> MeshConfig:
    """The largest valid mesh on ``healthy_count`` devices: shrink the
    DATA axis first, preserve expert/model.

    The data axis is the safe one to fold — batch rows redistribute and
    the gradient all-reduce simply spans fewer shards — while the
    expert/model axes encode the parameter partitioning the rule table
    placed.  The new data extent is the largest **divisor** of the old
    one that fits: divisor, not just ≤, so a batch size divisible by the
    old data axis stays divisible by the new one (the
    ``feed_global_batch`` contract survives the shrink — 8→4→2→1, never
    8→7).  Raises :class:`NoValidMeshError` when even ``data=1`` does
    not fit (fewer than ``expert * model`` healthy devices).
    """
    if healthy_count < 1:
        raise NoValidMeshError(
            f"no healthy devices remain (mesh was "
            f"{config.data}x{config.expert}x{config.model})")
    em = config.expert * config.model
    if em > healthy_count:
        raise NoValidMeshError(
            f"only {healthy_count} healthy device(s) remain but the "
            f"expert*model plane needs {em} "
            f"({config.expert}x{config.model}); the expert/model axes "
            "carry the parameter partitioning and cannot shrink in-run")
    budget = healthy_count // em
    d = next(d for d in range(min(config.data, budget), 0, -1)
             if config.data % d == 0)
    return MeshConfig(data=d, expert=config.expert, model=config.model)


def mesh_config_of(mesh: Mesh) -> MeshConfig:
    """The :class:`MeshConfig` a live mesh was (or could have been)
    built from — the shrink computation's input when a trainer holds
    only the constructed mesh."""
    return MeshConfig(data=int(mesh.shape["data"]),
                      expert=int(mesh.shape["expert"]),
                      model=int(mesh.shape["model"]))


def make_mesh(config: MeshConfig | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the (data, expert, model) mesh.

    Defaults to all available devices on the data axis when no config is
    given; a 1×1×1 config is a valid single-device mesh, so the trainer uses
    one code path everywhere.
    """
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(data=len(devices))
    if config.size > len(devices):
        raise ValueError(
            f"mesh {config.data}x{config.expert}x{config.model} needs "
            f"{config.size} devices, only {len(devices)} available"
        )
    grid = np.asarray(devices[: config.size]).reshape(
        config.data, config.expert, config.model
    )
    return Mesh(grid, AXES)
