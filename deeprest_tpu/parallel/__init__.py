"""Device-mesh construction and GSPMD sharding rules."""

from deeprest_tpu.parallel.mesh import make_mesh
from deeprest_tpu.parallel.sharding import (
    batch_sharding,
    param_sharding,
    param_specs,
    shard_batch,
    shard_params,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "param_sharding",
    "param_specs",
    "shard_batch",
    "shard_params",
]
