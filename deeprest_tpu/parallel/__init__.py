"""Device-mesh construction, GSPMD sharding rules, and multi-host bring-up."""

from deeprest_tpu.parallel.mesh import make_mesh
from deeprest_tpu.parallel.sharding import (
    batch_sharding,
    param_sharding,
    param_specs,
    shard_batch,
    shard_params,
)
from deeprest_tpu.parallel.distributed import (
    feed_global_batch,
    prefetch_to_device,
    global_mesh,
    initialize_distributed,
    process_batch_slice,
    stage_plan,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "param_sharding",
    "param_specs",
    "shard_batch",
    "shard_params",
    "feed_global_batch",
    "prefetch_to_device",
    "global_mesh",
    "initialize_distributed",
    "process_batch_slice",
    "stage_plan",
]
