"""Device-mesh construction, GSPMD sharding rules, and multi-host bring-up."""

from deeprest_tpu.parallel.mesh import (
    NoValidMeshError,
    make_mesh,
    mesh_config_of,
    shrink_mesh_config,
)
from deeprest_tpu.parallel.elastic import (
    DeviceLossError,
    FaultInjector,
    RemeshExhaustedError,
    enumerate_healthy,
    is_device_loss,
)
from deeprest_tpu.parallel.sharding import (
    PARTITION_RULES,
    batch_sharding,
    match_partition_rules,
    param_sharding,
    param_specs,
    shard_batch,
    shard_params,
    state_sharding,
    state_specs,
)
from deeprest_tpu.parallel.distributed import (
    feed_global_batch,
    prefetch_to_device,
    global_mesh,
    initialize_distributed,
    process_batch_slice,
    stage_plan,
)

__all__ = [
    "make_mesh",
    "mesh_config_of",
    "shrink_mesh_config",
    "NoValidMeshError",
    "DeviceLossError",
    "FaultInjector",
    "RemeshExhaustedError",
    "enumerate_healthy",
    "is_device_loss",
    "PARTITION_RULES",
    "match_partition_rules",
    "state_sharding",
    "state_specs",
    "batch_sharding",
    "param_sharding",
    "param_specs",
    "shard_batch",
    "shard_params",
    "feed_global_batch",
    "prefetch_to_device",
    "global_mesh",
    "initialize_distributed",
    "process_batch_slice",
    "stage_plan",
]
