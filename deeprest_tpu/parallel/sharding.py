"""Sharding rules: ONE ordered regex table, every TrainState leaf path.

Every QuantileGRU parameter carries a leading expert axis (models/qrnn.py),
so EP is uniformly "axis 0 on ``expert``"; TP shards the call-path feature
dimension F where it appears (the mask output and the layer-0 GRU input
projections — the two places that grow with the endpoint vocabulary,
SURVEY.md §7.3); everything else is replicated.  The batch shards on
``data``.  No manual collectives anywhere: the cross-expert mixing sum and
the gradient all-reduce are inserted by GSPMD from these annotations.

The table below (:data:`PARTITION_RULES`) is the SINGLE owner of those
decisions: an ordered ``(regex, PartitionSpec)`` list matched against
"/"-joined pytree leaf paths (the SNIPPETS.md [2]/[3]
``match_partition_rules`` shape).  Trainer ``pin_state``, checkpoint
restore, and the serving plane all resolve shardings here — there are no
hand-pinned per-leaf spec dicts anywhere else (graftlint JX005 enforces
that NamedSharding literals stay out of other modules).  Optimizer state
needs no rules of its own: Adam's ``mu``/``nu`` mirror the params dict
keyed by the same names, so the param rules match their paths too.

Strict mode errors on any leaf no rule matches: a new TrainState leaf must
be *placed deliberately*, not silently replicated (the silent-collapse
class behind the PR 2 double-executable incident).
"""

from __future__ import annotations

import re
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Ordered: first match wins.  Patterns run (re.search) against "/"-joined
# leaf paths such as ``params/mask_w2`` or ``opt_state/0/mu/gru_fwd_w_ih``,
# so ``(^|/)name$`` anchors on the leaf name wherever it sits in the tree.
PARTITION_RULES: tuple[tuple[str, P], ...] = (
    # -- soft feature mask MLP ------------------------------------------
    (r"(^|/)mask_w1$", P("expert", None)),             # [E, H]
    (r"(^|/)mask_b1$", P("expert", None)),             # [E, H]
    (r"(^|/)mask_w2$", P("expert", None, "model")),    # [E, H, F]  TP out
    (r"(^|/)mask_b2$", P("expert", "model")),          # [E, F]     TP out
    # -- GRU stacks: deep-layer (_lN) w_ih consumes the 2H hidden output
    # of the previous layer, not the TP-sharded feature axis — those
    # replicate like w_hh.  Order matters: the _lN rule must win before
    # the layer-0 w_ih rule below.
    (r"(^|/)gru_(fwd|bwd)_l\d+_w_ih$", P("expert", None, None)),
    (r"(^|/)gru_(fwd|bwd)_w_ih$", P("expert", "model", None)),  # [E, F, 3H]
    (r"(^|/)gru_(fwd|bwd)(_l\d+)?_w_hh$", P("expert", None, None)),
    (r"(^|/)gru_(fwd|bwd)(_l\d+)?_b_(ih|hh)$", P("expert", None)),
    # -- quantile heads --------------------------------------------------
    (r"(^|/)head_w$", P("expert", None, None)),        # [E, 4H, Q]
    (r"(^|/)head_b$", P("expert", None)),              # [E, Q]
    # -- TrainState bookkeeping: replicated everywhere -------------------
    #    step (scalar), the PRNG key, Adam's update counter.
    (r"(^|/)(step|rng|count)$", P()),
)


def leaf_path_name(path: Sequence[Any]) -> str:
    """``tree_flatten_with_path`` key path → the "/"-joined rule name
    (``params/mask_w2``, ``opt_state/0/mu/head_w``, ``rng``)."""
    parts = []
    for entry in path:
        for attr in ("name", "key", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def _leaf_ndim(leaf: Any) -> int:
    return getattr(leaf, "ndim", np.ndim(leaf))


def _leaf_size(leaf: Any) -> int:
    return int(getattr(leaf, "size", np.size(leaf)))


def match_partition_rules(tree: Any,
                          rules: Sequence[tuple[str, P]] = PARTITION_RULES,
                          strict: bool = True) -> Any:
    """A PartitionSpec pytree mirroring ``tree``, resolved from ``rules``.

    Scalar (and single-element) leaves replicate without consulting the
    table — there is nothing to shard.  Otherwise the FIRST rule whose
    regex ``search``-matches the leaf's "/"-joined path wins.  ``strict``
    raises ``KeyError`` on an unmatched leaf instead of silently
    replicating it: every new TrainState leaf must be placed on the mesh
    deliberately.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(path, leaf):
        if _leaf_ndim(leaf) == 0 or _leaf_size(leaf) <= 1:
            return P()
        name = leaf_path_name(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        if strict:
            raise KeyError(
                f"no partition rule matches leaf {name!r} "
                f"(shape {tuple(np.shape(leaf))}); add a rule to "
                "parallel/sharding.PARTITION_RULES — strict mode refuses "
                "to replicate unknown state silently")
        return P()

    return jax.tree_util.tree_map_with_path(resolve, tree)


def param_specs(params: Mapping[str, Any]) -> dict[str, P]:
    """PartitionSpec dict mirroring a QuantileGRU param dict (the params
    slice of the rule table; raises KeyError on an unmatched name)."""
    return match_partition_rules(dict(params), strict=True)


def state_specs(state: Any) -> Any:
    """PartitionSpec pytree for a full TrainState (params, optimizer
    mirrors, step/rng bookkeeping), strictly rule-resolved."""
    return match_partition_rules(state, strict=True)


def state_sharding(mesh: Mesh, state: Any) -> Any:
    """NamedSharding pytree for a full TrainState on ``mesh`` — what the
    trainer's ``pin_state`` constrains every step output to, and what
    checkpoint restore assembles shards into."""
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        state_specs(state),
                        is_leaf=lambda x: isinstance(x, P))


def param_sharding(mesh: Mesh, params: Mapping[str, Any]) -> dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec)
            for k, spec in param_specs(params).items()}


def batch_sharding(mesh: Mesh, ndim: int = 3) -> NamedSharding:
    """Batch arrays shard on ``data`` along axis 0; rest replicated."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def shard_params(mesh: Mesh, params: Mapping[str, Any]) -> dict[str, jax.Array]:
    """Place (replicated-identical) host params onto the mesh.

    On one host a sharded device_put; on a pod each process materializes
    only its addressable shards (``make_array_from_callback``) — init
    with the same PRNGKey makes every host's source params identical.
    """
    shardings = param_sharding(mesh, params)

    def put(v, shd):
        if jax.process_count() == 1:
            return jax.device_put(v, shd)
        host = np.asarray(v)
        return jax.make_array_from_callback(host.shape, shd,
                                            lambda idx: host[idx])

    return {k: put(v, shardings[k]) for k, v in params.items()}


def shard_batch(mesh: Mesh, *arrays: jax.Array | Any) -> tuple[jax.Array, ...]:
    out = tuple(
        jax.device_put(a, batch_sharding(mesh, getattr(a, "ndim", 1))) for a in arrays
    )
    return out if len(out) > 1 else out[0]
