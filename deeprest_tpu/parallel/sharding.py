"""Sharding rules: parameter-name → PartitionSpec.

Every QuantileGRU parameter carries a leading expert axis (models/qrnn.py),
so EP is uniformly "axis 0 on ``expert``"; TP shards the call-path feature
dimension F where it appears (the mask output and the GRU input
projections — the two places that grow with the endpoint vocabulary,
SURVEY.md §7.3); everything else is replicated.  The batch shards on
``data``.  No manual collectives anywhere: the cross-expert mixing sum and
the gradient all-reduce are inserted by GSPMD from these annotations.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# parameter name → spec; F is the TP-sharded feature axis.
_PARAM_SPECS: dict[str, P] = {
    "mask_w1": P("expert", None),            # [E, H]
    "mask_b1": P("expert", None),            # [E, H]
    "mask_w2": P("expert", None, "model"),   # [E, H, F]
    "mask_b2": P("expert", "model"),         # [E, F]
    "gru_fwd_w_ih": P("expert", "model", None),  # [E, F, 3H]
    "gru_bwd_w_ih": P("expert", "model", None),
    "gru_fwd_w_hh": P("expert", None, None),     # [E, H, 3H]
    "gru_bwd_w_hh": P("expert", None, None),
    "gru_fwd_b_ih": P("expert", None),       # [E, 3H]
    "gru_bwd_b_ih": P("expert", None),
    "gru_fwd_b_hh": P("expert", None),
    "gru_bwd_b_hh": P("expert", None),
    "head_w": P("expert", None, None),       # [E, 4H, Q]
    "head_b": P("expert", None),             # [E, Q]
}


_LAYER_SUFFIX = re.compile(r"_l\d+(_)")


def _rule_key(name: str) -> str:
    """Canonical rule name: stacked-layer params (gru_fwd_l1_w_ih) share the
    base rule, except deep-layer w_ih whose input dim is hidden-sized (2H),
    not the TP-sharded feature axis — those replicate like w_hh."""
    base = _LAYER_SUFFIX.sub(r"\1", name)
    if base != name and base.endswith("_w_ih"):
        return base.replace("_w_ih", "_w_hh")
    return base


def param_specs(params: Mapping[str, Any]) -> dict[str, P]:
    """PartitionSpec tree mirroring a QuantileGRU param dict."""
    specs = {}
    for name in params:
        key = _rule_key(name)
        if key not in _PARAM_SPECS:
            raise KeyError(f"no sharding rule for parameter {name!r}")
        specs[name] = _PARAM_SPECS[key]
    return specs


def param_sharding(mesh: Mesh, params: Mapping[str, Any]) -> dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec) for k, spec in param_specs(params).items()}


def batch_sharding(mesh: Mesh, ndim: int = 3) -> NamedSharding:
    """Batch arrays shard on ``data`` along axis 0; rest replicated."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def shard_params(mesh: Mesh, params: Mapping[str, Any]) -> dict[str, jax.Array]:
    """Place (replicated-identical) host params onto the mesh.

    On one host a sharded device_put; on a pod each process materializes
    only its addressable shards (``make_array_from_callback``) — init
    with the same PRNGKey makes every host's source params identical.
    """
    import numpy as np

    shardings = param_sharding(mesh, params)

    def put(v, shd):
        if jax.process_count() == 1:
            return jax.device_put(v, shd)
        host = np.asarray(v)
        return jax.make_array_from_callback(host.shape, shd,
                                            lambda idx: host[idx])

    return {k: put(v, shardings[k]) for k, v in params.items()}


def shard_batch(mesh: Mesh, *arrays: jax.Array | Any) -> tuple[jax.Array, ...]:
    out = tuple(
        jax.device_put(a, batch_sharding(mesh, getattr(a, "ndim", 1))) for a in arrays
    )
    return out if len(out) > 1 else out[0]
