"""Elastic remeshing primitives: device-loss detection for the fault
barrier (ROADMAP item 7's last training gap).

A preempted multi-chip run used to die and restart the whole process on
the surviving mesh (round 17's ``resume_training`` contract).  GSPMD's
annotation model makes the in-process fix natural — the rule table
(`parallel/sharding.PARTITION_RULES`) already places every leaf on *any*
mesh shape and the round-12 cross-mesh restore reassembles checkpoints
by global index — so device loss becomes a caught exception and a
re-dispatch, not a process death.  This module owns the DETECT leg:

- :class:`DeviceLossError` — the typed synthetic loss the deterministic
  :class:`FaultInjector` raises at step K on the CPU backend, making the
  whole detect→rebuild→restore→resume path tier-1 testable without a
  chip to actually lose;
- :func:`is_device_loss` — classifies an exception as the device-loss
  family: a :class:`DeviceLossError`, or a real ``XlaRuntimeError``
  whose message carries the runtime's device-failure markers (slice
  preemption, halted cores, ``UNAVAILABLE``/``ABORTED`` transport
  states on a dead ICI neighbor);
- :func:`enumerate_healthy` — the hardware re-enumeration probe: one
  tiny ``device_put`` per candidate device, survivors in stable order.

The REBUILD leg (shrink the data axis first, preserve expert/model)
lives in :func:`parallel.mesh.shrink_mesh_config`; the RESTORE leg is
the round-12 cross-mesh assembly in ``train/checkpoint.py``; the barrier
composing them is ``Trainer._run_epochs_elastic`` (the ONLY sanctioned
swallow point for this exception family — graftlint EX004 enforces
that).
"""

from __future__ import annotations

import re
from typing import Mapping, Sequence

__all__ = [
    "DeviceLossError",
    "RemeshExhaustedError",
    "FaultInjector",
    "is_device_loss",
    "enumerate_healthy",
    "xla_runtime_error_type",
]


class DeviceLossError(RuntimeError):
    """Synthetic device loss (the :class:`FaultInjector`'s signal).

    ``lost`` is how many devices the event takes down — the injector's
    deterministic stand-in for the hardware re-enumeration a real
    ``XlaRuntimeError`` triggers.
    """

    def __init__(self, message: str, lost: int = 1):
        super().__init__(message)
        self.lost = int(lost)


class RemeshExhaustedError(RuntimeError):
    """Device losses outran ``TrainConfig.remesh_max_attempts``: the
    bounded barrier refuses to respin forever (the RS004 discipline,
    applied to the training plane) and surfaces the final loss."""


def xla_runtime_error_type() -> type | None:
    """The running jaxlib's ``XlaRuntimeError`` class (None when the
    probe paths all miss — an exotic jax build; the synthetic family
    still classifies)."""
    try:
        import jax

        t = getattr(getattr(jax, "errors", None), "JaxRuntimeError", None)
        if isinstance(t, type):
            return t
    except Exception:
        pass
    try:
        from jax._src.lib import xla_client

        t = getattr(xla_client, "XlaRuntimeError", None)
        if isinstance(t, type):
            return t
    except Exception:
        pass
    return None


# Message markers of a LOST DEVICE inside an XlaRuntimeError.  Deliberately
# conservative: a compile error or a shape mismatch also arrives as
# XlaRuntimeError, and remeshing on those would loop a deterministic bug
# through restore-retry until the attempt budget ran out.  These markers
# are the TPU runtime's device-death vocabulary (slice preemption, halted
# cores, dead-ICI transport states).
_DEVICE_LOSS_RE = re.compile(
    r"(?i)(device\s+(lost|fail|halt)|DEVICE_SHUTDOWN|slice.*(preempt|halt)"
    r"|preempt(ed|ion)|UNAVAILABLE|ABORTED|DATA_LOSS"
    r"|hardware\s+fail|core\s+halt)")


def is_device_loss(exc: BaseException) -> bool:
    """Is this exception the device-loss family the fault barrier owns?

    True for the synthetic :class:`DeviceLossError` and for a real
    ``XlaRuntimeError`` whose message matches the device-death markers.
    Everything else — including other XlaRuntimeErrors (compile errors,
    shape mismatches: deterministic bugs a remesh would merely replay) —
    is NOT device loss and must propagate.
    """
    if isinstance(exc, DeviceLossError):
        return True
    xla_err = xla_runtime_error_type()
    if xla_err is not None and isinstance(exc, xla_err):
        return bool(_DEVICE_LOSS_RE.search(str(exc)))
    return False


def enumerate_healthy(devices: Sequence) -> list:
    """Re-enumerate which of ``devices`` still accept work.

    One scalar ``device_put`` + readback per candidate; survivors come
    back in the input order (stable, so a rebuilt mesh keeps the
    surviving prefix layout deterministic).  On the CPU backend every
    virtual device always answers — synthetic losses are the
    :class:`FaultInjector`'s job there.
    """
    import numpy as np

    import jax

    healthy = []
    probe = np.zeros((), np.int32)
    for dev in devices:
        try:
            jax.block_until_ready(jax.device_put(probe, dev))
        except Exception:
            # the probe failing IS the health verdict this function
            # exists to produce; the dead device simply drops out
            continue
        healthy.append(dev)
    return healthy


class FaultInjector:
    """Deterministic synthetic device loss at global step K.

    ``lose_at`` maps GLOBAL train-step numbers to how many devices that
    event takes down (dropped from the TAIL of the current device list,
    so the surviving prefix matches what a fresh process would lay its
    shrunk mesh over — the parity spec's requirement).  The trainer
    calls :meth:`note_steps` after every train dispatch, BEFORE any
    bookkeeping: a superstep whose chunk covers a scheduled step raises
    mid-chunk semantics — nothing from that dispatch is committed, the
    barrier restores the newest durable snapshot.

    Each event fires exactly once (keyed by global step), so the
    post-restore REPLAY of the same steps does not re-trigger it — the
    device is already gone.
    """

    def __init__(self, lose_at: Mapping[int, int]):
        self._lose_at = {int(k): int(v) for k, v in dict(lose_at).items()}
        for step, n in self._lose_at.items():
            if step < 1 or n < 1:
                raise ValueError(
                    f"FaultInjector lose_at[{step}]={n}: steps and "
                    "device counts must be >= 1")
        # devices lost by events not yet consumed by healthy()
        self._pending_lost = 0
        self.events: list[tuple[int, int]] = []

    def note_steps(self, global_step_before: int, n: int) -> None:
        """A dispatch just covered global steps (before, before+n]."""
        lo, hi = int(global_step_before), int(global_step_before) + int(n)
        hit = sorted(s for s in self._lose_at if lo < s <= hi)
        if not hit:
            return
        lost = sum(self._lose_at.pop(s) for s in hit)
        self._pending_lost += lost
        self.events.append((hit[0], lost))
        raise DeviceLossError(
            f"synthetic device loss at step {hit[0]}: {lost} device(s) "
            f"dropped (dispatch covered steps {lo + 1}..{hi})", lost=lost)

    def healthy(self, devices: Sequence) -> list:
        """The surviving subset of ``devices`` after pending loss events
        (tail-dropped, order preserved); consuming resets the pending
        count so sequential losses compose."""
        lost, self._pending_lost = self._pending_lost, 0
        keep = max(0, len(devices) - lost)
        return list(devices)[:keep]
