"""Multi-host bring-up: ``jax.distributed`` over DCN, one global mesh.

The reference's only distribution is app-plane RPC (Thrift/AMQP/redis —
SURVEY.md §5.8); its ML core is strictly single-device.  The multi-host
tier here follows the TPU-native recipe instead of translating an
NCCL/MPI design:

- every host runs the SAME single-controller program and calls
  :func:`initialize_distributed` first — a no-op for single-process runs,
  so one code path serves laptop, single chip, and pod;
- after initialization ``jax.devices()`` is the GLOBAL device set; the
  (data, expert, model) mesh is laid over it with **data outermost** so
  the per-step gradient all-reduce crosses DCN once while expert/model
  collectives (the mixing sum, TP reductions) stay on intra-slice ICI
  (the "collectives ride ICI, not DCN" rule);
- each host feeds only its own shard of the global batch
  (:func:`process_batch_slice` + :func:`feed_global_batch`), the standard
  single-controller data path (``jax.make_array_from_process_local_data``).

Single-process tests exercise all of this on the virtual CPU mesh; the
arithmetic (slicing, axis layout) is process-count-parameterized so the
multi-host math is testable without multiple hosts.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeprest_tpu.config import MeshConfig
from deeprest_tpu.parallel.mesh import AXES, make_mesh


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> bool:
    """Join the multi-host job if one is configured; returns whether it was.

    Configuration comes from the arguments or the standard environment
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``;
    on TPU pods ``jax.distributed.initialize()`` auto-discovers all three
    from the metadata server, so bare ``initialize_distributed()`` works
    there too).  With no configuration at all this is a no-op returning
    False — single-process runs never pay for the distributed service.
    """
    env = os.environ
    coordinator_address = (coordinator_address
                           or env.get("JAX_COORDINATOR_ADDRESS") or None)
    if num_processes is None and env.get("JAX_NUM_PROCESSES"):
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and env.get("JAX_PROCESS_ID"):
        process_id = int(env["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def global_mesh(config: MeshConfig | None = None,
                devices: Sequence[jax.Device] | None = None) -> Mesh:
    """The (data, expert, model) mesh over the global device set.

    A documentation-carrying alias of :func:`make_mesh` (same defaults):
    after :func:`initialize_distributed`, ``jax.devices()`` is global, and
    the C-order reshape puts the **data axis outermost** — it strides
    across whole hosts, so the gradient all-reduce crosses DCN while
    expert/model collectives stay on intra-host ICI.  The default config
    (data = every device) is the DP north-star layout.
    """
    return make_mesh(config, devices=devices)


def process_batch_slice(global_batch: int,
                        process_index: int | None = None,
                        process_count: int | None = None) -> slice:
    """This process's contiguous slice of the global batch axis.

    The global batch must divide evenly — a ragged split would desync the
    compiled step's static shapes across hosts.
    """
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    if global_batch % process_count != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{process_count} processes")
    per = global_batch // process_count
    return slice(process_index * per, (process_index + 1) * per)


def _feed_data_sharded(mesh: Mesh, arr: np.ndarray,
                       axes: tuple[str | None, ...]) -> jax.Array:
    """The ONE per-host feed path: slice this process's contiguous chunk
    of the ``data``-sharded axis and let
    ``jax.make_array_from_process_local_data`` stitch the global array.

    Under one process the local slice IS the global array, so the virtual
    CPU mesh exercises the exact multi-process assembly code (not a
    device_put twin of it) — no host ever ships another host's rows to
    its devices, and there is no second code path to drift.
    """
    ax = axes.index("data")
    n = int(arr.shape[ax])
    data_size = int(mesh.shape["data"])
    if n % data_size != 0:
        # device_put would raise an opaque GSPMD shape error here — and
        # older jax versions silently REPLICATED the batch instead of
        # sharding it (8x the per-device memory and a wrong-throughput
        # measurement, never a wrong result).  Fail loudly with the fix:
        # the trainer's _batches/_epoch_plan already pad ragged batches
        # with zero-weight rows, so a divisible batch size is one config
        # knob away.
        raise ValueError(
            f"batch axis {ax} of shape {tuple(arr.shape)} has {n} rows, "
            f"not divisible by the mesh data axis ({data_size} shards); "
            "pad the batch to a multiple with zero-weight rows (the "
            "trainer's _batches wrap-padding) or pick a batch size "
            "divisible by MeshConfig.data")
    # graftlint: disable=JX005 -- designed feed-path site: batch/plan arrays are constructed here from the table-owned axis names, not per-leaf state specs
    sharding = NamedSharding(mesh, P(*axes))
    sel = (slice(None),) * ax + (process_batch_slice(n),)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(arr[sel]))


def feed_global_batch(mesh: Mesh, global_batch: np.ndarray,
                      axes: tuple[str | None, ...] | None = None) -> jax.Array:
    """Turn the host-side GLOBAL batch into the global data-sharded array.

    Every process passes the same ``global_batch`` view (deterministic
    selection keeps them identical across hosts); each keeps only its
    :func:`process_batch_slice` of the ``data`` axis and the
    per-host assembly (:func:`_feed_data_sharded`) stitches the global
    array.  A batch axis not divisible by the mesh's data-axis size
    raises immediately (it used to silently replicate on older jax).
    """
    if axes is None:
        axes = ("data",) + (None,) * (global_batch.ndim - 1)
    return _feed_data_sharded(mesh, np.asarray(global_batch), axes)


def feed_replicated(mesh: Mesh, arr: np.ndarray) -> jax.Array:
    """A fully-replicated global array from identical per-process data
    (eval/predict inputs: every process holds the same windows)."""
    # graftlint: disable=JX005 -- designed feed-path site: replicated input placement, not a per-leaf state spec
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(arr))


def feed_global_coo(mesh: Mesh, cols: np.ndarray, vals: np.ndarray,
                    axes: tuple[str | None, ...] | None = None
                    ) -> tuple[jax.Array, jax.Array]:
    """The padded-COO twin of :func:`feed_global_batch`: ship a sparse
    ``(cols[..., K], vals[..., K])`` window batch data-sharded over the
    mesh.

    Both halves shard identically along the leading (batch) axis so a
    row's columns and values land on the same shard; the divisibility
    contract (and its loud error) is :func:`_feed_data_sharded`'s.  At
    the 10k-endpoint width this is the ~F/(2K) host→device byte saving
    the sparse-first pipeline exists for (ops/densify.py densifies on
    device inside the consuming executable).
    """
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    if cols.shape != vals.shape:
        raise ValueError(
            f"padded-COO halves disagree: cols {cols.shape} vs "
            f"vals {vals.shape}")
    if axes is None:
        axes = ("data",) + (None,) * (cols.ndim - 1)
    return (_feed_data_sharded(mesh, cols, axes),
            _feed_data_sharded(mesh, vals, axes))


def stage_sparse_base(mesh: Mesh, cols: np.ndarray, vals: np.ndarray,
                      mn: np.ndarray, rg: np.ndarray, capacity: int):
    """Replicated device residency for a padded-COO BASE series plus its
    normalization stats — the sparse twin of the trainer's staged dense
    base (every process holds the same rows; per-step feeds are then just
    ``[B]`` start indices).  Returns an ``ops.densify.SparseBase`` whose
    static ``capacity`` the consuming jit treats as a compile-time
    constant.  Stats ride as device arrays (runtime ARGUMENTS — baked
    constants would let XLA strength-reduce the normalize divide and
    break bit parity; the serve/fused.py lesson)."""
    from deeprest_tpu.ops.densify import SparseBase

    return SparseBase(
        cols=feed_replicated(mesh, np.asarray(cols, np.int32)),
        vals=feed_replicated(mesh, np.asarray(vals, np.float32)),
        mn=feed_replicated(mesh, np.asarray(mn, np.float32)),
        rg=feed_replicated(mesh, np.asarray(rg, np.float32)),
        capacity=int(capacity))


def prefetch_to_device(mesh: Mesh, batches, depth: int = 2):
    """Overlap host→device transfer with device compute.

    ``batches`` yields tuples of host numpy arrays; each is fed through
    :func:`feed_global_batch` immediately (device transfers are
    asynchronous), and up to ``depth`` fed batches are kept in flight ahead
    of the consumer — so the copy of batch t+1 proceeds while the step on
    batch t executes.  ``depth=0`` degenerates to synchronous per-batch
    feeding.  Order is preserved exactly, so training is bit-identical with
    or without prefetch.
    """
    import collections

    queue: collections.deque = collections.deque()
    for batch in batches:
        queue.append(tuple(feed_global_batch(mesh, np.asarray(a))
                           for a in batch))
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def stage_plan(mesh: Mesh, starts: np.ndarray,
               weights: np.ndarray) -> tuple[jax.Array, jax.Array]:
    """Ship an epoch's superstep batch plan to device memory once.

    ``starts``/``weights`` are ``[C, S, B]`` (chunks × steps-per-superstep
    × batch) host arrays from ``Trainer._epoch_plan``.  The batch axis is
    the TRAILING one and shards over the mesh's ``data`` axis — the
    in-step gather then produces a data-sharded window batch, keeping the
    superstep data-parallel exactly like the per-step indexed feed.  On a
    pod every process passes the same (rng-deterministic) global plan and
    keeps only its batch slice, mirroring :func:`feed_global_batch`'s
    contract for the leading axis.
    """
    def ship(a: np.ndarray) -> jax.Array:
        axes = (None,) * (a.ndim - 1) + ("data",)
        return _feed_data_sharded(mesh, np.asarray(a), axes)

    return ship(np.asarray(starts)), ship(np.asarray(weights))


def gather_to_host(arr: jax.Array) -> np.ndarray:
    """A numpy copy of a possibly cross-host-sharded array on every host
    (eval predictions feeding the host-side MAE report)."""
    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


__all__ = [
    "AXES",
    "initialize_distributed",
    "global_mesh",
    "process_batch_slice",
    "feed_global_batch",
    "feed_global_coo",
    "feed_replicated",
    "stage_sparse_base",
    "prefetch_to_device",
    "stage_plan",
    "gather_to_host",
]
