"""Quantile (pinball) regression loss.

Vectorized over (batch, time, metric, quantile) in one shot instead of the
reference's per-metric/per-quantile Python loops (reference:
resource-estimation/qrnn.py:58-67); reductions are arranged to be
algebraically identical: sum over quantiles, mean over batch×time, mean over
metrics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pinball_loss(
    preds: jax.Array,
    targets: jax.Array,
    quantiles: tuple[float, ...] | jax.Array,
    sample_weight: jax.Array | None = None,
    allow_empty: bool = False,
) -> jax.Array:
    """Mean pinball loss.

    Args:
      preds: ``[B, T, E, Q]`` quantile predictions.
      targets: ``[B, T, E]`` observed values.
      quantiles: the Q quantile levels in prediction order.
      sample_weight: optional ``[B]`` weights; the batch mean becomes a
        weighted mean.  Used to pad ragged trailing batches up to a static
        shape with zero-weight duplicates while keeping the loss exactly
        the mean over real samples.
      allow_empty: guard the weighted mean's denominator at 1 so an
        all-zero-weight batch yields loss 0 (and exactly-zero gradients)
        instead of 0/0 NaN.  Real batches have ``sum(weight) >= 1``, where
        ``max(sum, 1)`` returns the identical float — bit-equal to the
        unguarded loss (the window-coalesced trainer relies on this:
        zero-weight pad microbatches inside a partially-real group must
        contribute nothing without a per-microbatch cond branch).

    Returns: scalar loss,
      ``mean_E( mean_{B,T}( sum_Q max((q-1)·err, q·err) ) )``
      with ``err = target - pred``.
    """
    q = jnp.asarray(quantiles, dtype=preds.dtype)  # [Q]
    err = targets[..., None] - preds               # [B, T, E, Q]
    per_q = jnp.maximum((q - 1.0) * err, q * err)  # [B, T, E, Q]
    per_sample = jnp.sum(per_q, axis=-1)           # [B, T, E]
    if sample_weight is None:
        per_metric = jnp.mean(per_sample, axis=(0, 1))
    else:
        w = sample_weight.astype(per_sample.dtype)[:, None, None]
        den = jnp.sum(sample_weight)
        if allow_empty:
            den = jnp.maximum(den, jnp.ones((), den.dtype))
        per_metric = jnp.sum(per_sample * w, axis=(0, 1)) / (
            den * per_sample.shape[1]
        )
    return jnp.mean(per_metric)
