"""Expert-batched GRU as a TPU-friendly `lax.scan`.

The reference runs one `torch.nn.GRU` per expert in a Python loop
(reference: resource-estimation/qrnn.py:24,33-44).  Here all experts run as
one batched scan with the expert axis `E` as a leading array dimension, which

- turns E small matmuls per step into one `[E,B,H] x [E,H,3H]` batched
  matmul that tiles onto the MXU,
- **hoists the input projections out of the recurrence**: the `x @ W_ih`
  term has no sequential dependency, so it is computed for all T time steps
  as a single large `[E,B*T,F] x [E,F,3H]` matmul before the scan; only the
  hidden-to-hidden matmul stays inside the sequential loop, and
- makes expert parallelism a sharding annotation on axis 0 instead of a
  code change.

Gate math matches torch's GRU (gate order r, z, n; two separate biases;
``n = tanh(x_n + b_in + r * (h @ W_hn + b_hn))``) so numerics are directly
comparable against the public torch API.
"""

from __future__ import annotations

import os as _os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_BACKENDS = ("auto", "scan", "pallas", "pallas_interpret")

# Fused bidirectional (both directions stacked on the expert axis of ONE
# gru_recurrence call) never demonstrated a win at the production bf16
# dtypes: the round-4 fused on-chip headline was 117.2 steps/s vs the
# round-3 unfused 122.0 (PERF.md "Measured so far"), and PERF.md committed
# to reverting if unfused won.  Round 11 executes that revert: the default
# pallas bidirectional path is two single-direction gru_recurrence calls.
# The fused path stays behind this knob so benchmarks/kernel_tuning.py can
# keep A/B-ing it on-chip without a code edit.
BIDIR_FUSED = _os.environ.get("DEEPREST_GRU_BIDIR_FUSED", "0") == "1"


def _resolve_backend(backend: str) -> str:
    """'auto' → the fused pallas kernel on TPU, `lax.scan` elsewhere."""
    if backend not in _BACKENDS:
        raise ValueError(f"unknown GRU backend {backend!r}; one of {_BACKENDS}")
    if backend == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "scan"
    return backend


class GRUParams(NamedTuple):
    """One direction of GRU weights with a leading expert axis.

    Shapes: ``w_ih [E, F, 3H]``, ``w_hh [E, H, 3H]``, ``b_ih [E, 3H]``,
    ``b_hh [E, 3H]``; gate order along the ``3H`` axis is (r, z, n).
    """

    w_ih: jax.Array
    w_hh: jax.Array
    b_ih: jax.Array
    b_hh: jax.Array

    @property
    def hidden_size(self) -> int:
        return self.w_hh.shape[-2]


def resolve_weights(params: GRUParams) -> GRUParams:
    """Weights-adapter hook (round 22): dequantize-at-use for quantized
    serving weights, identity otherwise.  Called once at the top of the
    ``gru``/``bidirectional_gru`` entry points — the coalesced variants
    delegate to them, so EVERY recurrence path (scan, pallas, coalesced,
    bidirectional) shares the one sanctioned dequant site
    (ops/quantize.dequantize); the widen+scale runs inside the calling
    executable and XLA fuses it into the first projection dot."""
    from deeprest_tpu.ops.quantize import QuantTensor, dequantize

    if isinstance(params.w_ih, QuantTensor) \
            or isinstance(params.w_hh, QuantTensor):
        return params._replace(w_ih=dequantize(params.w_ih),
                               w_hh=dequantize(params.w_hh))
    return params


def init_gru_params(
    key: jax.Array, num_experts: int, input_size: int, hidden_size: int,
    dtype=jnp.float32,
) -> GRUParams:
    """Uniform(-1/sqrt(H), 1/sqrt(H)) init, the torch GRU default, so
    like-for-like numerical comparisons start from the same distribution."""
    k = 1.0 / np.sqrt(hidden_size)
    ks = jax.random.split(key, 4)
    shapes = [
        (num_experts, input_size, 3 * hidden_size),
        (num_experts, hidden_size, 3 * hidden_size),
        (num_experts, 3 * hidden_size),
        (num_experts, 3 * hidden_size),
    ]
    return GRUParams(*[
        jax.random.uniform(kk, s, dtype=dtype, minval=-k, maxval=k)
        for kk, s in zip(ks, shapes)
    ])


def _gru_scan(
    params: GRUParams,
    x: jax.Array,
    h0: jax.Array,
    reverse: bool,
    unroll: int,
) -> jax.Array:
    """Core scan. x: [E, B, T, F]; h0: [E, B, H] → outputs [E, B, T, H]."""
    # Hoisted input projection: one big MXU matmul over all time steps,
    # time-major for the scan.  A rank-3 ``x [B,T,F]`` is shared across all
    # experts without materializing E copies (the per-expert feature mask is
    # folded into w_ih by the caller instead — see models/qrnn.py).
    if x.ndim == 3:
        proj = jnp.einsum("btf,efg->tebg", x, params.w_ih) + params.b_ih[:, None, :]
    else:
        proj = jnp.einsum("ebtf,efg->tebg", x, params.w_ih) + params.b_ih[:, None, :]

    def step(h, xproj):
        # xproj: [E,B,3H]; h: [E,B,H]
        gates_h = jnp.einsum("ebh,ehg->ebg", h, params.w_hh) + params.b_hh[:, None, :]
        xr, xz, xn = jnp.split(xproj, 3, axis=-1)
        hr, hz, hn = jnp.split(gates_h, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        return h_new, h_new

    _, outs = jax.lax.scan(step, h0, proj, reverse=reverse, unroll=unroll)
    return jnp.moveaxis(outs, 0, 2)  # [T,E,B,H] -> [E,B,T,H]


def _kernel_io_dtype(dtype) -> jnp.dtype:
    """bf16 proj stays bf16 (the producing einsum already quantized the
    values, so wider storage only doubles the recurrence's dominant HBM
    stream — proj in, dproj out); anything else upcasts to f32.  For bf16
    models the kernel also runs its matmuls in bf16 (f32 accumulate) and
    W_hh ships in bf16; the hidden-state CARRY and all gate elementwise
    math stay f32 in VMEM (pallas_gru._dot_dtype_for)."""
    return jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32


def _project(params: GRUParams, x: jax.Array) -> jax.Array:
    """Hoisted input projection ``x @ W_ih + b_ih`` → [E, T, B, 3H] in the
    kernel's I/O dtype."""
    eq = "btf,efg->etbg" if x.ndim == 3 else "ebtf,efg->etbg"
    proj = jnp.einsum(eq, x, params.w_ih) + params.b_ih[:, None, None, :]
    return proj.astype(_kernel_io_dtype(proj.dtype))


def _pad_proj(proj: jax.Array, b_pad: int, e_pad: int, t_pad: int) -> jax.Array:
    """Shape hygiene for the kernel's tiling constraints.  The time pad
    sits at the END of scan order (callers flip BEFORE padding), beyond
    every real output: sliced off afterwards, zero incoming gradient in
    the VJP."""
    e, t, b, _ = proj.shape
    if b_pad != b:
        proj = jnp.pad(proj, ((0, 0), (0, 0), (0, b_pad - b), (0, 0)))
    if e_pad:
        proj = jnp.pad(proj, ((0, e_pad), (0, 0), (0, 0), (0, 0)))
    if t_pad:
        proj = jnp.pad(proj, ((0, 0), (0, t_pad), (0, 0), (0, 0)))
    return proj


def _pad_weights(params: GRUParams, e_pad: int, io_dtype):
    # W_hh ships in the dot dtype: for bf16 models an f32 copy would
    # double its HBM/VMEM footprint only to be downcast inside every grid
    # program.  b_hh stays f32 (it is ADDED to the f32 accumulator).
    w_hh = params.w_hh.astype(io_dtype)
    b_hh = params.b_hh.astype(jnp.float32)
    if e_pad:
        w_hh = jnp.pad(w_hh, ((0, e_pad), (0, 0), (0, 0)))
        b_hh = jnp.pad(b_hh, ((0, e_pad), (0, 0)))
    return w_hh, b_hh


def _gru_pallas(
    params: GRUParams,
    x: jax.Array,
    h0: jax.Array,
    reverse: bool,
    interpret: bool,
) -> jax.Array:
    """Fused-kernel path: hoisted input projection (one MXU einsum), then the
    pallas recurrence of ops/pallas_gru.py. Output matches the scan path's
    layout/time-alignment; see that module for the kernel design."""
    from deeprest_tpu.ops import pallas_gru

    proj = _project(params, x)
    e, t, b, _ = proj.shape
    b_pad = pallas_gru.pad_batch(b, proj.dtype)
    e_pad = -e % pallas_gru.E_BLK
    t_pad = pallas_gru.pad_time(t) - t
    if reverse:
        proj = jnp.flip(proj, axis=1)
    proj = _pad_proj(proj, b_pad, e_pad, t_pad)
    w_hh, b_hh = _pad_weights(params, e_pad, proj.dtype)
    h0 = h0.astype(jnp.float32)
    if b_pad != b:
        h0 = jnp.pad(h0, ((0, 0), (0, b_pad - b), (0, 0)))
    if e_pad:
        h0 = jnp.pad(h0, ((0, e_pad), (0, 0), (0, 0)))
    h_all = pallas_gru.gru_recurrence(proj, w_hh, b_hh, h0, interpret)
    if t_pad:
        h_all = h_all[:, :t]
    if reverse:
        h_all = jnp.flip(h_all, axis=1)
    h_all = h_all[:e, :, :b]
    return jnp.moveaxis(h_all, 1, 2).astype(x.dtype)  # [E,B,T,H]


def gru(
    params: GRUParams,
    x: jax.Array,
    h0: jax.Array | None = None,
    reverse: bool = False,
    unroll: int = 4,
    backend: str = "auto",
) -> jax.Array:
    """Single-direction GRU over the time axis.

    Args:
      params: expert-stacked weights.
      x: inputs ``[E, B, T, F]``, or ``[B, T, F]`` shared across experts.
      h0: initial hidden state ``[E, B, H]`` (zeros if None — the reference
          always starts from zeros, reference: resource-estimation/qrnn.py:38-41).
      reverse: scan the sequence back-to-front; outputs stay time-aligned
          with ``x`` (``out[:, :, t]`` is the state after consuming x[t] in
          scan order), matching the torch bidirectional layout.
      unroll: scan unroll factor (amortizes per-step overhead on TPU).
      backend: 'auto' | 'scan' | 'pallas' | 'pallas_interpret'. 'auto'
          picks the fused pallas kernel on TPU backends, `lax.scan`
          elsewhere; 'pallas_interpret' runs the kernel in interpret mode
          (CPU numerics tests).

    Returns: ``[E, B, T, H]`` hidden states.
    """
    params = resolve_weights(params)
    e = params.w_ih.shape[0]
    b = x.shape[-3]
    if h0 is None:
        h0 = jnp.zeros((e, b, params.hidden_size), dtype=x.dtype)
    resolved = _resolve_backend(backend)
    if resolved != "scan":
        from deeprest_tpu.ops import pallas_gru

        if pallas_gru.supported(x.shape[-2], params.hidden_size):
            return _gru_pallas(params, x, h0, reverse,
                               interpret=resolved == "pallas_interpret")
        if backend != "auto":
            # An explicit pallas request that silently ran the scan path
            # would hide a perf bug; 'auto' falls through quietly by design.
            import warnings

            warnings.warn(
                f"GRU backend {backend!r} requested but unsupported for "
                f"T={x.shape[-2]}, H={params.hidden_size} (needs H % 128 == 0);"
                " falling back to lax.scan",
                stacklevel=2,
            )
    return _gru_scan(params, x, h0, reverse=reverse, unroll=unroll)


# ---------------------------------------------------------------------------
# window-coalesced batching (round 11)
# ---------------------------------------------------------------------------


class GroupSpec(NamedTuple):
    """Segment descriptor for a row-coalesced batch: ``groups`` independent
    window batches of ``rows`` windows each, stacked along the recurrence's
    B (row) axis as ``[G·B, ...]`` in group-major order.

    Groups share the SAME weights — which is exactly why the fold is
    algebraically free: unlike the rejected expert fold (PERF.md round 5:
    each expert contracts its OWN ``W_hh``, so stacking experts into rows
    needs a block-diagonal embedding that multiplies FLOPs), window batches
    all contract one shared ``W_hh``, so G thin ``[B,H]×[H,3H]`` dots
    become one ``[G·B,H]×[H,3H]`` dot with G× the MXU row occupancy.
    Unlike serve/fused.py's carry-offset/segment-reset vectors there is no
    cross-row state to reset — every window batch starts from ``h0`` and
    rows never interact — so the descriptor is pure split bookkeeping.
    """

    groups: int
    rows: int

    @property
    def coalesced_rows(self) -> int:
        return self.groups * self.rows


def coalesce_windows(x: jax.Array) -> tuple[jax.Array, GroupSpec]:
    """``[G, B, T, F] → ([G·B, T, F], GroupSpec)`` — fold group batches
    into the row axis (group-major, zero-copy reshape)."""
    if x.ndim != 4:
        raise ValueError(f"expected [G, B, T, F] window groups, got shape "
                         f"{x.shape}")
    g, b = x.shape[:2]
    return x.reshape(g * b, *x.shape[2:]), GroupSpec(groups=g, rows=b)


def split_coalesced(h: jax.Array, spec: GroupSpec) -> jax.Array:
    """``[E, G·B, T, D] → [E, G, B, T, D]`` — unfold a coalesced GRU
    output back to per-group batches."""
    if h.shape[1] != spec.coalesced_rows:
        raise ValueError(
            f"coalesced output has {h.shape[1]} rows; spec says "
            f"{spec.groups}x{spec.rows}={spec.coalesced_rows}")
    return h.reshape(h.shape[0], spec.groups, spec.rows, *h.shape[2:])


def gru_coalesced(
    params: GRUParams,
    x: jax.Array,
    h0: jax.Array | None = None,
    reverse: bool = False,
    unroll: int = 4,
    backend: str = "auto",
) -> jax.Array:
    """Single-direction GRU over G coalesced window batches.

    ``x``: ``[G, B, T, F]`` independent window batches → ``[E, G, B, T, H]``
    hidden states.  All G batches ride ONE ``gru`` call (one recurrence
    kernel invocation on the pallas backends) with ``G·B`` rows in every
    per-step matmul; each group's output slice is bit-identical to a
    standalone ``gru`` call on that group (rows are independent — pinned by
    tests/test_coalesce.py).  ``h0``, when given, is per group:
    ``[E, G, B, H]``.
    """
    flat, spec = coalesce_windows(x)
    if h0 is not None:
        h0 = h0.reshape(h0.shape[0], spec.coalesced_rows, h0.shape[-1])
    out = gru(params, flat, h0=h0, reverse=reverse, unroll=unroll,
              backend=backend)
    return split_coalesced(out, spec)


def bidirectional_gru_coalesced(
    fwd: GRUParams,
    bwd: GRUParams,
    x: jax.Array,
    unroll: int = 4,
    backend: str = "auto",
) -> jax.Array:
    """Bidirectional variant of :func:`gru_coalesced`:
    ``[G, B, T, F] → [E, G, B, T, 2H]`` with both directions' recurrences
    each running once over the coalesced ``G·B`` rows."""
    flat, spec = coalesce_windows(x)
    out = bidirectional_gru(fwd, bwd, flat, unroll=unroll, backend=backend)
    return split_coalesced(out, spec)


def _bidir_pallas(
    fwd: GRUParams,
    bwd: GRUParams,
    x: jax.Array,
    interpret: bool,
) -> jax.Array:
    """Fused bidirectional kernel path: BOTH directions ride one
    ``gru_recurrence`` invocation, stacked along the expert axis with the
    backward direction's projections pre-flipped in time.

    The recurrence kernel is direction-agnostic — it only ever scans its
    grid forward — so direction fusion is pure plumbing: stack
    ``[E,...]``+``[E,...]`` into ``[2E,...]``, run once, split.  This
    halves the pallas invocations per layer (2→1 forward, 2→1 in the VJP)
    and doubles the expert-block count each invocation pipelines over,
    which is where the per-call ramp overhead went at the flagship shape
    (VERDICT r3: fused bidirectional listed as explored but not
    productionized).
    """
    from deeprest_tpu.ops import pallas_gru

    e = fwd.w_ih.shape[0]
    b = x.shape[-3]
    t = x.shape[-2]
    h = fwd.hidden_size

    proj_f = _project(fwd, x)
    proj_b = jnp.flip(_project(bwd, x), axis=1)   # flip BEFORE padding

    b_pad = pallas_gru.pad_batch(b, proj_f.dtype)
    e_pad = -e % pallas_gru.E_BLK
    t_pad = pallas_gru.pad_time(t) - t

    proj = jnp.concatenate([_pad_proj(proj_f, b_pad, e_pad, t_pad),
                            _pad_proj(proj_b, b_pad, e_pad, t_pad)], axis=0)
    wf, bf = _pad_weights(fwd, e_pad, proj_f.dtype)
    wb, bb = _pad_weights(bwd, e_pad, proj_f.dtype)
    w_hh = jnp.concatenate([wf, wb], axis=0)
    b_hh = jnp.concatenate([bf, bb], axis=0)
    h0 = jnp.zeros((2 * (e + e_pad), b_pad, h), jnp.float32)

    h_all = pallas_gru.gru_recurrence(proj, w_hh, b_hh, h0, interpret)
    if t_pad:
        h_all = h_all[:, :t]
    half = e + e_pad
    out_f = h_all[:e, :, :b]
    out_b = jnp.flip(h_all[half:half + e], axis=1)[:, :, :b]
    out = jnp.concatenate([out_f, out_b], axis=-1)      # [E,T,B,2H]
    return jnp.moveaxis(out, 1, 2).astype(x.dtype)      # [E,B,T,2H]


def bidirectional_gru(
    fwd: GRUParams,
    bwd: GRUParams,
    x: jax.Array,
    unroll: int = 4,
    backend: str = "auto",
) -> jax.Array:
    """Bidirectional GRU: ``[E, B, T, F] → [E, B, T, 2H]``.

    Output layout matches torch: last-dim halves are (forward, backward),
    each time-aligned with the input.  On the pallas path both directions
    run fused in one kernel invocation (see :func:`_bidir_pallas`).
    """
    fwd, bwd = resolve_weights(fwd), resolve_weights(bwd)
    resolved = _resolve_backend(backend)
    if resolved != "scan" and BIDIR_FUSED:
        from deeprest_tpu.ops import pallas_gru

        if pallas_gru.supported(x.shape[-2], fwd.hidden_size):
            return _bidir_pallas(fwd, bwd, x,
                                 interpret=resolved == "pallas_interpret")
    # Default (round-11 revert, PERF.md): two single-direction calls — on
    # the pallas backends each direction is its own kernel invocation.
    out_f = gru(fwd, x, reverse=False, unroll=unroll, backend=backend)
    out_b = gru(bwd, x, reverse=True, unroll=unroll, backend=backend)
    return jnp.concatenate([out_f, out_b], axis=-1)
