"""Fused pallas TPU kernel for the GRU recurrence.

The `lax.scan` recurrence in ops/gru.py lowers to an XLA while-loop whose
per-step state round-trips through HBM and whose per-step matmul is far too
small to hide loop overhead (B=32, H=128 — latency-bound, SURVEY.md §7.3).
This kernel runs the whole time loop *inside one pallas invocation*:

- grid = (expert_blocks, T) with time as the innermost (sequential) grid
  dimension; the hidden state lives in a VMEM scratch buffer that persists
  across time steps — zero HBM traffic for the carry;
- the hoisted input projections ``proj = x @ W_ih + b_ih`` (computed
  outside, one large MXU matmul) stream through VMEM blocks, double-
  buffered by the pallas pipeline;
- ``W_hh`` is indexed only by the expert block, so it stays resident in
  VMEM for all T steps of that block;
- the backward pass is a second pallas kernel walking the grid in reverse
  time order, recomputing gate activations from (proj, h_prev) — no
  activation stash beyond the forward outputs — and accumulating weight
  gradients in VMEM scratch, flushed to HBM on the final step.

Only the recurrence is hand-written: input/output projections, the feature
mask, mixing, and heads remain plain XLA einsums (models/qrnn.py), which
XLA already fuses well. Numerics match ops/gru.py's scan (gate order r,z,n;
``n = tanh(x_n + b_in + r · (h·W_hn + b_hn))``).

Used automatically on TPU backends (ops/gru.py dispatch); `interpret=True`
makes every entry point runnable on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os

# jax renamed pltpu.TPUCompilerParams → pltpu.CompilerParams; the fields
# used here (dimension_semantics) exist under both names.  Resolve once so
# the kernels trace on either side of the rename.
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Experts per kernel program: amortizes grid overhead while keeping
# VMEM residency (W_hh alone is E_BLK * H * 3H * 4B).  Env-overridable
# (DEEPREST_GRU_E_BLK) so on-chip sweeps can A/B without code edits.
E_BLK = int(_os.environ.get("DEEPREST_GRU_E_BLK", "8"))
# Time steps per kernel program.  Each program advances the recurrence
# T_BLK steps with the hidden state in VMEM scratch: fewer grid programs
# and fewer (larger) DMA blocks.  Inside a program the loop runs
# time-OUTER, expert-INNER so each step issues E_BLK *independent*
# matmuls that pipeline through the MXU (expert-outer would serialize
# each expert's whole T_BLK chain).  Measured on v5e at the flagship
# shape (benchmarks/kernel_tuning.py): ~25% faster than T_BLK=1.
# Callers pad T up to a multiple (pad_time); padded tail steps compute
# garbage that is sliced off, which is safe because the tail is beyond
# every real output in scan order.  Env-overridable (DEEPREST_GRU_T_BLK;
# clamped to ≥1 — 0 would divide-by-zero pad_time and empty the chooser's
# candidate list).
T_BLK = max(1, int(_os.environ.get("DEEPREST_GRU_T_BLK", "6")))
# f32 sublane granularity — batch is padded up to this.
_SUBLANE = 8
# Scoped-VMEM budget for one kernel program's blocks (the hardware limit
# is 16 MiB; headroom covers in-kernel temporaries the block math below
# cannot see).  Blocks indexed by the sequential time grid are double-
# buffered by the pallas pipeline and count twice.
_VMEM_BUDGET = int(_os.environ.get("DEEPREST_GRU_VMEM_BUDGET",
                                   str(14 << 20)))
# Stash the pre-activation hidden-side gates (h·W_hh + b_hh) from the
# training forward so the backward skips its recompute dot — per
# expert-step that removes one [B,H]x[H,3H] MXU dot (~1/3 of the
# backward's dispatches) for one extra [E,T,B,3H] stream in the kernel's
# I/O dtype each way (~0.3 ms HBM vs ~0.8 ms MXU at the flagship shape).
# Env-tunable for the on-chip A/B (benchmarks/kernel_tuning.py).
STASH_GATES = _os.environ.get("DEEPREST_GRU_STASH_GATES", "1") != "0"
# In-program loop order.  "expert_inner" (default) walks time outer /
# experts inner: each step issues E_BLK independent dots that pipeline
# through the MXU.  "time_inner" walks expert outer / time inner: all of
# one expert's steps run consecutively so the SAME W_hh tiles feed
# consecutive dots — scheduler-friendlier for weight reuse, but the
# sequential h dependency stalls between steps.  Which wins is a
# hardware-scheduling question; benchmarks/kernel_tuning.py settles it.
LOOP_ORDER = _os.environ.get("DEEPREST_GRU_LOOP_ORDER", "expert_inner")
def _checked_loop_order() -> str:
    """Validate LOOP_ORDER at every trace, not just env-var load — the
    tuning sweep (and tests) assign the module global directly, and a typo
    falling through an ``== "time_inner"`` check would silently mislabel
    an on-chip A/B."""
    if LOOP_ORDER not in ("expert_inner", "time_inner"):
        raise ValueError(
            f"DEEPREST_GRU_LOOP_ORDER={LOOP_ORDER!r}: must be "
            f"'expert_inner' or 'time_inner'")
    return LOOP_ORDER


_checked_loop_order()   # fail fast on a bad env var at import too


def _choose_blocks(e: int, t: int, per_expert_bytes) -> tuple[int, int]:
    """Pick (e_blk, t_blk) whose block footprint fits the scoped-VMEM
    budget.

    The f32 backward kernel at the default E_BLK=8/T_BLK=6 needs ~18 MB
    of double-buffered blocks — over the chip's 16 MiB scoped-VMEM limit
    (observed on v5e as a hard compile OOM) — while the bf16 production
    path fits.  The expert axis is the sublane of the 2-D f32 bias
    blocks, so pallas requires e_blk % 8 == 0 (or e_blk == e); the time
    axis is grid-leading and unconstrained, so VMEM pressure is relieved
    by shrinking t_blk.  ``per_expert_bytes`` maps t_blk → bytes per
    expert.  Correctness is unaffected (experts independent; the kernels
    carry hidden state across time blocks in scratch)."""
    legal_e = [c for c in range(_SUBLANE, e + 1, _SUBLANE)
               if e % c == 0 and c <= E_BLK] or [e]
    if E_BLK % _SUBLANE and E_BLK < e:
        import warnings

        warnings.warn(
            f"DEEPREST_GRU_E_BLK={E_BLK} is not a multiple of {_SUBLANE} "
            f"(the sublane of the 2-D f32 bias blocks) — pallas cannot "
            f"tile it; using e_blk={legal_e[-1]} instead", stacklevel=3)
    t_candidates = [c for c in range(min(T_BLK, t), 0, -1) if t % c == 0]
    # Prefer the widest expert block; shrink time first, then experts.
    for e_blk in reversed(legal_e):
        for t_blk in t_candidates:
            if e_blk * per_expert_bytes(t_blk) <= _VMEM_BUDGET:
                return e_blk, t_blk
    import warnings

    warnings.warn(
        f"GRU kernel block footprint exceeds the scoped-VMEM budget even "
        f"at ({legal_e[0]}, 1) — compile may OOM; raise "
        f"DEEPREST_GRU_VMEM_BUDGET only if the chip allows it",
        stacklevel=3)
    return legal_e[0], t_candidates[-1]


def _gates(xproj, gates_h):
    """Shared gate math. xproj/gates_h: [B, 3H] → (r, z, n)."""
    xr, xz, xn = jnp.split(xproj, 3, axis=-1)
    hr, hz, hn = jnp.split(gates_h, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return r, z, n, hn


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(proj_ref, w_ref, b_ref, h0_ref, *refs, dot_dtype, emit_prev,
                stash_gates, loop_order):
    # Training (emit_prev=True) also streams out the PRE-update hidden
    # state per step: the VJP consumes h_prev directly instead of
    # re-materializing it outside the kernel as concat(h0, h_all[:-1]) —
    # one full [E,T,B,H] HBM round-trip saved per step.  With stash_gates
    # the pre-activation hidden gates stream out too, so the backward
    # skips its recompute dot entirely.
    refs = list(refs)
    out_ref = refs.pop(0)
    prev_ref = refs.pop(0) if emit_prev else None
    gates_ref = refs.pop(0) if (emit_prev and stash_gates) else None
    (h_scr,) = refs
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)

    n_e, t_blk = proj_ref.shape[0], proj_ref.shape[1]
    hs = [h_scr[i] for i in range(n_e)]
    ws = [w_ref[i].astype(dot_dtype) for i in range(n_e)]
    bs = [b_ref[i].astype(jnp.float32) for i in range(n_e)]

    def step(i, tt):
        if prev_ref is not None:
            prev_ref[i, tt] = hs[i].astype(prev_ref.dtype)
        gates_h = (
            jax.lax.dot_general(hs[i].astype(dot_dtype), ws[i],
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            + bs[i]
        )
        if gates_ref is not None:
            gates_ref[i, tt] = gates_h.astype(gates_ref.dtype)
        xproj = proj_ref[i, tt].astype(jnp.float32)
        r, z, n, _ = _gates(xproj, gates_h)
        hs[i] = (1.0 - z) * n + z * hs[i]
        out_ref[i, tt] = hs[i].astype(out_ref.dtype)

    if loop_order == "time_inner":
        for i in range(n_e):          # experts OUTER: W_hh stays hot
            for tt in range(t_blk):   # time INNER: sequential chain
                step(i, tt)
    else:
        for tt in range(t_blk):       # time OUTER
            for i in range(n_e):      # experts INNER: independent matmuls
                step(i, tt)
    for i in range(n_e):
        h_scr[i] = hs[i]


def _dot_dtype_for(proj_dtype):
    """bf16 models run the recurrence matmuls in bf16 with f32 accumulation
    (an f32 matmul costs ~3x the MXU passes of bf16 and the model's own
    dtype is bf16 — the hidden-state CARRY stays f32 in VMEM either way);
    f32 models keep exact f32 dots."""
    return jnp.bfloat16 if proj_dtype == jnp.bfloat16 else jnp.float32


def _out_dtype_for(proj_dtype):
    """Hidden-state STORAGE dtype: bf16 models stream h in bf16 (the model
    casts h_all to its own dtype right after the kernel anyway — f32
    storage only doubled the largest HBM stream); f32 models stay exact.

    Currently coincides with _dot_dtype_for (matmul precision), but the
    two are distinct knobs: storage feeds the VJP's h_prev residual — and
    the _bwd_call byte accounting — while the dot dtype only picks the
    MXU path.  Change one without the other deliberately, not by drift.
    Accepted approximation for bf16 models: the backward's dz term
    (dh·(h_prev − n)) sees bf16-rounded h_prev where it previously saw
    the exact f32 carry — ~2^-9 relative, inside the bf16 training noise
    floor, and covered by the bf16 grad-parity test tolerances."""
    return jnp.bfloat16 if proj_dtype == jnp.bfloat16 else jnp.float32


def _fwd_per_expert_bytes(b, g3, h, proj_dtype, stash, n_h_out,
                          w_itemsize, h0_itemsize):
    """Forward-kernel VMEM bytes per expert as a function of t_blk — the
    single source for _choose_blocks AND the public block_plan probe."""
    io = jnp.dtype(proj_dtype).itemsize
    oo = jnp.dtype(_out_dtype_for(proj_dtype)).itemsize
    return lambda t_blk: (
        # proj in + h out (+ prev out and gates out when training),
        # double-buffered
        2 * (t_blk * b * g3 * io + n_h_out * t_blk * b * h * oo
             + (t_blk * b * g3 * io if stash else 0))
        + h * g3 * w_itemsize + g3 * 4                   # W_hh, b_hh resident
        + b * h * h0_itemsize + b * h * 4                # h0 block + scratch
    )


def _fwd_call(proj, w_hh, b_hh, h0, interpret, emit_prev=False):
    e, t, b, g3 = proj.shape
    h = g3 // 3
    assert t % T_BLK == 0, (t, T_BLK)   # callers pad_time first
    out_dtype = _out_dtype_for(proj.dtype)
    stash = emit_prev and STASH_GATES
    n_h_out = 2 if emit_prev else 1
    per_expert = _fwd_per_expert_bytes(b, g3, h, proj.dtype, stash, n_h_out,
                                       w_hh.dtype.itemsize, h0.dtype.itemsize)
    e_blk, t_blk = _choose_blocks(e, t, per_expert)
    eb = e // e_blk
    grid = (eb, t // t_blk)
    h_spec = pl.BlockSpec((e_blk, t_blk, b, h), lambda i, j: (i, j, 0, 0))
    h_shape = jax.ShapeDtypeStruct((e, t, b, h), out_dtype)
    out_specs, out_shape = [h_spec], [h_shape]
    if emit_prev:
        out_specs.append(h_spec)
        out_shape.append(h_shape)
    if stash:
        out_specs.append(
            pl.BlockSpec((e_blk, t_blk, b, g3), lambda i, j: (i, j, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((e, t, b, g3), proj.dtype))
    if not emit_prev:
        out_specs, out_shape = out_specs[0], out_shape[0]
    return pl.pallas_call(
        functools.partial(_fwd_kernel, dot_dtype=_dot_dtype_for(proj.dtype),
                          emit_prev=emit_prev, stash_gates=stash,
                          loop_order=_checked_loop_order()),
        grid=grid,
        in_specs=[
            pl.BlockSpec((e_blk, t_blk, b, g3), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((e_blk, h, g3), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((e_blk, g3), lambda i, j: (i, 0)),
            pl.BlockSpec((e_blk, b, h), lambda i, j: (i, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((e_blk, b, h), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(proj, w_hh, b_hh, h0)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_kernel(proj_ref, hprev_ref, *refs, dot_dtype, stash_gates,
                loop_order):
    if stash_gates:
        (gates_in_ref, w_ref, b_ref, dout_ref,
         dproj_ref, dw_ref, db_ref, dh0_ref,
         dh_scr, dw_scr, db_scr, dg_scr) = refs
    else:
        gates_in_ref = None
        (w_ref, b_ref, dout_ref,
         dproj_ref, dw_ref, db_ref, dh0_ref,
         dh_scr, dw_scr, db_scr, dg_scr) = refs
    t = pl.program_id(1)
    t_total = pl.num_programs(1)

    @pl.when(t == 0)  # first grid step == last time block
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dw_scr[...] = jnp.zeros_like(dw_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    n_e, t_blk = proj_ref.shape[0], proj_ref.shape[1]
    ws = [w_ref[i].astype(dot_dtype) for i in range(n_e)]
    bs = [b_ref[i].astype(jnp.float32) for i in range(n_e)]
    dhs = [dh_scr[i] for i in range(n_e)]
    dbs = [db_scr[i] for i in range(n_e)]
    def step(i, tt):
        h_prev = hprev_ref[i, tt].astype(jnp.float32)
        if gates_in_ref is not None:
            # Forward stashed the pre-activation hidden gates — no
            # recompute dot (1/3 of this kernel's per-step MXU work).
            gates_h = gates_in_ref[i, tt].astype(jnp.float32)
        else:
            gates_h = (
                jax.lax.dot_general(h_prev.astype(dot_dtype), ws[i],
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                + bs[i]
            )
        xproj = proj_ref[i, tt].astype(jnp.float32)
        r, z, n, hn = _gates(xproj, gates_h)

        dh_total = dout_ref[i, tt].astype(jnp.float32) + dhs[i]
        dn = dh_total * (1.0 - z)
        dz = dh_total * (h_prev - n)
        dtanh = dn * (1.0 - n * n)
        da_r = dtanh * hn * r * (1.0 - r)
        da_z = dz * z * (1.0 - z)
        dhn = dtanh * r
        # Gate-sliced stores instead of jnp.concatenate: each concat is a
        # full [B,3H] VPU copy per expert-step; the gate pieces land
        # directly in their 128-aligned lane slices of the output block
        # and the dgates stash (dot dtype — the SAME quantization the
        # old per-step dW dot applied).
        hh = da_r.shape[-1]
        dproj_ref[i, tt, :, 0:hh] = da_r.astype(dproj_ref.dtype)
        dproj_ref[i, tt, :, hh:2 * hh] = da_z.astype(dproj_ref.dtype)
        dproj_ref[i, tt, :, 2 * hh:3 * hh] = dtanh.astype(dproj_ref.dtype)
        dg_scr[i, tt, :, 0:hh] = da_r.astype(dg_scr.dtype)
        dg_scr[i, tt, :, hh:2 * hh] = da_z.astype(dg_scr.dtype)
        dg_scr[i, tt, :, 2 * hh:3 * hh] = dhn.astype(dg_scr.dtype)

        # dh_prev = dh·z + dgates_h @ W_hhᵀ (contract the 3H axis); the
        # dgates operand reads back from the stash in the dot dtype.
        dhs[i] = dh_total * z + jax.lax.dot_general(
            dg_scr[i, tt], ws[i], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dbs[i] = dbs[i] + jnp.concatenate(
            [jnp.sum(da_r, axis=0), jnp.sum(da_z, axis=0),
             jnp.sum(dhn, axis=0)])

    if loop_order == "time_inner":
        for i in range(n_e):               # experts OUTER: W_hh stays hot
            for tt in reversed(range(t_blk)):
                step(i, tt)
    else:
        for tt in reversed(range(t_blk)):  # time OUTER, back-to-front
            for i in range(n_e):           # experts INNER
                step(i, tt)
    for i in range(n_e):
        # dW_hh += h_prevᵀ @ dgates, contracted over the WHOLE time block
        # (K = t_blk·B instead of B): one MXU dot per block instead of one
        # per step — ~t_blk× fewer dW dispatches at far better systolic
        # occupancy; algebraically the same sum, reassociated.
        h_flat = hprev_ref[i].astype(dot_dtype).reshape(
            -1, hprev_ref.shape[-1])
        g_flat = dg_scr[i].reshape(-1, dg_scr.shape[-1])
        dw_scr[i] = dw_scr[i] + jax.lax.dot_general(
            h_flat, g_flat, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dh_scr[i] = dhs[i]
        db_scr[i] = dbs[i]

    @pl.when(t == t_total - 1)  # last grid step == time 0: flush accumulators
    def _flush():
        dw_ref[...] = dw_scr[...]
        db_ref[...] = db_scr[...]
        dh0_ref[...] = dh_scr[...]


def _bwd_per_expert_bytes(b, g3, h, proj_dtype, stash, hp_io, do_io,
                          w_itemsize):
    """Backward-kernel VMEM bytes per expert as a function of t_blk — the
    single source for _choose_blocks AND the public block_plan probe."""
    io = jnp.dtype(proj_dtype).itemsize
    dot_io = jnp.dtype(_dot_dtype_for(proj_dtype)).itemsize
    return lambda t_blk: (
        # time-grid blocks, double-buffered: proj, h_prev, dout (and the
        # stashed gates when present) in; dproj out (h_prev/dout ride the
        # model's out dtype — _vjp_bwd)
        2 * (t_blk * b * g3 * io + t_blk * b * h * (hp_io + do_io)
             + t_blk * b * g3 * io
             + (t_blk * b * g3 * io if stash else 0))
        # resident: W_hh + b_hh in, dW/db/dh0 out, dh/dW/db scratch,
        # dgates stash (dot dtype) for the block-batched dW dot
        + h * g3 * w_itemsize + g3 * 4
        + h * g3 * 4 + g3 * 4 + b * h * 4
        + b * h * 4 + h * g3 * 4 + g3 * 4
        + t_blk * b * g3 * dot_io
    )


def _bwd_call(proj, h_prev_all, gates_all, w_hh, b_hh, dout, interpret):
    e, t, b, g3 = proj.shape
    h = g3 // 3
    assert t % T_BLK == 0, (t, T_BLK)   # callers pad_time first
    stash = gates_all is not None
    per_expert = _bwd_per_expert_bytes(
        b, g3, h, proj.dtype, stash, h_prev_all.dtype.itemsize,
        dout.dtype.itemsize, w_hh.dtype.itemsize)
    e_blk, t_blk = _choose_blocks(e, t, per_expert)
    eb = e // e_blk
    nb = t // t_blk
    grid = (eb, nb)
    rev = lambda i, j: (i, nb - 1 - j, 0, 0)  # walk time blocks back-to-front
    in_specs = [
        pl.BlockSpec((e_blk, t_blk, b, g3), rev),
        pl.BlockSpec((e_blk, t_blk, b, h), rev),
    ]
    operands = [proj, h_prev_all]
    if stash:
        in_specs.append(pl.BlockSpec((e_blk, t_blk, b, g3), rev))
        operands.append(gates_all)
    in_specs += [
        pl.BlockSpec((e_blk, h, g3), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((e_blk, g3), lambda i, j: (i, 0)),
        pl.BlockSpec((e_blk, t_blk, b, h), rev),
    ]
    operands += [w_hh, b_hh, dout]
    dproj, dw, db, dh0 = pl.pallas_call(
        functools.partial(_bwd_kernel, dot_dtype=_dot_dtype_for(proj.dtype),
                          stash_gates=stash, loop_order=_checked_loop_order()),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((e_blk, t_blk, b, g3), rev),
            pl.BlockSpec((e_blk, h, g3), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((e_blk, g3), lambda i, j: (i, 0)),
            pl.BlockSpec((e_blk, b, h), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((e, t, b, g3), proj.dtype),
            jax.ShapeDtypeStruct((e, h, g3), jnp.float32),
            jax.ShapeDtypeStruct((e, g3), jnp.float32),
            jax.ShapeDtypeStruct((e, b, h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((e_blk, b, h), jnp.float32),
            pltpu.VMEM((e_blk, h, g3), jnp.float32),
            pltpu.VMEM((e_blk, g3), jnp.float32),
            pltpu.VMEM((e_blk, t_blk, b, g3), _dot_dtype_for(proj.dtype)),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return dproj, dw, db, dh0


# ---------------------------------------------------------------------------
# custom-VJP wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def gru_recurrence(proj, w_hh, b_hh, h0, interpret=False):
    """Run the GRU time recurrence over pre-projected inputs.

    Args:
      proj: ``[E, T, B, 3H]`` — ``x @ W_ih + b_ih`` per expert (gate order
        r, z, n along the last axis); f32 or bf16.  bf16 proj selects the
        bf16-dot path (_dot_dtype_for): matmuls run bf16 with f32
        accumulation while the carry and gate math stay f32 in VMEM —
        bf16 I/O also halves the dominant HBM stream, and
        ``dproj`` comes back in the same dtype).
      w_hh: ``[E, H, 3H]`` hidden-to-hidden weights.
      b_hh: ``[E, 3H]`` hidden bias.
      h0: ``[E, B, H]`` initial hidden state.
      interpret: run the pallas kernels in interpret mode (CPU testing).

    Returns: ``[E, T, B, H]`` hidden states — f32 for f32 models, bf16 for
    bf16 models (_out_dtype_for: the model casts to its own dtype right
    after the kernel anyway, and f32 storage doubled the largest stream).
    """
    return _fwd_call(proj, w_hh, b_hh, h0, interpret)


def _vjp_fwd(proj, w_hh, b_hh, h0, interpret):
    # Training forward streams h_prev out of the kernel directly — the
    # backward consumes it without the concat(h0, h_all[:-1]) round-trip,
    # and h_all itself is NOT a residual (the recompute needs only
    # h_prev).  h0 rides along for its dtype/shape (tiny next to the
    # [E,T,B,H] stash this replaces).  With STASH_GATES the pre-activation
    # hidden gates ride as a third output so the backward skips its
    # recompute dot.
    outs = _fwd_call(proj, w_hh, b_hh, h0, interpret, emit_prev=True)
    if STASH_GATES:
        h_all, h_prev_all, gates_all = outs
    else:
        (h_all, h_prev_all), gates_all = outs, None
    return h_all, (proj, w_hh, b_hh, h0, h_prev_all, gates_all)


def _vjp_bwd(interpret, res, dout):
    proj, w_hh, b_hh, h0, h_prev_all, gates_all = res
    dproj, dw, db, dh0 = _bwd_call(
        proj, h_prev_all, gates_all, w_hh, b_hh,
        dout.astype(_out_dtype_for(proj.dtype)), interpret
    )
    return (dproj, dw.astype(w_hh.dtype), db.astype(b_hh.dtype),
            dh0.astype(h0.dtype))


gru_recurrence.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# padding helpers (shape hygiene for the kernel's tiling constraints)
# ---------------------------------------------------------------------------


def pad_batch(b: int, dtype=None) -> int:
    """Round the batch up to the sublane granularity of ``dtype``.

    The batch is the second-minor axis of every ``[.., B, 3H/H]`` block:
    f32 tiles need B % 8 == 0, bf16 tiles B % 16 == 0."""
    import jax.numpy as jnp

    gran = 2 * _SUBLANE if dtype == jnp.bfloat16 else _SUBLANE
    return int(np.ceil(b / gran) * gran)


def pad_time(t: int) -> int:
    """Round the time axis up to the kernel's T_BLK granularity.

    ``gru_recurrence`` requires ``T % T_BLK == 0``; callers pad ``proj``
    with zeros at the END of scan order to this length and slice the
    output back to ``t`` (the tail contributes zero gradient — see
    ops/gru.py's pallas path)."""
    return int(np.ceil(t / T_BLK) * T_BLK)


def supported(t: int, h: int) -> bool:
    """Kernel preconditions: lane-aligned hidden size, non-trivial window."""
    return h % 128 == 0 and t >= 1


def block_plan(e: int, t: int, b: int, h: int, dtype=jnp.float32,
               training: bool = True) -> dict:
    """Predict the (e_blk, t_blk) blocking and scoped-VMEM fit at a shape.

    The round-11 window coalescing fattens the kernels' B (row) axis by
    G× — the VMEM footprint model that sizes blocks (_choose_blocks) was
    built at B=32 and is re-validated here at the fatter row counts:
    callers (tests/test_coalesce.py, benchmarks/kernel_tuning.py
    ``--coalesce``) probe the EXACT per-expert byte model the kernel calls
    use (shared _fwd/_bwd_per_expert_bytes) without compiling anything.

    ``dtype`` is the kernel I/O (proj) dtype — bf16 for bf16 models, f32
    otherwise (ops/gru.py ``_kernel_io_dtype``); ``b`` is the PRE-padding
    row count (``pad_batch`` is applied here).  ``training=True`` reports
    the tighter of the forward (emit_prev + gate stash) and backward
    plans, since both kernels run under the custom VJP.

    Returns ``{"e_blk", "t_blk", "per_expert_bytes", "block_bytes",
    "fits", "b_padded", "t_padded", "budget"}`` for the binding kernel.
    """
    io_dtype = jnp.bfloat16 if jnp.dtype(dtype) == jnp.bfloat16 \
        else jnp.float32
    b_pad = pad_batch(b, io_dtype)
    t_pad = pad_time(t)
    g3 = 3 * h
    w_itemsize = jnp.dtype(io_dtype).itemsize
    out_io = jnp.dtype(_out_dtype_for(io_dtype)).itemsize
    plans = []
    fwd_pe = _fwd_per_expert_bytes(
        b_pad, g3, h, io_dtype, stash=training and STASH_GATES,
        n_h_out=2 if training else 1, w_itemsize=w_itemsize, h0_itemsize=4)
    plans.append(("fwd", fwd_pe))
    if training:
        bwd_pe = _bwd_per_expert_bytes(
            b_pad, g3, h, io_dtype, stash=STASH_GATES, hp_io=out_io,
            do_io=out_io, w_itemsize=w_itemsize)
        plans.append(("bwd", bwd_pe))
    worst = None
    import warnings

    for _name, per_expert in plans:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # probe, not a compile site
            e_blk, t_blk = _choose_blocks(e, t_pad, per_expert)
        block_bytes = e_blk * per_expert(t_blk)
        entry = {
            "e_blk": e_blk, "t_blk": t_blk,
            "per_expert_bytes": per_expert(t_blk),
            "block_bytes": block_bytes,
            "fits": block_bytes <= _VMEM_BUDGET,
            "b_padded": b_pad, "t_padded": t_pad, "budget": _VMEM_BUDGET,
        }
        if worst is None or entry["block_bytes"] > worst["block_bytes"]:
            worst = entry
    return worst
