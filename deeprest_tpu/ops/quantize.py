"""Weight quantization for the serving path: int8 / bf16 storage,
f32-reference parity measured and pinned (ROADMAP item 2, round 22).

The serving profile is weight-bandwidth-bound at the flagship shapes
(per-step ``[32,128]x[128,384]`` dots touch every GRU weight byte each
window step at ~12% MXU row occupancy), so shrinking the weight plane is
the raw-speed lever that needs no new kernel: int8 storage moves 4x
fewer bytes through HBM per step, bf16 2x.  This module owns the whole
discipline:

- ``quantize_params(params, mode)`` — per-output-channel symmetric int8
  (a ``QuantTensor`` of int8 data + f32 scales) or bf16 storage for
  every matmul weight leaf (``w_ih``/``w_hh``/``head_w``/``mask_w2``);
  biases, the mask MLP's first layer, and all norm/stat leaves stay f32.
- ``dequantize`` — THE sanctioned dequant site.  int8 values may reach
  float math only through this helper; graftlint's QT001 rule
  (analysis/rules_jax.py) fires on any other int8→float promotion along
  any call chain into ops/ or serve/.  Dequant runs ON DEVICE inside
  the existing jitted executables (the resolve hooks below are called
  from the jitted wrappers), so XLA fuses scale-multiply into the
  consumer and the fused engine's executables stay one-per-rung.
- ``resolve hooks`` — ``ops.gru.resolve_weights`` and
  ``models.qrnn.resolve_params`` both route here, so the scan and
  pallas recurrence paths (and the coalesced/bidirectional variants)
  share this one dequant site.
- parity as a product contract — ``parity_envelope`` measures the
  per-(metric, quantile) max deviation vs the f32 reference on a
  deterministic probe batch at quantize time; ``budget_from_measured``
  pins the stored budget; ``check_envelope`` is the loud gate
  (serve/predictor.py raises on violation at every (re)load).

Quantization itself runs once per (re)load on the host path; only
``dequantize`` is jit-reachable, so everything here uses jnp with
explicit dtypes (the JX006 discipline).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# The serving quant modes (config.InferConfig.quant / cli --quant).
QUANT_MODES = ("off", "int8", "bf16")

# Matmul weight leaves, by the param-name fragments the model fixes
# (models/qrnn.py): the GRU input/recurrent kernels, the quantile head,
# and the feature-mask MLP's second (einsum) layer.  ``mask_w1`` is an
# elementwise gate input, biases are adds — both stay f32.
WEIGHT_FRAGMENTS = ("w_ih", "w_hh", "head_w", "mask_w2")

# Symmetric int8: scales map the per-channel max magnitude to the full
# signed range (127, not 128 — symmetric, no zero-point).
_INT8_MAX = 127.0


class QuantParityError(ValueError):
    """A quantized prediction exceeded its stored parity budget — the
    envelope gate (serve/predictor.py) fails loudly, by contract; the
    checkpoint reloader must never mistake this for a benign mid-write
    checkpoint race."""


class QuantTensor(NamedTuple):
    """One int8-quantized weight matrix: ``data`` int8 ``[..., K, C]``
    with f32 per-output-channel ``scale`` ``[..., 1, C]`` (the reduction
    ran over the contraction axis K, so each output channel dequantizes
    with its own scale).  A NamedTuple, hence a pytree: it threads
    through jit/checkpoint treedefs as two leaves."""

    data: Any
    scale: Any


def is_weight_leaf(name: str) -> bool:
    """Is this param leaf one of the matmul weight matrices the
    quantized path stores narrow?"""
    return any(frag in name for frag in WEIGHT_FRAGMENTS)


def _leaf_name(path) -> str:
    """Last path component's name: DictKey for flax param dicts,
    GetAttrKey for NamedTuple params (ops.gru.GRUParams)."""
    key = path[-1]
    name = getattr(key, "key", None)
    if name is None:
        name = getattr(key, "name", None)
    return name if isinstance(name, str) else ""


def quantize_leaf_int8(w) -> QuantTensor:
    """Per-output-channel symmetric int8 quantization of one weight
    matrix ``[..., K, C]`` (contraction axis second-to-last, matching
    every einsum in models/qrnn.py and ops/gru.py)."""
    w = jnp.asarray(w, jnp.float32)
    if w.ndim < 2:
        raise ValueError(
            f"int8 quantization needs a [.., K, C] matrix, got {w.shape}")
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / jnp.float32(_INT8_MAX)
    q = jnp.clip(jnp.round(w / scale), -_INT8_MAX, _INT8_MAX)
    return QuantTensor(data=q.astype(jnp.int8),
                       scale=scale.astype(jnp.float32))


def dequantize(leaf, dtype=None):
    """THE sanctioned dequant site (QT001): int8 weights re-enter float
    math here and nowhere else.  Runs on device inside the calling
    executable — XLA fuses the widen+scale into the consumer dot.
    Identity on anything that is not a ``QuantTensor`` (f32 leaves and
    the bf16-storage mode, whose leaves are plain bf16 arrays cast at
    use by the model's own compute-dtype cast)."""
    if isinstance(leaf, QuantTensor):
        w = leaf.data.astype(jnp.float32) * leaf.scale
        return w if dtype is None else w.astype(dtype)
    return leaf


def _is_quant_leaf(x) -> bool:
    return isinstance(x, QuantTensor)


def quantize_params(params, mode: str):
    """Quantize every matmul weight leaf of ``params`` (a flax param
    dict or an ops.gru.GRUParams) for serving.

    - ``"off"``  — identity.
    - ``"int8"`` — weight leaves become ``QuantTensor`` (int8 + f32
      per-output-channel scales); everything else unchanged.
    - ``"bf16"`` — weight leaves stored bf16 (plain arrays; the model's
      compute-dtype cast handles them at use); everything else
      unchanged.
    """
    if mode == "off":
        return params
    if mode not in QUANT_MODES:
        raise ValueError(f"quant mode {mode!r} not in {QUANT_MODES}")

    def convert(path, leaf):
        if not is_weight_leaf(_leaf_name(path)):
            return leaf
        if mode == "int8":
            return quantize_leaf_int8(leaf)
        return jnp.asarray(leaf).astype(jnp.bfloat16)

    return jax.tree_util.tree_map_with_path(convert, params)


def dequantize_params(params):
    """Tree-wide dequant-at-use: every ``QuantTensor`` leaf through the
    sanctioned helper, every other leaf untouched.  This IS the
    weights-adapter the jitted serving wrappers call (identity trace
    for unquantized trees), so quantized and f32 predictors share one
    apply path and the executable count stays flat across quant modes."""
    return jax.tree_util.tree_map(dequantize, params,
                                  is_leaf=_is_quant_leaf)


# -- accounting (the bench's bytes gate) ------------------------------------


def weight_bytes(params) -> int:
    """Bytes held by the matmul weight leaves (scales included for
    QuantTensors — the honest number: the scale plane ships with the
    weights on every tenant swap)."""
    total = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=_is_quant_leaf)
    for path, leaf in flat:
        if isinstance(leaf, QuantTensor):
            total += leaf.data.size * leaf.data.dtype.itemsize
            total += leaf.scale.size * leaf.scale.dtype.itemsize
        elif is_weight_leaf(_leaf_name(path)):
            total += (int(np.prod(leaf.shape))
                      * np.dtype(leaf.dtype).itemsize)
    return total


# -- the parity envelope (measured, stored, enforced) -----------------------

# Probe geometry: deterministic, seeded, and small — one batch is enough
# because the envelope is a BUDGET (measured x margin), not a proof; the
# margin absorbs input-distribution slack and the reload-time re-measure
# keeps the stored budget honest across code changes.
PROBE_BATCH = 4
PROBE_SEED = 0
ENVELOPE_MARGIN = 2.0
ENVELOPE_FLOOR = 1e-6


def probe_batch(window_size: int, feature_dim: int,
                batch: int = PROBE_BATCH) -> np.ndarray:
    """The deterministic parity probe: uniform [0,1) windows (the
    normalized-feature range the model serves)."""
    rng = np.random.default_rng(PROBE_SEED)
    return rng.random((batch, window_size, feature_dim)).astype(np.float32)


def parity_envelope(ref_out, quant_out, metric_names,
                    quantiles) -> dict[str, float]:
    """Per-(metric, quantile) max |quantized - f32| over the probe,
    keyed ``"<metric>|q<quantile>"`` — model outputs are ``[B,T,E,Q]``
    (models/qrnn.py), reduced over batch and time."""
    ref = np.asarray(ref_out, np.float32)
    got = np.asarray(quant_out, np.float32)
    per = np.abs(got - ref).max(axis=(0, 1))              # [E, Q]
    return {
        f"{m}|q{q:g}": float(per[i, j])  # graftlint: disable=JX003 -- per is already a HOST np array (the one device→host readback happened at the np.asarray above); this loop indexes host memory once per (metric, quantile) cell at quantize time, not per serving request
        for i, m in enumerate(metric_names)
        for j, q in enumerate(quantiles)
    }


def budget_from_measured(measured: dict[str, float],
                         margin: float = ENVELOPE_MARGIN,
                         floor: float = ENVELOPE_FLOOR) -> dict[str, float]:
    """The stored budget: measured x margin with an absolute floor (a
    dead-zero measured cell must not pin an unmeetable 0.0 budget)."""
    return {k: max(v * margin, floor) for k, v in measured.items()}


def check_envelope(measured: dict[str, float],
                   budget: dict[str, float]) -> list[str]:
    """Violations of the stored budget — the loud-gate input.  A cell
    missing from the budget is a violation too (a quant mode must never
    silently serve metrics its envelope never covered)."""
    out = []
    for key, val in measured.items():
        cap = budget.get(key)
        if cap is None:
            out.append(f"{key}: no stored budget for this cell")
        elif val > cap:
            out.append(f"{key}: measured {val:.3e} > budget {cap:.3e}")
    return out
