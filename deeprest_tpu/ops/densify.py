"""On-device densification of padded-COO traffic rows.

The 10k-endpoint regime (ROADMAP item 4, PAPERS [1]) makes the per-window
call-path count vector >99% zeros: any one window touches a handful of
call paths out of F=10240 columns.  The sparse-first pipeline therefore
carries traffic as padded-COO rows — ``(cols[..., K], vals[..., K])`` with
``K = nnz_cap`` real entries padded by ``(0, 0.0)`` — from featurization
(``CallPathSpace.extract_sparse``) through the ring corpus
(``SparseSeriesRing``) and the host→device feed, and densifies to the
model's static ``[..., F]`` inside the existing jit boundaries via the
scatter-add here.  Host→device bytes drop ~F/(2K) (cols int32 + vals
float32 vs dense float32): ~80× at F=10240, K=64.

Numerics contract (pinned by tests/test_sparse.py):

- ``densify_coo`` is BIT-EXACT vs the dense reference
  (``np.bincount``-built vectors): real columns within a row are unique
  (``extract_sparse`` goes through ``np.unique``; ``sparsify_rows``
  through ``np.flatnonzero``), so every output element receives exactly
  one real contribution, and the ``(0, 0.0)`` padding contributes exact
  float zeros (x + 0.0 == x for the non-negative count values carried
  here).  Scatter order therefore cannot re-associate anything.
- ``normalize_minmax`` mirrors ``MinMaxStats.apply`` exactly (including
  the degenerate-range passthrough); stats must enter the jit as runtime
  ARGUMENTS, never baked constants — a constant range lets XLA
  strength-reduce the divide into a multiply-by-reciprocal, which breaks
  bit parity with the host path (the serve/fused.py lesson).
"""

from __future__ import annotations

import numpy as np

try:  # host-only callers (benchmarks, lint) may lack an initialized backend
    import flax.struct
    import jax.numpy as jnp

    _HAVE_JAX = True
except Exception:  # pragma: no cover - jax is a hard dep of the repo
    _HAVE_JAX = False


DEFAULT_NNZ_CAP = 64


if _HAVE_JAX:

    @flax.struct.dataclass
    class SparseBase:
        """Device-staged padded-COO base series plus its normalization.

        The sparse twin of the staged dense ``x_base``: ``cols``/``vals``
        are ``[T, K]`` RAW (un-normalized) traffic rows resident in HBM;
        the train/eval steps gather windows by start index, densify via
        :func:`densify_coo`, and normalize on device with the staged
        ``mn``/``rg`` runtime arguments.  ``capacity`` is the static
        dense width — a Python int excluded from the pytree so jit
        treats it as a compile-time constant.
        """

        cols: object                 # [T, K] int32 device array
        vals: object                 # [T, K] float32 device array
        mn: object                   # broadcastable x_stats.min
        rg: object                   # broadcastable x_stats.range
        capacity: int = flax.struct.field(pytree_node=False, default=0)

    def densify_coo(cols, vals, capacity: int):
        """``(cols[..., K], vals[..., K])`` padded-COO → ``[..., capacity]``.

        One scatter-add per call, batched over every leading axis; see the
        module docstring for why this is bit-exact vs the dense reference.
        """
        k = cols.shape[-1]
        flat_c = cols.reshape(-1, k)
        flat_v = vals.reshape(-1, k)
        b = flat_c.shape[0]
        idx = (jnp.arange(b, dtype=jnp.int32)[:, None] * capacity
               + flat_c).reshape(-1)
        out = jnp.zeros((b * capacity,), flat_v.dtype)
        out = out.at[idx].add(flat_v.reshape(-1))
        return out.reshape(*cols.shape[:-1], capacity)

    def normalize_minmax(x, mn, rg):
        """The exact device mirror of ``MinMaxStats.apply`` (degenerate
        ranges pass through raw)."""
        return jnp.where(rg == 0.0, x,
                         (x - mn) / jnp.where(rg == 0.0, 1.0, rg))

    def gather_densify_normalize(base: "SparseBase", idx):
        """Window gather + densify + normalize for a staged sparse base:
        ``idx [..., W]`` start-expanded row indices → normalized dense
        ``[..., W, capacity]`` windows, all inside the caller's jit."""
        x = densify_coo(base.cols[idx], base.vals[idx], base.capacity)
        return normalize_minmax(x, base.mn, base.rg)


# -- host twins (numpy; shared by ETL, parity tests, and fallbacks) --------


def densify_rows(cols: np.ndarray, vals: np.ndarray, capacity: int,
                 out: np.ndarray | None = None) -> np.ndarray:
    """Host-side dense reconstruction of padded-COO rows — the parity
    reference for :func:`densify_coo` and the serve-side fallback when no
    sparse device path is available.  ``cols``/``vals`` are ``[..., K]``;
    returns float32 ``[..., capacity]``."""
    cols = np.asarray(cols)
    vals = np.asarray(vals, np.float32)
    shape = (*cols.shape[:-1], capacity)
    if out is None:
        # graftlint: disable=DN002 -- the sanctioned host densify: the ONE dense [..., F] window per sweep/parity call is built HERE so the hot zones never allocate it themselves
        out = np.zeros(shape, np.float32)
    else:
        if out.shape != shape:
            raise ValueError(f"out shape {out.shape} != {shape}")
        out[:] = 0.0
    if cols.size == 0:          # K=0 rows (e.g. an empty bucket): all zeros
        return out
    flat_o = out.reshape(-1, capacity)
    flat_c = cols.reshape(-1, cols.shape[-1])
    flat_v = vals.reshape(-1, vals.shape[-1])
    # np.add.at handles the (0, 0.0) padding exactly like the device
    # scatter: a zero add is a no-op on the non-negative counts here.
    rows = np.repeat(np.arange(flat_c.shape[0]), flat_c.shape[1])
    np.add.at(flat_o, (rows, flat_c.reshape(-1)), flat_v.reshape(-1))
    return out


def sparsify_rows(dense: np.ndarray, nnz_cap: int
                  ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense ``[..., F]`` rows → padded-COO ``(cols, vals, nnz)``.

    The inverse of :func:`densify_rows` for rows whose nonzero count fits
    ``nnz_cap`` — rows that don't RAISE loudly (the documented K-cap
    policy; size ``--sparse-nnz-cap`` to the corpus, never silently drop
    traffic).  Round-trip is bit-exact: the nonzero values are copied,
    not recomputed.
    """
    dense = np.asarray(dense)
    flat = dense.reshape(-1, dense.shape[-1])
    n = flat.shape[0]
    cols = np.zeros((n, nnz_cap), np.int32)
    vals = np.zeros((n, nnz_cap), np.float32)
    nnz = np.zeros((n,), np.int32)
    for i in range(n):
        nz = np.flatnonzero(flat[i])
        if len(nz) > nnz_cap:
            raise ValueError(
                f"row {i} has {len(nz)} nonzero traffic columns, over the "
                f"sparse nnz cap {nnz_cap}; raise --sparse-nnz-cap (or "
                f"disable --sparse-feed) — silently dropping call paths "
                f"would corrupt the count vector")
        cols[i, :len(nz)] = nz
        vals[i, :len(nz)] = flat[i, nz]
        nnz[i] = len(nz)
    return (cols.reshape(*dense.shape[:-1], nnz_cap),
            vals.reshape(*dense.shape[:-1], nnz_cap),
            nnz.reshape(dense.shape[:-1]))


def sparse_minmax(cols: np.ndarray, vals: np.ndarray, nnz: np.ndarray,
                  span: int, capacity: int):
    """Per-column min/max over the first ``span`` padded-COO rows,
    BIT-IDENTICAL to ``minmax_fit`` over the equivalent dense rows.

    A column absent from any row in the span has a dense 0.0 there, so
    its min folds 0 in; a column present in EVERY row never sees an
    implicit zero.  Presence is decided by the ``nnz`` row lengths (never
    by ``val != 0`` heuristics), so padding at column 0 cannot pollute
    column 0's statistics.  Returns a ``MinMaxStats`` with the stream's
    per-feature ``[1, F]`` broadcast shape.
    """
    from deeprest_tpu.data.windows import MinMaxStats

    c = np.asarray(cols[:span])
    v = np.asarray(vals[:span], np.float32)
    n = np.asarray(nnz[:span])
    mask = np.arange(c.shape[1])[None, :] < n[:, None]
    cm = c[mask]
    vm = v[mask]
    mx = np.full((capacity,), -np.inf, np.float32)
    mn = np.full((capacity,), np.inf, np.float32)
    np.maximum.at(mx, cm, vm)
    np.minimum.at(mn, cm, vm)
    cnt = np.zeros((capacity,), np.int64)
    np.add.at(cnt, cm, 1)
    everywhere = cnt == span
    mx = np.where(everywhere, mx, np.maximum(mx, np.float32(0.0)))
    mn = np.where(everywhere, mn, np.minimum(mn, np.float32(0.0)))
    return MinMaxStats(min=mn[None, :].astype(np.float32),
                       max=mx[None, :].astype(np.float32))


__all__ = [
    "DEFAULT_NNZ_CAP",
    "densify_rows",
    "sparsify_rows",
    "sparse_minmax",
]
if _HAVE_JAX:
    __all__ += ["SparseBase", "densify_coo", "normalize_minmax",
                "gather_densify_normalize"]
