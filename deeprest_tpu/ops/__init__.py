"""TPU compute primitives: scan-based GRU, quantile (pinball) loss."""

from deeprest_tpu.ops.gru import GRUParams, gru, bidirectional_gru, init_gru_params
from deeprest_tpu.ops.quantile import pinball_loss

__all__ = [
    "GRUParams",
    "gru",
    "bidirectional_gru",
    "init_gru_params",
    "pinball_loss",
]
