"""TPU compute primitives: scan-based GRU, quantile (pinball) loss."""

from deeprest_tpu.ops.gru import (
    GroupSpec,
    GRUParams,
    bidirectional_gru,
    bidirectional_gru_coalesced,
    gru,
    gru_coalesced,
    init_gru_params,
)
from deeprest_tpu.ops.quantile import pinball_loss

__all__ = [
    "GroupSpec",
    "GRUParams",
    "gru",
    "gru_coalesced",
    "bidirectional_gru",
    "bidirectional_gru_coalesced",
    "init_gru_params",
    "pinball_loss",
]
