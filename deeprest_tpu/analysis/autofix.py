"""``deeprest lint --fix``: safe mechanical rewrites for HY001/HY002.

Only the two hygiene rules are fixable — their fixes are provably
behavior-preserving (deleting a never-used import binding, deleting
statements no control flow can reach).  Everything else graftlint flags
is a *design* violation whose fix needs a human (or stays as a reasoned
suppression).

Contract (pinned by tests/test_analysis.py):

- fix → re-lint reports zero HY001/HY002 → a second fix pass is a
  byte-identical no-op (idempotency);
- suppressed findings are REFUSED, never rewritten — an in-code
  ``graftlint: disable=HY001 -- reason`` documents a deliberate
  deviation and the fixer must not undo a documented decision;
- a rewrite that would leave a file unparsable is aborted for that
  file (original bytes kept) and reported, never written.

Mechanics: fixes are computed from the same predicates the rules run
(rules_hygiene.unused_import_bindings / unreachable_tails — one
predicate, two consumers), applied as whole-line edits bottom-up so
line numbers stay valid, and the pass loops until stable because one
fix can expose another (deleting unreachable code can orphan the
import it was the only user of).
"""

from __future__ import annotations

import ast
import dataclasses
import os

from deeprest_tpu.analysis.core import Finding, SourceFile
from deeprest_tpu.analysis.rules_hygiene import (
    unreachable_tails, unused_import_bindings,
)

_MAX_PASSES = 10


@dataclasses.dataclass(frozen=True)
class FixEdit:
    """One applied (or refused) rewrite."""

    path: str
    rule: str
    line: int
    action: str        # "deleted import", "trimmed import", ...


@dataclasses.dataclass
class FixReport:
    applied: list[FixEdit] = dataclasses.field(default_factory=list)
    refused: list[FixEdit] = dataclasses.field(default_factory=list)
    passes: int = 0

    def summary(self) -> str:
        lines = [f"{e.path}:{e.line}: fixed {e.rule} ({e.action})"
                 for e in self.applied]
        lines += [f"{e.path}:{e.line}: REFUSED {e.rule} ({e.action})"
                  for e in self.refused]
        lines.append(f"{len(self.applied)} fix(es) applied, "
                     f"{len(self.refused)} refused, "
                     f"{self.passes} pass(es)")
        return "\n".join(lines)


# -- per-file fix computation ----------------------------------------------


@dataclasses.dataclass
class _LineEdit:
    """Replace lines [start, end] (1-based, inclusive) with ``repl``
    (a list of replacement lines; empty list = pure deletion)."""

    start: int
    end: int
    repl: list[str]
    rule: str
    action: str


def _stmt_lines_exclusive(sf: SourceFile, node: ast.stmt) -> bool:
    """True when ``node``'s source lines are not shared with any OTHER
    statement (the semicolon guard: rewriting shared lines would eat
    the neighbor).  Enclosing blocks necessarily span the node's lines
    and don't count; an import has no statement descendants, so every
    other overlapping statement is a genuine line-sharer."""
    lo, hi = node.lineno, node.end_lineno or node.lineno
    ancestors = set(map(id, sf.ancestors(node)))
    for other in ast.walk(sf.tree):
        if other is node or not isinstance(other, ast.stmt):
            continue
        if id(other) in ancestors:
            continue
        o_lo = getattr(other, "lineno", None)
        if o_lo is None:
            continue
        o_hi = other.end_lineno or o_lo
        if o_lo <= hi and o_hi >= lo:
            return False
    return True


def _parent_block(sf: SourceFile, node: ast.stmt) -> list[ast.stmt]:
    parent = sf.parents().get(node)
    for field in ("body", "orelse", "finalbody"):
        block = getattr(parent, field, None)
        if isinstance(block, list) and node in block:
            return block
    for h in getattr(parent, "handlers", None) or []:
        if node in h.body:
            return h.body
    return []


def _indent_of(sf: SourceFile, node: ast.stmt) -> str:
    text = sf.lines[node.lineno - 1]
    return text[:len(text) - len(text.lstrip())]


def _render_import(node: ast.stmt, keep: list[ast.alias],
                   indent: str) -> list[str]:
    def one(a: ast.alias) -> str:
        return a.name + (f" as {a.asname}" if a.asname else "")

    if isinstance(node, ast.Import):
        line = indent + "import " + ", ".join(one(a) for a in keep)
        if len(line) <= 79:
            return [line]
        return [indent + "import " + one(a) for a in keep]
    mod = "." * node.level + (node.module or "")
    line = indent + f"from {mod} import " + ", ".join(one(a) for a in keep)
    if len(line) <= 79:
        return [line]
    out = [indent + f"from {mod} import ("]
    out += [indent + "    " + one(a) + "," for a in keep]
    out.append(indent + ")")
    return out


def _import_edits(sf: SourceFile, report: FixReport) -> list[_LineEdit]:
    unused = unused_import_bindings(sf)
    if not unused:
        return []
    by_stmt: dict[int, list[str]] = {}
    node_of: dict[int, ast.stmt] = {}
    for bound, node, _original in unused:
        by_stmt.setdefault(id(node), []).append(bound)
        node_of[id(node)] = node
    edits: list[_LineEdit] = []
    for nid, bounds in by_stmt.items():
        node = node_of[nid]
        probe = Finding(sf.rel, node.lineno, node.col_offset, "HY001", "")
        if sf.suppressed(probe):
            report.refused.append(FixEdit(
                sf.rel, "HY001", node.lineno,
                "suppressed in code — a documented deviation"))
            continue
        if not _stmt_lines_exclusive(sf, node):
            report.refused.append(FixEdit(
                sf.rel, "HY001", node.lineno,
                "import shares source lines with another statement"))
            continue
        gone = set(bounds)

        def alias_bound(a: ast.alias) -> str:
            if isinstance(node, ast.Import):
                return a.asname or a.name.split(".")[0]
            return a.asname or a.name
        keep = [a for a in node.names if alias_bound(a) not in gone]
        end = node.end_lineno or node.lineno
        if keep:
            edits.append(_LineEdit(
                node.lineno, end,
                _render_import(node, keep, _indent_of(sf, node)),
                "HY001", f"trimmed import ({', '.join(sorted(gone))})"))
        else:
            block = _parent_block(sf, node)
            # deleting a block's only statement must leave `pass`, not
            # an unparsable empty body
            repl = ([_indent_of(sf, node) + "pass"]
                    if len(block) == 1 else [])
            edits.append(_LineEdit(
                node.lineno, end, repl, "HY001",
                f"deleted import ({', '.join(sorted(gone))})"))
    return edits


def _unreachable_edits(sf: SourceFile,
                       report: FixReport) -> list[_LineEdit]:
    edits: list[_LineEdit] = []
    for prev, first, tail in unreachable_tails(sf):
        probe = Finding(sf.rel, first.lineno, first.col_offset,
                        "HY002", "")
        if sf.suppressed(probe):
            report.refused.append(FixEdit(
                sf.rel, "HY002", first.lineno,
                "suppressed in code — a documented deviation"))
            continue
        prev_end = prev.end_lineno or prev.lineno
        if prev_end >= first.lineno:
            report.refused.append(FixEdit(
                sf.rel, "HY002", first.lineno,
                "unreachable code shares a line with its terminator"))
            continue
        last = tail[-1]
        edits.append(_LineEdit(
            first.lineno, last.end_lineno or last.lineno, [],
            "HY002",
            f"deleted {len(tail)} unreachable statement(s) after "
            f"{type(prev).__name__.lower()}"))
    return edits


def _apply_edits(source: str, edits: list[_LineEdit]) -> str | None:
    """Apply non-overlapping whole-line edits bottom-up; overlapping
    edits are dropped (the next fix pass reconsiders them)."""
    lines = source.splitlines(keepends=True)
    taken: list[tuple[int, int]] = []
    for e in sorted(edits, key=lambda e: e.start, reverse=True):
        if any(e.start <= hi and e.end >= lo for lo, hi in taken):
            continue
        taken.append((e.start, e.end))
        repl = [r + "\n" for r in e.repl]
        lines[e.start - 1:e.end] = repl
    return "".join(lines)


def fix_file(path: str, rel: str, report: FixReport) -> bool:
    """One fix pass over one on-disk file; True when bytes changed."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    sf = SourceFile(rel, source)
    if sf.tree is None:
        return False
    edits = _import_edits(sf, report) + _unreachable_edits(sf, report)
    if not edits:
        return False
    fixed = _apply_edits(source, edits)
    if fixed is None or fixed == source:
        return False
    try:
        ast.parse(fixed)
    except SyntaxError:
        report.refused.append(FixEdit(
            rel, edits[0].rule, edits[0].start,
            "rewrite would not parse — file left untouched"))
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(fixed)
    for e in edits:
        report.applied.append(FixEdit(rel, e.rule, e.start, e.action))
    return True


def fix_paths(paths) -> FixReport:
    """Fix HY001/HY002 across directories/files, looping until stable
    (one fix can expose another: unreachable code may be the only user
    of an import).  Bounded by ``_MAX_PASSES``."""
    from deeprest_tpu.analysis.core import collect_py_files

    report = FixReport()
    for _ in range(_MAX_PASSES):
        report.passes += 1
        # refusal sites re-announce identically every pass — keep only
        # the current pass's so the report lists each site once
        report.refused = []
        changed = False
        for rel, full in collect_py_files(paths):
            if not os.path.isfile(full):
                continue
            changed |= fix_file(full, rel, report)
        if not changed:
            break
    return report
